"""The coordination controller — global agreement on which named tensors are
ready everywhere, every cycle.

Role of the reference's ``horovod/common/controller.cc:97-525``
(``ComputeResponseList``) with the rank-0 coordinator protocol documented at
``controller.h:68-103``:

  1. every rank drains its TensorQueue into a RequestList;
  2. workers send their lists to rank 0 (the coordinator); rank 0 tallies
     per-tensor readiness in a MessageTable (``IncrementTensorCount``,
     ``controller.cc:1030-1053``);
  3. when a tensor has been requested by every (non-joined) rank, the
     coordinator validates cross-rank consistency and builds a Response
     (``ConstructResponse``, ``controller.cc:547-824``);
  4. completed responses are fused under the fusion threshold
     (``FuseResponses``, ``controller.cc:859-998``) and broadcast back;
  5. every rank executes the ResponseList in identical order.

The reference implements step 2/4 with MPI gather/bcast or gloo
allgatherv/broadcast (tree-structured inside those libraries); ours run
over the self-contained ``TcpMesh`` with a choice of fan-out
(``HOROVOD_CONTROLLER_TOPOLOGY=star|tree|auto``): the star does a
sequential recv/send loop at rank 0 (lowest latency at small P), the
binomial tree relays gather bundles / response broadcasts through
O(log P) levels (rank-0 cost stops growing linearly with P).  ``auto``
switches at ``TREE_TOPOLOGY_THRESHOLD``, set by
``benchmarks/controller_bench.py`` measurement.

Also here: Join bookkeeping (zero-substitution for finished ranks) and the
stall inspector hook.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..common import faults
from ..common.logging_util import get_logger
from . import flight_recorder
from ..common.topology import ProcessTopology
from ..transport.tcp import TcpMesh
from . import metrics
from .messages import (
    DataType,
    HostMaskFrame,
    MaskFrame,
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
    is_host_mask_frame,
    is_mask_frame,
)

log = get_logger("horovod_tpu.controller")

JOIN_TENSOR_NAME = "__join__"
BARRIER_TENSOR_NAME = "__barrier__"

#: World size at which ``HOROVOD_CONTROLLER_TOPOLOGY=auto`` switches from
#: the star to the binomial tree.  Set by measurement
#: (``benchmarks/controller_bench.py``): the star's O(P) serial recv/send
#: at the coordinator crosses the tree's O(log P) depth around this size
#: for control-plane-sized messages.
TREE_TOPOLOGY_THRESHOLD = 64


def tree_parent(rank: int) -> int:
    """Binomial-tree parent rooted at 0: clear the lowest set bit
    (the role MPI's internal gather/bcast trees play for the reference,
    ``mpi_controller.cc:108-162``)."""
    return rank & (rank - 1)


def tree_children(rank: int, size: int) -> List[int]:
    """Binomial-tree children of ``rank`` in a ``size``-rank job: rank+2^k
    for every power of two below rank's lowest set bit (all powers for
    the root), capped by size."""
    low = (rank & -rank) if rank else size
    children, bit = [], 1
    while bit < low and rank + bit < size:
        children.append(rank + bit)
        bit <<= 1
    return children


def _encode_bundle(entries: List[tuple]) -> bytes:
    """[(rank, payload)] → wire bytes for the up-tree gather."""
    parts = [len(entries).to_bytes(4, "little")]
    for rank, payload in entries:
        parts.append(rank.to_bytes(4, "little"))
        parts.append(len(payload).to_bytes(4, "little"))
        parts.append(payload)
    return b"".join(parts)


def _decode_bundle(data: bytes) -> List[tuple]:
    n = int.from_bytes(data[:4], "little")
    entries, off = [], 4
    for _ in range(n):
        rank = int.from_bytes(data[off:off + 4], "little")
        ln = int.from_bytes(data[off + 4:off + 8], "little")
        entries.append((rank, data[off + 8:off + 8 + ln]))
        off += 8 + ln
    return entries


@dataclass
class _TableEntry:
    requests: List[Request] = field(default_factory=list)
    ranks: Set[int] = field(default_factory=set)
    first_seen: float = field(default_factory=time.monotonic)
    # When the MEDIAN announcer became ready (the instant half the active
    # ranks had tallied): the straggler detector measures the remaining
    # ranks' lag from here, not from first_seen, so one early rank cannot
    # smear everyone else as "behind".
    majority_seen: Optional[float] = None


class DemotionPolicy:
    """Chronic-straggler verdict state machine (pure; no I/O, no clocks).

    Promotes the per-cycle straggler *flag* to a demotion *verdict*: a
    rank whose readiness-lag EWMA stays above ``demote_secs`` for
    ``demote_cycles`` consecutive busy cycles is chronically slow and
    worth shedding.  Three safety properties are built in:

    - **Hysteresis window**: one streak counter per rank, reset the
      moment its EWMA dips back under the threshold — a transient stall
      can never accumulate a verdict across gaps.
    - **Whole-world-slow guard**: when half or more of the active ranks
      are over threshold, the mesh is globally stalled (GC pause, shared
      NFS hiccup, coordinator overload) and *nobody* is demoted; all
      streaks reset so the stall doesn't seed later verdicts.  At
      np <= 2 one slow rank IS half the world, so demotion needs at
      least 3 active ranks — by construction, not by special case.
    - **One demotion per epoch**: a misconfigured threshold demotes at
      most one host before the epoch advances and the world is
      re-evaluated; it cannot cascade the fleet to zero.

    Fed by ``Controller._update_stragglers`` on busy cycles only (idle
    cycles stamp no majorities, so "consecutive cycles" means cycles
    that actually measured lag).  ``docs/elastic.md`` has the diagram.
    """

    def __init__(self, demote_secs: float, demote_cycles: int):
        if demote_cycles < 1:
            raise ValueError(
                f"HOROVOD_STRAGGLER_DEMOTE_CYCLES={demote_cycles!r}: "
                "expected >= 1")
        self.demote_secs = demote_secs
        self.demote_cycles = demote_cycles
        self._streak: Dict[int, int] = {}
        self._demoted_epochs: Set[int] = set()

    @property
    def enabled(self) -> bool:
        return self.demote_secs > 0.0

    def observe(self, epoch: int, ewma: Dict[int, float],
                active: Set[int]) -> Optional[int]:
        """One busy cycle's EWMA snapshot → the rank to demote, or None.

        Marks the epoch demoted when it returns a victim; callers own
        delivering the verdict (the coordinator posts it to the driver).
        """
        if not self.enabled or not active:
            return None
        over = {r for r in active if ewma.get(r, 0.0) > self.demote_secs}
        if not over or 2 * len(over) >= len(active):
            # Nothing chronic, or the whole world is slow — either way no
            # rank is individually at fault this cycle.
            self._streak.clear()
            return None
        for r in [r for r in self._streak if r not in over]:
            del self._streak[r]
        for r in over:
            self._streak[r] = self._streak.get(r, 0) + 1
        if epoch in self._demoted_epochs:
            return None
        chronic = [r for r in over if self._streak[r] >= self.demote_cycles]
        if not chronic:
            return None
        victim = max(chronic, key=lambda r: ewma.get(r, 0.0))
        self._demoted_epochs.add(epoch)
        self._streak.pop(victim, None)
        return victim


class Controller:
    def __init__(self, topology: ProcessTopology, mesh: Optional[TcpMesh],
                 fusion_threshold_bytes: int = 64 * 1024 * 1024,
                 stall_warning_secs: float = 60.0,
                 stall_shutdown_secs: float = 0.0,
                 cache_capacity: int = 1024,
                 parameter_manager=None):
        from .response_cache import CoordinatorCache, WorkerCacheMirror

        self.topo = topology
        self.mesh = mesh
        self.fusion_threshold = fusion_threshold_bytes
        self.stall_warning_secs = stall_warning_secs
        self.stall_shutdown_secs = stall_shutdown_secs
        self._message_table: Dict[str, _TableEntry] = {}
        self._joined_ranks: Set[int] = set()
        self._last_stall_check = time.monotonic()
        self.timeline = None  # coordinator-side negotiation lanes
        self.param_manager = parameter_manager
        # Cache fast path (response_cache.py): coordinator owns assignments,
        # workers mirror keys; disabled when capacity <= 0.
        self.cache_enabled = cache_capacity > 0 and topology.size > 1
        self._cache = CoordinatorCache(cache_capacity) \
            if self.cache_enabled and topology.rank == 0 else None
        self._mirror = WorkerCacheMirror() \
            if self.cache_enabled and topology.rank != 0 else None
        self._cycle_assignments: List[tuple] = []
        self._cycle_evictions: List[int] = []
        self.cache_hit_count = 0
        self.cache_miss_count = 0
        # Fast-path accounting (tests + benchmarks assert against these):
        # fast_cycle_count counts mask-only cycles that COMPLETED at least
        # one tensor (idle polling cycles also ride the compact frames but
        # would swamp the metric, so they count separately), and
        # serialized_request_count is the number of Requests this rank
        # ever put on / took off the wire.
        self.fast_cycle_count = 0
        self.idle_fast_cycle_count = 0
        self.mask_only_sent_count = 0
        self.serialized_request_count = 0
        # Mask fast path (coordinator): per-rank pending cache-bit masks,
        # aggregated with big-int AND/OR — O(ranks) C-speed work per cycle
        # instead of O(ranks × tensors) Python (reference bitvector
        # allreduce role, ``mpi_controller.cc:88-106``).
        self._pending_masks: Dict[int, int] = {}
        self._mask_bit_since: Dict[int, float] = {}
        # When each leftover bit reached majority announcement (the mask
        # path's majority_seen analog); keyed like _mask_bit_since.
        self._mask_bit_majority: Dict[int, float] = {}
        # Tensors completed by a stall-time bit→table conversion (after this
        # cycle's responses were already built); delivered next cycle.
        self._stall_completed: List[str] = []
        # Negotiation fan-out topology: the star does O(P) serial
        # recv/send at rank 0; the binomial tree spreads that over
        # O(log P) levels (every rank relays its subtree's bundles).
        # "auto" picks by world size at the measured crossover.
        from ..common import env as env_mod

        topo_env = env_mod.get_str(
            env_mod.HOROVOD_CONTROLLER_TOPOLOGY, "auto").strip().lower()
        if topo_env not in ("auto", "star", "tree"):
            raise ValueError(
                f"HOROVOD_CONTROLLER_TOPOLOGY={topo_env!r}: expected "
                "auto|star|tree")
        if topo_env == "auto":
            topo_env = "tree" if topology.size >= TREE_TOPOLOGY_THRESHOLD \
                else "star"
        # A 2-rank tree degenerates to the star exactly.
        self.fanout_topology = "star" if topology.size <= 2 else topo_env
        # Fusion ordering: "arrival" emits responses in the order tensors
        # *complete* within the cycle (biased by the coordinator's rank scan
        # order); "readiness" (default) sorts the cycle's completed set by
        # each tensor's first_seen timestamp, so the tensors that have been
        # negotiating longest — the ones downstream ranks are most likely
        # already blocked on — pack into the front fusion buckets.  Only the
        # coordinator sorts (it alone decides order, workers replay the
        # ResponseList), so determinism is preserved.
        order = env_mod.get_str(
            env_mod.HOROVOD_FUSION_ORDER, "readiness").strip().lower()
        if order not in ("readiness", "arrival"):
            raise ValueError(
                f"HOROVOD_FUSION_ORDER={order!r}: expected readiness|arrival")
        self.fusion_order = order
        # Online straggler detection (coordinator-side, single-threaded —
        # all state below is touched only from the coordinator's own cycle
        # path, so the hot path gains no locks).  Per-rank EWMAs of how
        # long each rank keeps tensors waiting past the median announcer;
        # crossing the threshold flags the rank (metrics + flight-recorder
        # event + log line).  docs/observability.md#straggler-detection.
        self.straggler_threshold = env_mod.get_float(
            env_mod.HOROVOD_STRAGGLER_THRESHOLD_SECS,
            env_mod.DEFAULT_STRAGGLER_THRESHOLD_SECS)
        alpha = env_mod.get_float(env_mod.HOROVOD_STRAGGLER_EWMA_ALPHA,
                                  env_mod.DEFAULT_STRAGGLER_EWMA_ALPHA)
        if not 0.0 < alpha <= 1.0:
            raise ValueError(
                f"HOROVOD_STRAGGLER_EWMA_ALPHA={alpha!r}: expected (0, 1]")
        self.straggler_alpha = alpha
        self._straggler_ewma: Dict[int, float] = {}
        self._straggler_suspects: Set[int] = set()
        # False while every EWMA sits at zero and nothing lags: the
        # per-cycle update early-outs to two dict checks in steady state.
        self._straggler_decaying = False
        # A fresh controller is a fresh world (elastic epoch restart in
        # the same process): the process-global suspect gauge must not
        # keep naming a suspect from the previous world's EWMA map.
        # Only a stale non-cleared gauge is reset — a clean start leaves
        # the registry untouched (steady state stays metrics-silent).
        if topology.rank == 0 and metrics.registry.get_gauge(
                "straggler_suspect") not in (None, -1):
            self._set_suspect_gauge()
        # Chronic-straggler demotion (docs/elastic.md "self-healing
        # demotion"): verdict state machine fed by the EWMAs above;
        # disabled unless HOROVOD_STRAGGLER_DEMOTE_SECS > 0.
        self.demotion = DemotionPolicy(
            env_mod.get_float(env_mod.HOROVOD_STRAGGLER_DEMOTE_SECS,
                              env_mod.DEFAULT_STRAGGLER_DEMOTE_SECS),
            env_mod.get_int(env_mod.HOROVOD_STRAGGLER_DEMOTE_CYCLES,
                            env_mod.DEFAULT_STRAGGLER_DEMOTE_CYCLES))
        # Tallies parked by a ``controller.tally`` delay_ms injection:
        # (maturity monotonic time, Request), replayed by
        # _mature_deferred_tallies once mature — the injected slowness
        # lands on one rank's tallies while the cycle keeps turning.
        self._deferred_tallies: List[Tuple[float, Request]] = []
        # Tree negotiation fan-in (core/negotiation_fanin.py): installed
        # per epoch by state._sync_controller_topology via
        # configure_fanin; while a plan is active it supersedes
        # fanout_topology — the wire shape is plan-defined end to end.
        self.fanin_plan = None
        self.fanin_heartbeat = None
        # Fast-path counters (exposed through state's controller metrics
        # view, like the cycle counters above — the ~1 ms negotiation
        # hot path never touches the metrics registry): coordinator
        # ingress frames/bytes per gather (every fan-out shape counts
        # them, so star-vs-fanin comparisons read the same series), the
        # per-rank upward-frame split by path, and stale-aggregator
        # convictions.
        self.ingress_frame_count = 0
        self.ingress_byte_count = 0
        self.fanin_tree_frame_count = 0
        self.fanin_direct_frame_count = 0
        self.fanin_fallback_count = 0
        # Lockstep cycle index: every rank increments once per
        # compute_response_list, so it is consistent across ranks without
        # a wire field — the FANIN_RELAY span's cycle tag rides it.
        self.cycle_index = 0

    # ------------------------------------------------------------------
    # the per-cycle negotiation round
    # ------------------------------------------------------------------

    def compute_response_list(self, requests: List[Request],
                              should_shutdown: bool = False) -> ResponseList:
        """One synchronous negotiation round. All ranks must call this every
        cycle; the TCP recv provides the lockstep."""
        self.cycle_index += 1
        if faults.ACTIVE:
            faults.inject("controller.negotiate", rank=self.topo.rank)
        if self.topo.size == 1:
            return self._single_process_responses(requests, should_shutdown)
        if self.topo.rank == 0:
            return self._coordinator_round(requests, should_shutdown)
        return self._worker_round(requests, should_shutdown)

    def _worker_payload(self, requests: List[Request],
                        should_shutdown: bool) -> bytes:
        """This rank's cycle contribution: a compact MaskFrame when every
        pending tensor hit the cache mirror (the steady-state case —
        including idle cycles, whose mask is empty), a full RequestList
        otherwise."""
        hits: List[int] = []
        if self._mirror is not None:
            misses = []
            for req in requests:
                bit = self._mirror.hit(req)
                if bit is not None:
                    hits.append(bit)
                else:
                    misses.append(req)
            requests = misses
            self.cache_hit_count += len(hits)
            self.cache_miss_count += len(requests)
        mask = 0
        for bit in hits:
            mask |= 1 << bit
        mask_bytes = mask.to_bytes((mask.bit_length() + 7) // 8, "little")
        if self._mirror is not None and not requests:
            self.mask_only_sent_count += 1
            return MaskFrame(mask=mask_bytes,
                             shutdown=should_shutdown).to_bytes()
        self.serialized_request_count += len(requests)
        return RequestList(requests=requests, shutdown=should_shutdown,
                           cache_mask=mask_bytes).to_bytes()

    def _apply_response_list(self, rlist: ResponseList) -> ResponseList:
        if self._mirror is not None:
            self._mirror.apply(rlist.cache_assignments, rlist.evicted_bits)
        if rlist.tuned_params is not None:
            self.fusion_threshold = rlist.tuned_params[0]
        return rlist

    def _apply_reply(self, payload: bytes) -> ResponseList:
        """Decode the coordinator's verdict: a MaskFrame reply means every
        rank's cycle was fully cached — reconstruct the Responses locally
        from the mirrored templates (zero Response payloads shipped)."""
        if is_mask_frame(payload):
            frame = MaskFrame.from_bytes(payload)
            if frame.mask_int:
                self.fast_cycle_count += 1
            else:
                self.idle_fast_cycle_count += 1
            return self._responses_from_agreed_mask(frame.mask_int,
                                                    frame.shutdown)
        return self._apply_response_list(ResponseList.from_bytes(payload))

    def configure_fanin(self, plan, heartbeat=None) -> None:
        """Install (plan != None) or clear this epoch's negotiation
        fan-in plan (``core/negotiation_fanin.py:FaninPlan``).  Called at
        epoch bring-up, after every rank adopted rank 0's decision
        (``state._sync_controller_topology``) — mid-epoch installs would
        desynchronize the lockstep recv sets.  An active plan supersedes
        ``fanout_topology``: gather, broadcast, and worker rounds all
        follow the plan's roles."""
        self.fanin_plan = plan
        self.fanin_heartbeat = heartbeat
        if plan is not None:
            log.debug("negotiation fan-in active: rank %d role=%s "
                      "aggregator=%d members=%s",
                      self.topo.rank, plan.role, plan.aggregator_rank,
                      list(plan.member_ranks))

    def _worker_round(self, requests: List[Request],
                      should_shutdown: bool) -> ResponseList:
        payload = self._worker_payload(requests, should_shutdown)
        plan = self.fanin_plan
        if plan is not None:
            if plan.role == "member":
                return self._worker_round_member(payload)
            if plan.role == "aggregator":
                return self._worker_round_aggregator(payload)
            # "direct": host 0 or a vetoed host — star semantics, but
            # counted so the tree-vs-direct split is observable.
            self.fanin_direct_frame_count += 1
            self.mesh.send(0, payload)
            return self._apply_reply(self.mesh.recv(0))
        if self.fanout_topology == "tree":
            return self._worker_round_tree(payload)
        self.mesh.send(0, payload)
        return self._apply_reply(self.mesh.recv(0))

    def _worker_round_member(self, payload: bytes) -> ResponseList:
        """Fan-in member: heartbeat-gate, then route this cycle through
        the host's aggregator.  A stale heartbeat raises
        AggregatorStaleError BEFORE the send — the member must not park
        a frame with (and then recv-block on) an aggregator it has
        already convicted.  Aggregator DEATH needs no gate: the blocking
        recv raises PeerGoneError promptly and the coordinated abort +
        reshard recovery owns it."""
        hb = self.fanin_heartbeat
        if hb is not None:
            from ..common.exceptions import AggregatorStaleError

            try:
                hb.check()
            except AggregatorStaleError:
                self.fanin_fallback_count += 1
                raise
        self.fanin_tree_frame_count += 1
        agg = self.fanin_plan.aggregator_rank
        self.mesh.send(agg, payload)
        return self._apply_reply(self.mesh.recv(agg))

    def _worker_round_aggregator(self, payload: bytes) -> ResponseList:
        """Fan-in aggregator: collect the host's cycle payloads, fold the
        mask frames into one HostMaskFrame (fold_host — stateless, pure
        per cycle), forward ONE bundle to the coordinator, and relay the
        response payload down verbatim (it is identical for every rank,
        like the tree fan-out's relays).  Heartbeat is touched AFTER the
        relay completes: a wedged coordinator link must not keep
        advertising a live aggregator while members' frames pile up."""
        from . import timeline as timeline_mod
        from .negotiation_fanin import fold_host

        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        collected = [(self.topo.rank, payload)]
        for member in self.fanin_plan.member_ranks:
            collected.append((member, self.mesh.recv(member)))
        self.mesh.send(0, _encode_bundle(fold_host(collected)))
        self.fanin_tree_frame_count += 1
        reply = self.mesh.recv(0)
        for member in self.fanin_plan.member_ranks:
            self.mesh.send(member, reply)
        hb = self.fanin_heartbeat
        if hb is not None:
            hb.touch()
        if t0 is not None:
            timeline_mod.control_span_since(
                "controller", "FANIN_RELAY", t0, cycle=self.cycle_index,
                members=len(self.fanin_plan.member_ranks))
        return self._apply_reply(reply)

    def _worker_round_tree(self, payload: bytes) -> ResponseList:
        """Binomial-tree flavor: relay the subtree's gather bundles up to
        the parent, then relay the response broadcast down to the
        children.  Depth is O(log P) versus the star's O(P) serial
        coordinator loop; interior ranks do O(subtree) byte copies but
        those run in parallel across the tree.  Payloads (and the reply)
        are opaque bytes to the relays, so mask frames ride unchanged."""
        rank, size = self.topo.rank, self.topo.size
        entries = [(rank, payload)]
        for child in tree_children(rank, size):
            entries.extend(_decode_bundle(self.mesh.recv(child)))
        self.mesh.send(tree_parent(rank), _encode_bundle(entries))
        resp_payload = self.mesh.recv(tree_parent(rank))
        for child in tree_children(rank, size):
            self.mesh.send(child, resp_payload)
        return self._apply_reply(resp_payload)

    def _decode_worker_payload(self, payload: bytes):
        """(RequestList, was_mask_frame) from either wire flavor."""
        if is_mask_frame(payload):
            frame = MaskFrame.from_bytes(payload)
            return RequestList(shutdown=frame.shutdown,
                               cache_mask=frame.mask), True
        rl = RequestList.from_bytes(payload)
        self.serialized_request_count += len(rl.requests)
        return rl, False

    def _recv_ingress(self, sender: int) -> bytes:
        """One coordinator gather recv, counted: every fan-out shape
        funnels through here so ``controller_ingress_frames_total`` /
        ``_bytes_total`` compare star vs tree vs fan-in like for like —
        one increment per frame that actually arrived at rank 0."""
        data = self.mesh.recv(sender)
        self.ingress_frame_count += 1
        self.ingress_byte_count += len(data)
        return data

    def _gather_request_lists(self):
        """Yield every other rank's (rank, RequestList, was_mask) for this
        cycle, in deterministic rank order for the tree and fan-in shapes
        (the star's serial loop is ordered by construction).

        Under fan-in, a HostMaskFrame expands to one identical
        pending-mask contribution per covered rank — bit-exact with the
        star's per-rank MaskFrames because the frame's mask is the AND of
        exactly those ranks' masks and every rank re-announces its full
        mask every cycle."""
        plan = self.fanin_plan
        if plan is not None and plan.role == "coordinator":
            entries: List[tuple] = []
            for sender in plan.coordinator_senders:
                data = self._recv_ingress(sender)
                if sender in plan.bundle_senders:
                    entries.extend(_decode_bundle(data))
                else:
                    entries.append((sender, data))
            entries.sort()
            for rank, payload in entries:
                if is_host_mask_frame(payload):
                    frame = HostMaskFrame.from_bytes(payload)
                    for covered in frame.covered:
                        yield covered, RequestList(
                            shutdown=frame.shutdown,
                            cache_mask=frame.mask), True
                else:
                    rl, was_mask = self._decode_worker_payload(payload)
                    yield rank, rl, was_mask
        elif self.fanout_topology == "tree":
            entries = []
            for child in tree_children(0, self.topo.size):
                entries.extend(_decode_bundle(self._recv_ingress(child)))
            entries.sort()
            for rank, payload in entries:
                rl, was_mask = self._decode_worker_payload(payload)
                yield rank, rl, was_mask
        else:
            for worker in range(1, self.topo.size):
                rl, was_mask = self._decode_worker_payload(
                    self._recv_ingress(worker))
                yield worker, rl, was_mask

    def _broadcast_response_payload(self, payload: bytes) -> None:
        plan = self.fanin_plan
        if plan is not None and plan.role == "coordinator":
            for sender in plan.coordinator_senders:
                self.mesh.send(sender, payload)
        elif self.fanout_topology == "tree":
            for child in tree_children(0, self.topo.size):
                self.mesh.send(child, payload)
        else:
            for worker in range(1, self.topo.size):
                self.mesh.send(worker, payload)

    def _coordinator_round(self, own_requests: List[Request],
                           should_shutdown: bool) -> ResponseList:
        from .response_cache import CACHEABLE, cache_key

        self._cycle_assignments = []
        self._cycle_evictions = []
        ready: List[str] = list(self._stall_completed)
        self._stall_completed.clear()
        pending = self._pending_masks
        own_all_cached = True
        for req in own_requests:
            bit = self._cache.lookup(cache_key(req)) \
                if self._cache is not None \
                and req.request_type in CACHEABLE else None
            if bit is not None:
                pending[0] = pending.get(0, 0) | (1 << bit)
                self.cache_hit_count += 1
            else:
                own_all_cached = False
                if self._increment(req):
                    ready.append(req.tensor_name)
        all_mask_frames = True
        for worker, rl, was_mask in self._gather_request_lists():
            all_mask_frames = all_mask_frames and was_mask
            should_shutdown = should_shutdown or rl.shutdown
            if rl.cache_mask:
                pending[worker] = pending.get(worker, 0) | int.from_bytes(
                    rl.cache_mask, "little")
            for bit in rl.cache_hits:  # legacy list flavor
                pending[worker] = pending.get(worker, 0) | (1 << bit)
            for req in rl.requests:
                if self._increment(req):
                    ready.append(req.tensor_name)
        ready.extend(self._mature_deferred_tallies())

        # A JOIN that lands after a tensor's last active-rank request must
        # still complete that tensor: re-check pending entries against the
        # updated joined set (the reference re-evaluates the join-adjusted
        # count inside ComputeResponseList each cycle).
        if self._joined_ranks:
            ready_set = set(ready)
            for name, entry in self._message_table.items():
                if name in ready_set:
                    continue
                needed = self.topo.size - len(self._joined_ranks - entry.ranks)
                if len(entry.ranks) >= needed:
                    ready.append(name)

        # Readiness-ordered fusion: sort this cycle's completions by how
        # long each tensor has been negotiating (first_seen) before the
        # table entries are popped below.  The stable sort keeps arrival
        # order among ties; JOIN (never in the table) sorts first.  The
        # mask fast path is untouched — its bit order is already mirrored
        # deterministically on every rank.
        if self.fusion_order == "readiness" and len(ready) > 1:
            table = self._message_table
            by_age = sorted(
                ready,
                key=lambda n: e.first_seen
                if (e := table.get(n)) is not None else 0.0)
            if by_age != ready:
                metrics.inc("fusion_reorders_total")
                ready = by_age
        responses = [self._construct_response(name) for name in ready]
        responses = [r for r in responses if r is not None]
        mask_responses, ready_mask, mask_pure = self._mask_round(pending)
        responses.extend(mask_responses)
        tuned = self._autotune(responses)
        responses = self._fuse_responses(responses)
        self._update_stragglers()
        self._check_stalls()
        if self._cache is not None:
            self._cache.tick()

        # Zero-payload fast path: every rank's cycle was pure cache bits
        # (or idle) and the verdict is pure templates — broadcast only the
        # agreed bitvector; every rank (this one included, above)
        # reconstructs the identical fused ResponseList locally.  Any
        # cache-maintenance, tally, join, stall, or autotune traffic this
        # cycle forces the full ResponseList so that state ships.
        fast = (self.cache_enabled and own_all_cached and all_mask_frames
                and mask_pure and not ready and not self._joined_ranks
                and tuned is None and not self._cycle_assignments
                and not self._cycle_evictions and not self._stall_completed)
        if fast:
            if ready_mask:
                self.fast_cycle_count += 1
            else:
                self.idle_fast_cycle_count += 1
            mask_bytes = ready_mask.to_bytes(
                (ready_mask.bit_length() + 7) // 8, "little")
            self._broadcast_response_payload(
                MaskFrame(mask=mask_bytes,
                          shutdown=should_shutdown).to_bytes())
            return ResponseList(responses=responses,
                                shutdown=should_shutdown)

        rlist = ResponseList(responses=responses, shutdown=should_shutdown,
                             cache_assignments=self._cycle_assignments,
                             evicted_bits=self._cycle_evictions,
                             tuned_params=tuned)
        payload = rlist.to_bytes()
        self._broadcast_response_payload(payload)
        return rlist

    def _bit_template(self, bit: int) -> Optional[Request]:
        """Cached request template for a bit, from whichever side's cache
        this rank holds."""
        if self._cache is not None:
            return self._cache.rehydrate(bit, 0)
        if self._mirror is not None:
            return self._mirror.template(bit)
        return None

    def _responses_from_agreed_mask(self, mask: int,
                                    shutdown: bool) -> ResponseList:
        """Reconstruct the cycle's ResponseList from an agreed bitvector —
        the worker half of the zero-payload fast path.  Must mirror the
        coordinator's construction exactly: templates in ascending bit
        order, then the deterministic fusion scan under the (synchronized)
        threshold."""
        from ..common.exceptions import HorovodInternalError

        responses: List[Response] = []
        rm = mask
        while rm:
            low = rm & -rm
            bit = low.bit_length() - 1
            rm ^= low
            tpl = self._bit_template(bit)
            if tpl is None:
                # Protocol invariant: an agreed bit was announced by every
                # rank, so every rank holds its template.  Losing it means
                # divergent cache state — fail loudly, don't desync.
                raise HorovodInternalError(
                    f"fast-path agreed cache bit {bit} has no local "
                    "template (cache mirror diverged from coordinator)")
            responses.append(self._response_from_template(tpl))
        return ResponseList(responses=self._fuse_responses(responses),
                            shutdown=shutdown)

    def _mask_round(self, pending: Dict[int, int]):
        """Resolve the cache-bit masks: a bit set in EVERY active rank's
        pending mask is globally ready and its Response comes straight from
        the cached template (no per-rank tallying or re-validation — a hit
        means the rank's request matched the template key exactly).

        Also merges the transition case where some ranks sent a bit while
        others sent a full Request for the same tensor (e.g. around an
        eviction): those bits convert into table tallies so neither side
        strands.

        Returns ``(responses, ready_mask, pure)``; ``pure`` is True iff
        every response came straight from a live template in ready-bit
        order — the precondition for answering the cycle with the agreed
        bitvector alone (the coordinator half of the fast path).  Any
        eviction recovery, table merge, dropped bit, or error response
        clears it."""
        if not pending:
            return [], 0, True
        pure = True
        responses: List[Response] = []
        if self._cycle_evictions:
            # A bit evicted this cycle may still be pending on some ranks
            # (partial announcement): convert those announcements to table
            # tallies via the tombstoned template so the bit id can be
            # recycled safely once its tombstone expires.
            from dataclasses import replace as _replace

            for bit in self._cycle_evictions:
                low = 1 << bit
                if not any(m & low for m in pending.values()):
                    continue
                pure = False
                tpl = self._cache.rehydrate(bit, 0) if self._cache else None
                completed = False
                for r, m in list(pending.items()):
                    if m & low:
                        pending[r] = m & ~low
                        if tpl is not None:
                            completed |= self._increment(
                                _replace(tpl, request_rank=r))
                self._mask_bit_since.pop(bit, None)
                self._mask_bit_majority.pop(bit, None)
                if completed:
                    resp = self._construct_response(tpl.tensor_name)
                    if resp is not None:
                        responses.append(resp)

        union = 0
        for m in pending.values():
            union |= m
        if union == 0:
            return responses, 0, pure

        ready_mask = None
        for r in range(self.topo.size):
            eff = -1 if r in self._joined_ranks else pending.get(r, 0)
            ready_mask = eff if ready_mask is None else (ready_mask & eff)
            if ready_mask == 0:
                break
        ready_mask = ready_mask or 0
        # Bound to announced bits: with every rank joined each eff is -1 and
        # the AND-fold yields -1 (infinite sign-extended mask) — the bit
        # extraction loop below would never terminate on a negative int.
        ready_mask &= union
        if ready_mask:
            # One big-int op per rank clears every completing bit (the
            # per-bit/per-rank loop this path exists to avoid).
            for r, m in list(pending.items()):
                pending[r] = m & ~ready_mask

        rm = ready_mask
        while rm:
            low = rm & -rm
            bit = low.bit_length() - 1
            rm ^= low
            self._mask_bit_since.pop(bit, None)
            self._mask_bit_majority.pop(bit, None)
            tpl = self._cache.rehydrate(bit, 0) if self._cache else None
            if tpl is None:
                log.error("ready unknown cache bit %d; dropping", bit)
                pure = False
                continue
            if tpl.request_type == RequestType.BROADCAST and \
                    self._joined_ranks:
                pure = False
                responses.append(Response(
                    response_type=ResponseType.ERROR,
                    tensor_names=[tpl.tensor_name],
                    error_message=f"broadcast for {tpl.tensor_name} cannot "
                                  "complete with joined ranks (Join "
                                  "supports allreduce only)."))
                continue
            responses.append(self._response_from_template(tpl))

        # Leftover bits (present on SOME ranks only): start their stall
        # clock and merge with any same-tensor full-Request tally so mixed
        # bit/Request submissions cannot strand each other.  Steady state
        # (every bit completes in its cycle) leaves this loop empty.
        leftover = union & ~ready_mask
        if leftover:
            from dataclasses import replace as _replace

            now = time.monotonic()
            while leftover:
                low = leftover & -leftover
                bit = low.bit_length() - 1
                leftover ^= low
                self._mask_bit_since.setdefault(bit, now)
                if bit not in self._mask_bit_majority:
                    have = sum(1 for m in pending.values() if m & low)
                    if 2 * have >= self.topo.size - len(self._joined_ranks):
                        self._mask_bit_majority[bit] = now
                tpl = self._cache.rehydrate(bit, 0) if self._cache else None
                if tpl is None:
                    log.error("pending unknown cache bit %d; dropping", bit)
                    self._clear_bit(bit)
                    pure = False
                    continue
                if tpl.tensor_name in self._message_table:
                    pure = False
                    completed = False
                    for r, m in list(pending.items()):
                        if m & low:
                            pending[r] = m & ~low
                            completed |= self._increment(
                                _replace(tpl, request_rank=r))
                    self._mask_bit_since.pop(bit, None)
                    self._mask_bit_majority.pop(bit, None)
                    if completed:
                        resp = self._construct_response(tpl.tensor_name)
                        if resp is not None:
                            responses.append(resp)
        return responses, ready_mask, pure

    def _clear_bit(self, bit: int) -> None:
        low = 1 << bit
        for r, m in list(self._pending_masks.items()):
            if m & low:
                self._pending_masks[r] = m & ~low
        self._mask_bit_since.pop(bit, None)
        self._mask_bit_majority.pop(bit, None)

    def _response_from_template(self, tpl: Request) -> Response:
        """Response for a fully-hit cached tensor — field-for-field what
        ``_construct_response`` emits for a validated single-tensor
        ALLREDUCE/ADASUM/BROADCAST (the only cacheable ops)."""
        rtype = {
            RequestType.ALLREDUCE: ResponseType.ALLREDUCE,
            RequestType.ADASUM: ResponseType.ADASUM,
            RequestType.BROADCAST: ResponseType.BROADCAST,
        }[tpl.request_type]
        resp = Response(
            response_type=rtype,
            tensor_names=[tpl.tensor_name],
            tensor_type=tpl.tensor_type,
            tensor_sizes=[tpl.num_elements],
            devices=[tpl.device],
            prescale_factor=tpl.prescale_factor,
            postscale_factor=tpl.postscale_factor,
            last_joined_rank=min(self._joined_ranks)
            if self._joined_ranks else -1,
        )
        resp._payload_bytes = tpl.num_elements * tpl.tensor_type.itemsize
        return resp

    def _autotune(self, responses: List[Response]):
        """Feed the cycle's reduced byte volume to the ParameterManager;
        returns new (fusion_bytes, cycle_ms) when the tuner moves."""
        if self.param_manager is None or not self.param_manager.enabled:
            return None
        nbytes = sum(
            sum(r.tensor_sizes) * r.tensor_type.itemsize
            for r in responses
            if r.response_type in (ResponseType.ALLREDUCE, ResponseType.ADASUM))
        tuned = self.param_manager.update(nbytes)
        if tuned is not None:
            self.fusion_threshold = tuned[0]
        return tuned

    def _single_process_responses(self, requests: List[Request],
                                  should_shutdown: bool) -> ResponseList:
        responses = []
        for req in requests:
            if self._increment(req):
                resp = self._construct_response(req.tensor_name)
                if resp is not None:
                    responses.append(resp)
        return ResponseList(responses=self._fuse_responses(responses),
                            shutdown=should_shutdown)

    # ------------------------------------------------------------------
    # message table
    # ------------------------------------------------------------------

    def _increment(self, req: Request, defer_faults: bool = True) -> bool:
        """Tally one rank's readiness; True when the tensor is globally ready.

        Reference ``IncrementTensorCount`` (``controller.cc:1030-1053``):
        completion when (requesting ranks) + (joined ranks) covers the world.

        ``controller.tally`` fault site: a matching ``delay_ms`` clause
        parks this tally on ``_deferred_tallies`` instead of sleeping —
        sleeping here would slow the whole lockstep cycle equally and
        attribute lag to nobody, while a parked tally leaves the tensor
        incomplete *missing exactly this rank* across cycles, which is
        what a chronically slow rank looks like to the straggler EWMAs.
        Replayed tallies pass ``defer_faults=False`` so an ``after=``
        clause cannot re-defer them forever.  Only the request-table path
        is injectable: cache-bit announcements never reach this tally.
        """
        if faults.ACTIVE and defer_faults and self.topo.size > 1 \
                and req.request_type != RequestType.JOIN:
            delay = faults.inject_deferred("controller.tally",
                                           rank=req.request_rank)
            if delay > 0.0:
                self._deferred_tallies.append(
                    (time.monotonic() + delay, req))
                return False
        if req.request_type == RequestType.JOIN:
            self._joined_ranks.add(req.request_rank)
            # Join completes when *every* rank has joined.
            return len(self._joined_ranks) == self.topo.size

        entry = self._message_table.get(req.tensor_name)
        if entry is None:
            entry = self._message_table[req.tensor_name] = _TableEntry()
            if self.timeline is not None:
                self.timeline.negotiate_start(req.tensor_name,
                                              req.request_type.name)
        if req.request_rank in entry.ranks:
            log.warning("rank %d re-submitted tensor %s before completion",
                        req.request_rank, req.tensor_name)
            return False
        entry.ranks.add(req.request_rank)
        entry.requests.append(req)
        if self.timeline is not None:
            self.timeline.negotiate_rank_ready(req.tensor_name, req.request_rank)
        needed = self.topo.size - len(self._joined_ranks - entry.ranks)
        if entry.majority_seen is None and \
                2 * len(entry.ranks) >= self.topo.size - len(self._joined_ranks):
            entry.majority_seen = time.monotonic()
        return len(entry.ranks) >= needed

    def _mature_deferred_tallies(self) -> List[str]:
        """Replay parked tallies whose injected delay has matured; returns
        tensors the replays completed (merged into the cycle's ready list).
        Empty-list fast path when nothing is parked (the normal case)."""
        if not self._deferred_tallies:
            return []
        now = time.monotonic()
        completed: List[str] = []
        parked: List[Tuple[float, Request]] = []
        for due, req in self._deferred_tallies:
            if due <= now:
                if self._increment(req, defer_faults=False):
                    completed.append(req.tensor_name)
            else:
                parked.append((due, req))
        self._deferred_tallies = parked
        return completed

    # ------------------------------------------------------------------
    # response construction & validation
    # ------------------------------------------------------------------

    def _construct_response(self, name: str) -> Optional[Response]:
        """Validate cross-rank consistency and emit the Response.

        Reference ``ConstructResponse`` (``controller.cc:547-824``): any
        dtype/op/shape/root/scale disagreement yields an ERROR response that
        every rank delivers to the waiting callback."""
        if name == JOIN_TENSOR_NAME or not self._message_table.get(name):
            if len(self._joined_ranks) == self.topo.size:
                self._joined_ranks.clear()
                return Response(response_type=ResponseType.JOIN,
                                tensor_names=[JOIN_TENSOR_NAME])
            return None

        entry = self._message_table.pop(name)
        if self.timeline is not None:
            self.timeline.negotiate_end(name)
        reqs = entry.requests
        first = reqs[0]

        error = None
        for req in reqs[1:]:
            if req.tensor_type != first.tensor_type:
                error = (f"Mismatched data types for {name}: rank "
                         f"{first.request_rank} sent {first.tensor_type.name}, rank "
                         f"{req.request_rank} sent {req.tensor_type.name}.")
                break
            if req.request_type != first.request_type:
                error = (f"Mismatched operations for {name}: ranks disagree on "
                         f"{first.request_type.name} vs {req.request_type.name}.")
                break
            if req.prescale_factor != first.prescale_factor or \
                    req.postscale_factor != first.postscale_factor:
                error = f"Mismatched pre/postscale factors for {name}."
                break

        op = first.request_type
        tensor_sizes: List[int] = []
        devices = sorted({r.device for r in reqs})

        if error is None and op in (RequestType.ALLREDUCE, RequestType.ADASUM,
                                    RequestType.BROADCAST):
            for req in reqs[1:]:
                if req.tensor_shape != first.tensor_shape:
                    error = (f"Mismatched {op.name.lower()} tensor shapes for "
                             f"{name}: rank {first.request_rank} has "
                             f"{first.tensor_shape}, rank {req.request_rank} has "
                             f"{req.tensor_shape}.")
                    break
            tensor_sizes = [first.num_elements]

        if error is None and op == RequestType.BROADCAST:
            for req in reqs[1:]:
                if req.root_rank != first.root_rank:
                    error = (f"Mismatched broadcast root ranks for {name}: "
                             f"{first.root_rank} vs {req.root_rank}.")
                    break
            # A joined rank has no root_rank/output for a broadcast it never
            # submitted; like the reference, Join supports allreduce only.
            if error is None and len(entry.ranks) != self.topo.size:
                error = (f"broadcast for {name} cannot complete with joined "
                         f"ranks (Join supports allreduce only).")

        if error is None and op == RequestType.ALLGATHER:
            # Shapes must agree on every dim except the first; response
            # carries each rank's first dimension, ordered by rank
            # (reference packs the same into tensor_sizes).
            by_rank = sorted(reqs, key=lambda r: r.request_rank)
            for req in by_rank:
                if len(req.tensor_shape) != len(first.tensor_shape) or \
                        req.tensor_shape[1:] != first.tensor_shape[1:]:
                    error = (f"Mismatched allgather tensor shapes for {name}: "
                             f"all dims but the first must match "
                             f"({first.tensor_shape} vs {req.tensor_shape}).")
                    break
            if error is None:
                if len(by_rank) != self.topo.size:
                    error = (f"allgather for {name} cannot complete with joined "
                             f"ranks (Join supports allreduce only, as in the "
                             f"reference JoinOp).")
                else:
                    tensor_sizes = [r.tensor_shape[0] if r.tensor_shape else 1
                                    for r in by_rank]

        if error is None and op == RequestType.ALLTOALL:
            by_rank = sorted(reqs, key=lambda r: r.request_rank)
            if len(by_rank) != self.topo.size:
                error = f"alltoall for {name} cannot complete with joined ranks."
            else:
                for req in by_rank:
                    # Trailing dims must agree (like allgather): a
                    # mismatch would give ranks different row sizes and
                    # hang the exchange instead of erroring.
                    if len(req.tensor_shape) != len(first.tensor_shape) \
                            or req.tensor_shape[1:] != first.tensor_shape[1:]:
                        error = (f"Mismatched alltoall tensor shapes for "
                                 f"{name}: all dims but the first must "
                                 f"match ({first.tensor_shape} vs "
                                 f"{req.tensor_shape}).")
                        break
                    if len(req.splits) != self.topo.size:
                        error = (f"alltoall splits for {name} must have one entry "
                                 f"per rank (rank {req.request_rank} sent "
                                 f"{len(req.splits)}).")
                        break
                    dim0 = req.tensor_shape[0] if req.tensor_shape else 0
                    if sum(req.splits) != dim0:
                        error = (f"alltoall splits for {name} sum to "
                                 f"{sum(req.splits)} but first dimension is "
                                 f"{dim0} on rank {req.request_rank}.")
                        break
                if error is None:
                    # Flattened N×N send-split matrix, row r = rank r's splits;
                    # rank k's recv splits are column k.
                    for req in by_rank:
                        tensor_sizes.extend(req.splits)

        if error is not None:
            return Response(response_type=ResponseType.ERROR,
                            tensor_names=[name], error_message=error)

        rtype = {
            RequestType.ALLREDUCE: ResponseType.ALLREDUCE,
            RequestType.ALLGATHER: ResponseType.ALLGATHER,
            RequestType.BROADCAST: ResponseType.BROADCAST,
            RequestType.ADASUM: ResponseType.ADASUM,
            RequestType.ALLTOALL: ResponseType.ALLTOALL,
            RequestType.BARRIER: ResponseType.BARRIER,
        }[op]
        if self._cache is not None:
            bit, evicted = self._cache.maybe_insert(first)
            self._cycle_evictions.extend(evicted)
            if bit is not None:
                self._cycle_assignments.append((bit, first))
        resp = Response(
            response_type=rtype,
            tensor_names=[name],
            tensor_type=first.tensor_type,
            tensor_sizes=tensor_sizes,
            devices=devices,
            prescale_factor=first.prescale_factor,
            postscale_factor=first.postscale_factor,
            last_joined_rank=min(self._joined_ranks) if self._joined_ranks else -1,
        )
        # Coordinator-local payload accounting for the fusion threshold.
        # ALLGATHER tensor_sizes are first dims only; the true bytes scale
        # by the inner-dim product (available here from the request shape,
        # not in the wire Response).
        itemsize = first.tensor_type.itemsize
        if rtype == ResponseType.ALLGATHER:
            dim0 = first.tensor_shape[0] if first.tensor_shape else 1
            inner_n = first.num_elements // max(1, dim0)
            resp._payload_bytes = sum(tensor_sizes) * inner_n * itemsize
        else:
            resp._payload_bytes = sum(tensor_sizes) * itemsize
        return resp

    # ------------------------------------------------------------------
    # fusion
    # ------------------------------------------------------------------

    _FUSIBLE = (ResponseType.ALLREDUCE, ResponseType.ADASUM,
                ResponseType.ALLGATHER)

    @staticmethod
    def _fusion_compatible(a: Response, b: Response) -> bool:
        return (a.response_type == b.response_type
                and a.tensor_type == b.tensor_type
                and a.devices == b.devices
                and a.prescale_factor == b.prescale_factor
                and a.postscale_factor == b.postscale_factor)

    def _fuse_responses(self, responses: List[Response]) -> List[Response]:
        """FIFO scan with look-ahead (reference ``FuseResponses``,
        ``controller.cc:859-998``): pop the front response, then sweep the
        REMAINING queue for compatible ones to pack under the threshold —
        interleaved dtypes no longer defeat fusion (they merely get skipped
        and seed their own buckets).  ALLREDUCE/ADASUM fuse flat element
        counts; ALLGATHER fuses whole per-rank size blocks (each tensor
        contributes ``size`` entries to ``tensor_sizes``)."""
        fused: List[Response] = []
        pending = list(responses)
        while pending:
            resp = pending.pop(0)
            if resp.response_type not in self._FUSIBLE:
                fused.append(resp)
                continue
            itemsize = resp.tensor_type.itemsize

            def payload_bytes(r: Response) -> int:
                return getattr(r, "_payload_bytes",
                               sum(r.tensor_sizes) * itemsize)

            total = payload_bytes(resp)
            rest: List[Response] = []
            for cand in pending:
                cand_bytes = payload_bytes(cand)
                if (self._fusion_compatible(resp, cand)
                        and total + cand_bytes <= self.fusion_threshold):
                    resp.tensor_names.extend(cand.tensor_names)
                    resp.tensor_sizes.extend(cand.tensor_sizes)
                    total += cand_bytes
                else:
                    rest.append(cand)
            pending = rest
            fused.append(resp)
        return fused

    # ------------------------------------------------------------------
    # straggler detection (coordinator-side; docs/observability.md)
    # ------------------------------------------------------------------

    def _update_stragglers(self) -> None:
        """Per-cycle readiness-lag EWMAs from the tallies the coordinator
        already keeps: a rank is *behind* by ``now - majority_seen`` for
        every incomplete tensor (table entry or announced cache bit) whose
        median announcer is ready but this rank is not.  Steady state —
        every tensor completes in its announcement cycle — stamps no
        majorities, so the whole update is two falsy checks."""
        if not self._straggler_decaying and not self._mask_bit_majority \
                and not any(e.majority_seen is not None
                            for e in self._message_table.values()):
            return
        now = time.monotonic()
        behind: Dict[int, float] = {}
        active = set(range(self.topo.size)) - self._joined_ranks
        for entry in self._message_table.values():
            if entry.majority_seen is None:
                continue
            age = now - entry.majority_seen
            for r in active - entry.ranks:
                if age > behind.get(r, 0.0):
                    behind[r] = age
        for bit, since in self._mask_bit_majority.items():
            low = 1 << bit
            age = now - since
            for r in active:
                if not (self._pending_masks.get(r, 0) & low) \
                        and age > behind.get(r, 0.0):
                    behind[r] = age
        ewma = self._straggler_ewma
        thresh = self.straggler_threshold
        decaying = False
        for r in range(self.topo.size):
            lag = behind.get(r, 0.0)
            v = ewma.get(r, 0.0)
            v += self.straggler_alpha * (lag - v)
            ewma[r] = v
            decaying = decaying or v > 1e-9
            if lag > 0.0:
                metrics.observe("straggler_lag_seconds", lag, rank=str(r))
            if thresh <= 0.0:
                continue
            if v > thresh and r not in self._straggler_suspects:
                self._straggler_suspects.add(r)
                metrics.inc("straggler_flags_total", rank=str(r))
                flight_recorder.record(
                    "straggler", rank=r, lag_ewma=round(v, 6),
                    threshold=thresh)
                log.warning(
                    "straggler detected: rank %d readiness-lag EWMA %.3fs "
                    "exceeds HOROVOD_STRAGGLER_THRESHOLD_SECS=%.3fs "
                    "(it keeps completing tensors %0.3fs after the median "
                    "announcer)", r, v, thresh, lag)
                self._set_suspect_gauge()
            elif v < thresh / 2.0 and r in self._straggler_suspects:
                # Hysteresis: clear at half the flag threshold so a rank
                # oscillating near it doesn't spam flag transitions.
                self._straggler_suspects.discard(r)
                flight_recorder.record("straggler_cleared", rank=r,
                                       lag_ewma=round(v, 6))
                log.info("straggler cleared: rank %d readiness-lag EWMA "
                         "back to %.3fs", r, v)
                self._set_suspect_gauge()
        self._straggler_decaying = decaying or bool(self._straggler_suspects)
        if self.demotion.enabled:
            from ..common import env as env_mod

            victim = self.demotion.observe(env_mod.get_epoch(), ewma, active)
            if victim is not None:
                self._report_demotion(victim, ewma.get(victim, 0.0))

    def _report_demotion(self, victim: int, lag_ewma: float) -> None:
        """Deliver a chronic-straggler verdict: flight-recorder event +
        log line on the coordinator, and a best-effort demotion report to
        the elastic driver over the rendezvous store.  Outside an elastic
        job (no store in the environment) the verdict is detector-only —
        named loudly, acted on by nobody."""
        flight_recorder.record(
            "straggler_demotion", rank=victim, lag_ewma=round(lag_ewma, 6),
            threshold=self.demotion.demote_secs,
            cycles=self.demotion.demote_cycles)
        log.warning(
            "chronic straggler: rank %d readiness-lag EWMA %.3fs stayed "
            "over HOROVOD_STRAGGLER_DEMOTE_SECS=%.3fs for %d consecutive "
            "busy cycles — reporting for demotion", victim, lag_ewma,
            self.demotion.demote_secs, self.demotion.demote_cycles)
        try:
            from ..elastic import rendezvous_client

            posted = rendezvous_client.post_demotion_report(
                victim, lag_ewma, self.demotion.demote_secs,
                self.demotion.demote_cycles)
        except Exception as exc:  # noqa: BLE001 — a demotion report must
            # never take down the negotiation cycle it rode along with
            posted = False
            log.warning("demotion report for rank %d failed: %s",
                        victim, exc)
        if not posted:
            log.warning("no rendezvous store reachable: demotion verdict "
                        "for rank %d is detector-only", victim)

    def _set_suspect_gauge(self) -> None:
        worst = max(self._straggler_suspects,
                    key=lambda r: self._straggler_ewma.get(r, 0.0)) \
            if self._straggler_suspects else -1
        metrics.set_gauge("straggler_suspect", worst)

    def _lag_suffix(self, missing: List[int]) -> str:
        """Name the laggard for the stall-inspector warnings: the missing
        rank with the worst readiness-lag EWMA (empty when no lag has been
        observed — e.g. a rank that never announced anything)."""
        candidates = [r for r in missing
                      if self._straggler_ewma.get(r, 0.0) > 1e-9]
        if not candidates:
            return ""
        worst = max(candidates, key=lambda r: self._straggler_ewma[r])
        return (f"; slowest by readiness-lag EWMA: rank {worst} "
                f"({self._straggler_ewma[worst]:.3f}s)")

    # ------------------------------------------------------------------
    # stall inspection (coordinator-side; reference stall_inspector.cc)
    # ------------------------------------------------------------------

    def _check_stalls(self) -> None:
        # The shutdown deadline is independent of the warning: disabling
        # stall WARNINGS must not silently disable the hard abort, and a
        # shutdown time shorter than the warning time must still fire on
        # its own schedule.
        warn, shut = self.stall_warning_secs, self.stall_shutdown_secs
        enabled = [t for t in (warn, shut) if t > 0]
        if not enabled:
            return
        now = time.monotonic()
        if now - self._last_stall_check < min(enabled):
            return
        self._last_stall_check = now
        # Surface the inspector's view into the metrics registry: how many
        # tensors are currently past the stall threshold (gauge, refreshed
        # every check) and how many hard shutdowns ever fired (counter).
        stall_age = min(t for t in (warn, shut) if t > 0)
        stalled = sum(
            1 for e in self._message_table.values()
            if now - e.first_seen > stall_age)
        stalled += sum(1 for since in self._mask_bit_since.values()
                       if now - since > stall_age)
        metrics.set_gauge("stalled_tensors", stalled)
        for name, entry in self._message_table.items():
            age = now - entry.first_seen
            missing = sorted(set(range(self.topo.size))
                             - entry.ranks - self._joined_ranks)
            if shut > 0 and age > shut:
                # Hard abort (reference stall_inspector.h:77-80): tearing
                # down the coordinator breaks the mesh, so every healthy
                # rank surfaces a HorovodInternalError instead of hanging
                # forever on the missing ones.
                from ..common.exceptions import HorovodInternalError

                metrics.inc("stall_shutdowns_total")
                raise HorovodInternalError(
                    f"stall shutdown: tensor {name} incomplete for "
                    f"{age:.0f}s (> {shut}s), missing ranks {missing}")
            if warn <= 0 or age <= warn:
                continue
            log.warning(
                "One or more tensors were submitted to be reduced, gathered "
                "or broadcasted by subset of ranks and are waiting for the "
                "remainder: %s stalled for %.0fs, missing ranks: %s%s",
                name, age, missing, self._lag_suffix(missing))
            # A stalled tensor's cached negotiation is stale
            # (reference InvalidateStalledCachedTensors): evict so any
            # post-recovery resubmission renegotiates from scratch.
            if self._cache is not None:
                bit = self._cache.invalidate_name(name)
                if bit is not None:
                    self._cycle_evictions.append(bit)

        # Mask-path stalls: a bit some ranks announced long ago that never
        # reached all ranks.  Convert the partial announcements into table
        # tallies (so the waiting ranks eventually resolve — typically as a
        # loud mismatch/stall on the table path) and invalidate the entry.
        from dataclasses import replace as _replace

        for bit, since in list(self._mask_bit_since.items()):
            age = now - since
            have = [r for r, m in self._pending_masks.items()
                    if m & (1 << bit)]
            missing = sorted(set(range(self.topo.size)) - set(have)
                             - self._joined_ranks)
            if shut > 0 and age > shut:
                from ..common.exceptions import HorovodInternalError

                tpl = self._cache.rehydrate(bit, 0) if self._cache else None
                name = tpl.tensor_name if tpl else f"<bit {bit}>"
                metrics.inc("stall_shutdowns_total")
                raise HorovodInternalError(
                    f"stall shutdown: cached tensor {name} incomplete for "
                    f"{age:.0f}s (> {shut}s), missing ranks {missing}")
            if warn <= 0 or age <= warn:
                continue
            tpl = self._cache.rehydrate(bit, 0) if self._cache else None
            if tpl is None:
                self._clear_bit(bit)
                continue
            log.warning(
                "cached tensor %s announced by ranks %s stalled for %.0fs, "
                "missing ranks: %s%s — invalidating its cache entry",
                tpl.tensor_name, have, age, missing,
                self._lag_suffix(missing))
            for r in have:
                self._pending_masks[r] &= ~(1 << bit)
                if self._increment(_replace(tpl, request_rank=r)):
                    self._stall_completed.append(tpl.tensor_name)
            self._mask_bit_since.pop(bit, None)
            self._mask_bit_majority.pop(bit, None)
            evicted = self._cache.invalidate_name(tpl.tensor_name)
            if evicted is not None:
                self._cycle_evictions.append(evicted)

    # ------------------------------------------------------------------
    # small collective helpers for init/shutdown/elastic paths
    # ------------------------------------------------------------------

    def bcast_bytes(self, payload: Optional[bytes], root: int = 0) -> bytes:
        if self.topo.size == 1:
            return payload or b""
        if self.topo.rank == root:
            for peer in range(self.topo.size):
                if peer != root:
                    self.mesh.send(peer, payload or b"")
            return payload or b""
        return self.mesh.recv(root)

    def gather_bytes(self, payload: bytes, root: int = 0) -> Optional[List[bytes]]:
        if self.topo.size == 1:
            return [payload]
        if self.topo.rank == root:
            out: List[Optional[bytes]] = [None] * self.topo.size
            out[root] = payload
            for peer in range(self.topo.size):
                if peer != root:
                    out[peer] = self.mesh.recv(peer)
            return out  # type: ignore[return-value]
        self.mesh.send(root, payload)
        return None

    def barrier(self) -> None:
        self.gather_bytes(b"")
        self.bcast_bytes(b"")
