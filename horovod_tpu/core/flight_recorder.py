"""Flight recorder — always-on bounded event ring + post-mortem dumps.

The failure plane (PR 2/4) guarantees a *loud, coordinated* death, but
"why" still meant log archaeology across N ranks.  This module keeps a
small in-memory ring of recent events on every rank — wire frames,
negotiation cycles, fired fault clauses, epoch changes, abort traffic —
and, when the background loop dies (``CoordinatedAbortError``,
``FrameCorruptError``, any fatal error), dumps ``{reason, metrics
snapshot, last-K events, held locks if lockdep is active}`` to a per-rank
JSON file next to the worker's log.  The chaos suite asserts the dump
exists and parses on every rank after an injected corruption abort.

Recording is a deque append under a small lock (~1 µs) and is enabled by
default; ``HOROVOD_FLIGHT_RECORDER=0`` reduces every ``record`` call to
one attribute read.  Dumps are written atomically (tmp + ``os.replace``)
so a process dying mid-dump can never leave a half-written post-mortem.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import List, Optional

from ..common import env as env_mod
from ..common.logging_util import get_logger

log = get_logger("horovod_tpu.flight_recorder")

DUMP_FORMAT = "hvd-flight-recorder-v1"


def _dump_filename(rank: int) -> str:
    return f"hvd_flight_recorder.rank{rank}.json"


class FlightRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.reconfigure()

    def reconfigure(self) -> None:
        """(Re)read the env knobs — workers configure at import from the
        launcher-propagated env; tests re-point the dir/capacity."""
        self.enabled = env_mod.get_bool(env_mod.HOROVOD_FLIGHT_RECORDER,
                                        True)
        maxlen = max(1, env_mod.get_int(
            env_mod.HOROVOD_FLIGHT_RECORDER_EVENTS,
            env_mod.DEFAULT_FLIGHT_RECORDER_EVENTS))
        with self._lock:
            old = list(getattr(self, "_events", []))
            self._events: collections.deque = collections.deque(
                old[-maxlen:], maxlen=maxlen)

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        evt = {"t_mono": time.monotonic(), "t_wall": time.time(),
               "thread": threading.current_thread().name, "kind": kind}
        evt.update(fields)
        with self._lock:
            self._events.append(evt)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the post-mortem JSON; returns the path (None when the
        recorder is disabled).  Never raises — a failing dump must not
        mask the error being dumped (the caller logs the verdict)."""
        if not self.enabled:
            return None
        rank = env_mod.get_int(env_mod.HOROVOD_RANK, 0)
        if path is None:
            # Dumps land in an hvd_flight_recorder/ SUBDIRECTORY of the
            # configured dir (default cwd) so an N-rank post-mortem is one
            # self-contained folder instead of N files strewn at repo root.
            dump_dir = os.path.join(
                env_mod.get_str(env_mod.HOROVOD_FLIGHT_RECORDER_DIR) or ".",
                "hvd_flight_recorder")
            try:
                os.makedirs(dump_dir, exist_ok=True)
            except OSError as e:
                log.error("flight-recorder dir %s failed: %s", dump_dir, e)
                return None
            path = os.path.join(dump_dir, _dump_filename(rank))
        doc = {
            "format": DUMP_FORMAT,
            "reason": reason,
            "rank": rank,
            "pid": os.getpid(),
            "ts_unix": time.time(),
            "held_locks": self._held_locks(),
            "metrics": self._metrics_snapshot(),
            "events": self.events(),
        }
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.error("flight-recorder dump to %s failed: %s", path, e)
            return None

    @staticmethod
    def _metrics_snapshot() -> Optional[dict]:
        from . import metrics

        if not metrics.ENABLED:
            return None
        try:
            return metrics.registry.snapshot()
        except Exception as e:  # noqa: BLE001 — the dump must still land
            return {"error": f"metrics snapshot failed: {e}"}

    @staticmethod
    def _held_locks() -> Optional[List[str]]:
        """The dumping thread's held-lock sites, when lockdep is on —
        a loop that died while holding something is the smoking gun."""
        from ..common import lockdep

        if not lockdep.is_installed():
            return None
        try:
            return lockdep.current_held()
        except Exception:  # noqa: BLE001 — diagnostics only
            return None


#: Process-global recorder every instrumented site records into.
recorder = FlightRecorder()


def record(kind: str, **fields) -> None:
    """Module-level convenience mirroring :func:`metrics.inc` — one
    attribute read when the recorder is disabled."""
    if recorder.enabled:
        recorder.record(kind, **fields)
