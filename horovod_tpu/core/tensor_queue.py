"""TensorQueue — the hand-off point between framework threads and the
background coordination thread.

Role of the reference's ``horovod/common/tensor_queue.h:32-58`` /
``tensor_queue.cc``: a mutex-guarded table of in-flight tensor entries plus a
queue of pending Requests.  Framework threads add (entry, request) pairs; the
background thread pops requests each cycle and later claims entries named by
a negotiated Response.  Duplicate in-flight names are an error
(``DUPLICATE_NAME_ERROR``, ``common.h:164-167``).

Entries hold host numpy buffers on the TCP data plane, or jax device
arrays on the XLA data plane (``entry.device`` distinguishes them and the
controller negotiates agreement); the controller itself only reads
shape/dtype metadata, staying framework-agnostic.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ..common import faults
from ..common.exceptions import DuplicateNameError
from . import timeline as timeline_mod
from .messages import Request, RequestType, Response


@dataclass
class Status:
    ok: bool = True
    error_message: str = ""
    # True when the op dispatched async device work: outputs are unready
    # arrays and callbacks fire from the finalizer thread once the device
    # signals completion (reference IN_PROGRESS + finalizer-thread design,
    # ``gpu_operations.h:98-127``).
    pending: bool = False
    # True when outputs are immutable device futures (jax arrays): callbacks
    # fire IMMEDIATELY with the unready arrays — downstream jax work chains
    # on array readiness with no host wait — while a finalizer watchdog
    # still block_until_ready()s for failure detection, surfacing errors on
    # the next enqueue like the reference's NCCL async-error watchdog
    # (``nccl_operations.cc:96-109``).
    eager_complete: bool = False

    @staticmethod
    def OK() -> "Status":
        return Status(True, "")

    @staticmethod
    def in_progress() -> "Status":
        return Status(True, "", pending=True)

    @staticmethod
    def dispatched() -> "Status":
        return Status(True, "", pending=True, eager_complete=True)

    @staticmethod
    def error(msg: str) -> "Status":
        return Status(False, msg)


@dataclass
class TensorTableEntry:
    """Reference ``TensorTableEntry`` (``common.h:238-261``)."""

    tensor_name: str
    tensor: Optional[np.ndarray] = None      # input buffer (None for joined)
    output: Optional[np.ndarray] = None      # filled by the op
    root_rank: int = -1
    device: int = -1
    request_type: RequestType = RequestType.ALLREDUCE
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    splits: Optional[List[int]] = None       # alltoall send splits
    received_splits: Optional[List[int]] = None
    # Called exactly once with (status, entry); entry.output holds the result.
    callback: Callable = field(default=lambda status, entry: None)
    # context fields used by the data plane to hand results back
    context: dict = field(default_factory=dict)


class TensorQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[str, TensorTableEntry] = {}
        self._pending: List[Request] = []
        self._closed = False
        # Optional wake signal: the background loop parks on this event
        # between idle cycles instead of a fixed sleep, so an enqueue cuts
        # enqueue→negotiate latency from ~cycle_time/2 to ~0 (the adaptive
        # cycle timing half of the steady-state fast path).
        self._wake: Optional[threading.Event] = None

    def set_wake_event(self, event: threading.Event) -> None:
        self._wake = event

    def add(self, entry: TensorTableEntry, request: Request) -> None:
        from ..common.exceptions import HorovodInternalError

        # The submission-side fault site: delaying here makes THIS rank a
        # genuine compute straggler (it announces readiness cycles after
        # its peers, which keep negotiating), unlike delays inside the
        # lockstep negotiation/dispatch paths that stall every rank
        # equally.  Fires before the lock — a hang/delay must not block
        # other framework threads (HVD001).
        if faults.ACTIVE:
            faults.inject("enqueue.collective")
        timeline_mod.lifecycle_begin(entry.tensor_name, "LC_SUBMITTED")
        with self._lock:
            if self._closed:
                # The background loop has exited and drained the table; an
                # add after that point would strand its waiter forever.
                raise HorovodInternalError(
                    "Horovod background loop is not running (shut down or "
                    "failed); reinitialize before submitting collectives")
            if entry.tensor_name in self._table:
                raise DuplicateNameError(
                    f"tensor {entry.tensor_name!r} already in flight; collective "
                    f"names must be unique until the previous op completes")
            self._table[entry.tensor_name] = entry
            self._pending.append(request)
        if self._wake is not None:
            self._wake.set()

    def close(self) -> None:
        """Reject all future adds; called before the final drain."""
        with self._lock:
            self._closed = True

    def pop_messages(self) -> List[Request]:
        """Drain pending requests (one cycle's worth) —
        ``PopMessagesFromQueue`` (``tensor_queue.h:44``)."""
        with self._lock:
            out, self._pending = self._pending, []
            return out

    def push_messages(self, requests: List[Request]) -> None:
        """Re-queue requests (cache-invalidation / retry path)."""
        with self._lock:
            self._pending = requests + self._pending
        if self._wake is not None:
            self._wake.set()

    def get_entries_for_response(self, response: Response) -> List[TensorTableEntry]:
        """Claim (remove) the entries a Response names.

        For JOIN-substituted tensors absent from the table, the caller builds
        zero entries from the response metadata instead (reference
        ``GetTensorEntriesFromResponse`` zero-substitution,
        ``tensor_queue.h:39-41``)."""
        with self._lock:
            entries = []
            for name in response.tensor_names:
                entry = self._table.pop(name, None)
                if entry is not None:
                    entries.append(entry)
            return entries

    def peek(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._table.get(name)

    def remove(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            return self._table.pop(name, None)

    def size(self) -> int:
        with self._lock:
            return len(self._table)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._table)
