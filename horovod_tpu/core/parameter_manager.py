"""Online autotuning of fusion threshold + cycle time.

Reference: ``parameter_manager.h:88-97`` / ``parameter_manager.cc`` with
``optim/bayesian_optimization.cc`` + ``optim/gaussian_process.cc`` (Eigen):
Bayesian optimization over (tensor_fusion_threshold_mb, cycle_time_ms),
scoring observed negotiation throughput (bytes/sec), warmup-sample discard,
winning parameters broadcast from the coordinator
(``SynchronizeParameters``, ``controller.cc:43-57``).

numpy plays Eigen's role; expected improvement is maximized over a random
candidate set instead of LBFGS (the reference's GP hyperparameters are
fixed; ours too).  Coordinator-only, like the reference (scores are
computed from the coordinator's cycle observations; tuned values ride the
ResponseList).
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Tuple

import numpy as np

# Search space, matching the reference's grids
# (`parameter_manager.cc` BayesianOptimization setup).
_FUSION_MB_RANGE = (0.0, 64.0)
_CYCLE_MS_RANGE = (1.0, 25.0)

# Categorical wire-codec dimension (HOROVOD_AUTOTUNE_CODEC): the codecs
# the compression subsystem speaks (transport/compression.py), with
# "none" as the paired-comparison baseline.  The codec is tuned by
# sign-tested A/B pairs, not by the GP — a categorical knob has no
# gradient for expected improvement to climb, and the reference tunes
# its categorical knobs (hierarchical ops, cache) by category grids for
# the same reason.
_CODECS = ("none", "fp16", "bf16", "int8", "onebit")
_CODEC_ALPHA = 0.05


def _sign_test_p(wins: int, losses: int) -> float:
    """Two-sided paired sign-test p-value, numerically identical to
    ``benchmarks.ab_harness.sign_test_p`` (the PR-10 A/B gate) — kept
    local so the core package never imports the benchmark harness."""
    n = wins + losses
    if n == 0:
        return 1.0
    k = min(wins, losses)
    tail = sum(math.comb(n, i) for i in range(k + 1)) / 2.0 ** n
    return min(1.0, 2.0 * tail)


class CodecArm:
    """Paired A/B exploration of the categorical codec dimension.

    Samples alternate baseline/candidate: each even observation runs the
    baseline codec, each odd one the current candidate, and the two
    scores form one sign-test pair (ties discarded, like the harness).
    Candidates rotate round-robin so every codec keeps accruing pairs
    for as long as the tuner runs.  A candidate is recommended only on a
    significant win — strictly more wins than losses AND a two-sided
    sign-test p below alpha — otherwise the recommendation stays
    "none".  The verdict is report-only: the live wire format follows
    HOROVOD_WIRE_COMPRESSION, which all ranks must agree on, so the
    coordinator never flips it unilaterally mid-run.
    """

    def __init__(self, codecs: Tuple[str, ...] = _CODECS,
                 alpha: float = _CODEC_ALPHA):
        if len(codecs) < 2:
            raise ValueError("need a baseline plus >= 1 candidate codec")
        self.codecs = tuple(codecs)
        self.alpha = alpha
        self._candidates = self.codecs[1:]
        self._idx = 0                     # candidate being paired
        self._baseline_score: Optional[float] = None
        self._wins = {c: 0 for c in self._candidates}
        self._losses = {c: 0 for c in self._candidates}

    @property
    def under_test(self) -> str:
        """Codec the in-flight sample is (notionally) measured under."""
        if self._baseline_score is None:
            return self.codecs[0]
        return self._candidates[self._idx]

    def observe(self, score: float) -> None:
        if self._baseline_score is None:
            self._baseline_score = score
            return
        cand = self._candidates[self._idx]
        if score > self._baseline_score:
            self._wins[cand] += 1
        elif score < self._baseline_score:
            self._losses[cand] += 1
        self._baseline_score = None
        self._idx = (self._idx + 1) % len(self._candidates)

    def recommendation(self) -> Tuple[str, float]:
        """(codec, p-value) — baseline with p=1.0 unless some candidate
        clears the sign-test gate; the lowest-p significant winner
        breaks ties."""
        best, best_p = self.codecs[0], 1.0
        for cand in self._candidates:
            wins, losses = self._wins[cand], self._losses[cand]
            if wins <= losses:
                continue
            p = _sign_test_p(wins, losses)
            if p < self.alpha and p < best_p:
                best, best_p = cand, p
        return best, best_p


class GaussianProcess:
    """RBF-kernel GP regression (reference ``optim/gaussian_process.cc``)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-8):
        self.length_scale = length_scale
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._l_inv: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = x
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        l = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(l.T, np.linalg.solve(l, y))
        self._l_inv = np.linalg.inv(l)

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = self._l_inv @ ks.T
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return mu, np.sqrt(var)


class BayesianOptimization:
    """Expected-improvement acquisition over the 2-D knob space
    (reference ``optim/bayesian_optimization.cc``)."""

    def __init__(self, seed: int = 0, candidates: int = 256):
        self._rng = np.random.RandomState(seed)
        self._candidates = candidates
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []

    @staticmethod
    def _norm(p: Tuple[float, float]) -> np.ndarray:
        f = (p[0] - _FUSION_MB_RANGE[0]) / (_FUSION_MB_RANGE[1] - _FUSION_MB_RANGE[0])
        c = (p[1] - _CYCLE_MS_RANGE[0]) / (_CYCLE_MS_RANGE[1] - _CYCLE_MS_RANGE[0])
        return np.array([f, c])

    @staticmethod
    def _denorm(x: np.ndarray) -> Tuple[float, float]:
        return (
            float(x[0]) * (_FUSION_MB_RANGE[1] - _FUSION_MB_RANGE[0]) + _FUSION_MB_RANGE[0],
            float(x[1]) * (_CYCLE_MS_RANGE[1] - _CYCLE_MS_RANGE[0]) + _CYCLE_MS_RANGE[0],
        )

    def observe(self, params: Tuple[float, float], score: float) -> None:
        self._xs.append(self._norm(params))
        self._ys.append(score)

    def suggest(self) -> Tuple[float, float]:
        if len(self._xs) < 3:
            return self._denorm(self._rng.rand(2))
        x = np.stack(self._xs)
        y = np.asarray(self._ys)
        y_mean, y_std = y.mean(), max(y.std(), 1e-9)
        gp = GaussianProcess(length_scale=0.3, noise=1e-6)
        gp.fit(x, (y - y_mean) / y_std)
        cand = self._rng.rand(self._candidates, 2)
        mu, sigma = gp.predict(cand)
        best = (y.max() - y_mean) / y_std
        z = (mu - best) / sigma
        ei = sigma * (z * _phi_cdf(z) + _phi_pdf(z))
        return self._denorm(cand[int(np.argmax(ei))])


def _phi_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)


def _phi_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1 + np.vectorize(math.erf)(z / math.sqrt(2)))


class ParameterManager:
    """Coordinator-side tuning loop (reference ``ParameterManager::Update``,
    ``parameter_manager.h:88``)."""

    def __init__(self, enabled: bool = False, warmup_samples: int = 3,
                 steps_per_sample: int = 10, max_samples: int = 20,
                 initial_fusion_bytes: int = 64 * 1024 * 1024,
                 initial_cycle_ms: float = 1.0,
                 log_path: Optional[str] = None, seed: int = 0,
                 tune_codec: bool = False,
                 codec_alpha: float = _CODEC_ALPHA):
        self.enabled = enabled
        self.warmup_samples = warmup_samples
        self.steps_per_sample = steps_per_sample
        self.max_samples = max_samples
        self._fusion_bytes = initial_fusion_bytes
        self._cycle_ms = initial_cycle_ms
        self._bo = BayesianOptimization(seed=seed)
        self._samples_seen = 0
        self._step_in_sample = 0
        self._bytes_in_sample = 0
        # None until the first counted step (see update() clock notes);
        # thereafter always the previous sample's close timestamp.
        self._sample_start: Optional[float] = None
        self._best: Tuple[float, Tuple[int, float]] = (
            -1.0, (initial_fusion_bytes, initial_cycle_ms))
        self._done = False
        # Categorical codec dimension (HOROVOD_AUTOTUNE_CODEC, default
        # off): A/B sign-test pairs over _CODECS, report-only (see
        # CodecArm).  The reference's other categorical knobs
        # (hierarchical ops, cache on/off) stay structural here.
        self._codec_arm = CodecArm(alpha=codec_alpha) if tune_codec else None
        # Per-sample CSV artifact (reference HOROVOD_AUTOTUNE_LOG,
        # ``parameter_manager.h:112`` / ``.cc:81,266-272``): header naming
        # the tunables, one row per sample, and a final ``best`` row when
        # the tuner settles.  The codec column appears only when the
        # codec arm is on, so the established 4-column schema is stable
        # for every existing consumer.
        self._log = open(log_path, "w") if log_path else None
        if self._log:
            self._log.write(
                "sample,cycle_time_ms,tensor_fusion_threshold_mb,"
                "score_bytes_per_sec"
                + (",codec" if self._codec_arm else "") + "\n")
            self._log.flush()

    @property
    def fusion_threshold_bytes(self) -> int:
        return self._fusion_bytes

    @property
    def cycle_time_ms(self) -> float:
        return self._cycle_ms

    @property
    def codec_under_test(self) -> str:
        """Codec the in-flight sample is attributed to ("none" unless
        the codec arm is on)."""
        return self._codec_arm.under_test if self._codec_arm else _CODECS[0]

    @property
    def recommended_codec(self) -> str:
        """Sign-test-gated codec verdict so far: a candidate only when
        its paired wins over "none" are significant at the arm's alpha.
        Report-only — the wire format stays HOROVOD_WIRE_COMPRESSION."""
        if self._codec_arm is None:
            return _CODECS[0]
        return self._codec_arm.recommendation()[0]

    def update(self, nbytes: int) -> Optional[Tuple[int, float]]:
        """Record one negotiation cycle's reduced byte volume; returns new
        (fusion_bytes, cycle_ms) when the tuner moves, else None.

        Idle cycles (nothing reduced) do not advance the sample: the
        reference steps samples by per-tensor reduction counts
        (``parameter_manager.cc:148-159``), so only cycles that actually
        moved bytes count toward ``steps_per_sample`` — otherwise the
        background loop's empty ticks close zero-byte samples and the
        tuner optimizes noise.

        Clock discipline: a sample's clock starts when the PREVIOUS sample
        closes (the timestamp of its last counted step), so N counted
        steps are scored over N inter-step intervals.  Starting it at the
        first counted step instead would bill N steps' bytes to N-1
        intervals, inflating every score by N/(N-1) (2x at
        steps_per_sample=2).  The first sample ever has no previous close,
        so it keeps the first-counted-step start (and the residual
        one-sample bias) rather than billing the arbitrary init→training
        gap.  The flip side is accepted and uniform: a mid-run pause
        between samples (eval, checkpoint) deflates the one sample that
        follows it."""
        if not self.enabled or self._done or nbytes <= 0:
            return None
        if self._step_in_sample == 0 and self._sample_start is None:
            # Very first counted step of the run: no previous close to
            # anchor on.
            self._sample_start = time.monotonic()
        self._bytes_in_sample += nbytes
        self._step_in_sample += 1
        if self._step_in_sample < self.steps_per_sample:
            return None

        now = time.monotonic()
        elapsed = max(now - self._sample_start, 1e-6)
        score = self._bytes_in_sample / elapsed
        # This close is the NEXT sample's clock start (N steps scored over
        # N intervals — the N/(N-1) de-bias).
        self._sample_start = now
        params = (self._fusion_bytes / (1024.0 * 1024.0), self._cycle_ms)
        self._samples_seen += 1
        # Attribute the closing sample to its codec BEFORE the arm
        # observes it (observing flips the baseline/candidate phase).
        codec = self._codec_arm.under_test if self._codec_arm else None
        if self._log:
            self._log.write(f"{self._samples_seen},{params[1]:.2f},"
                            f"{params[0]:.2f},{score:.0f}"
                            + (f",{codec}" if codec else "") + "\n")
            self._log.flush()
        if self._samples_seen > self.warmup_samples:
            self._bo.observe(params, score)
            if self._codec_arm:
                self._codec_arm.observe(score)
            if score > self._best[0]:
                self._best = (score, (self._fusion_bytes, self._cycle_ms))

        if self._samples_seen >= self.max_samples + self.warmup_samples:
            # Settle on the best observed configuration.
            self._fusion_bytes, self._cycle_ms = self._best[1]
            self._done = True
            if self._log:
                # Final row mirrors the reference's LogBestParameters.
                self._log.write(
                    f"best,{self._cycle_ms:.2f},"
                    f"{self._fusion_bytes / (1024.0 * 1024.0):.2f},"
                    f"{max(self._best[0], 0):.0f}"
                    + (f",{self.recommended_codec}"
                       if self._codec_arm else "") + "\n")
                self._log.close()
                self._log = None
        else:
            fusion_mb, cycle = self._bo.suggest()
            self._fusion_bytes = int(fusion_mb * 1024 * 1024)
            self._cycle_ms = cycle

        self._step_in_sample = 0
        self._bytes_in_sample = 0
        return (self._fusion_bytes, self._cycle_ms)
