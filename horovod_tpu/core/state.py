"""Global runtime state and the background coordination loop.

Role of the reference's ``HorovodGlobalState`` + ``BackgroundThreadLoop`` /
``RunLoopOnce`` (``operations.cc:117, 361-689``) and the ``Enqueue*`` entry
points (``operations.cc:942-1170``): a singleton owning the topology, the
transport, the controller, the tensor queue and the op chains; a background
thread that wakes every cycle, runs one negotiation round, and executes the
agreed responses; framework threads enqueue named tensors with callbacks and
never touch the network.

The process model is one Python process per Horovod rank (per host or per
chip), exactly like ``horovodrun``'s worker processes — the background thread
here is the analog of the reference's C++ background thread, and the
GIL-free sections (socket I/O, numpy kernels) are where the real work
happens.
"""

from __future__ import annotations

import atexit
import queue
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..backend import cpu_ring
from ..common import env as env_mod
from ..common import faults
from ..common.exceptions import (
    CoordinatedAbortError,
    HorovodInternalError,
    PeerGoneError,
)
from ..common.logging_util import get_logger
from ..common.topology import ProcessTopology, from_env
from ..transport.select import build_link_mesh
from ..transport.store import HTTPStoreClient, MemoryStore, Store
from ..transport.tcp import TcpMesh
from . import flight_recorder, metrics
from . import timeline as timeline_mod
from .controller import BARRIER_TENSOR_NAME, JOIN_TENSOR_NAME, Controller
from .messages import (
    DataType,
    Request,
    RequestType,
    Response,
    ResponseType,
)
from .operation_manager import OperationManager
from .tensor_queue import Status, TensorQueue, TensorTableEntry

log = get_logger("horovod_tpu.state")


class HorovodGlobalState:
    def __init__(self):
        self.topo: Optional[ProcessTopology] = None
        self.mesh: Optional[TcpMesh] = None
        self.controller: Optional[Controller] = None
        self.tensor_queue = TensorQueue()
        self.op_manager = OperationManager()
        self.initialized = threading.Event()
        self.shutdown_requested = threading.Event()
        self.shutdown_complete = threading.Event()
        self.joined = False
        self.join_event: Optional[threading.Event] = None
        self.cycle_time_ms = env_mod.DEFAULT_CYCLE_TIME_MS
        self.background: Optional[threading.Thread] = None
        self.init_error: Optional[BaseException] = None
        # Adaptive cycle timing: enqueues set this event so an idle loop
        # wakes immediately instead of sleeping out the cycle; busy cycles
        # skip the sleep entirely (spin-then-park — the cycle_time_ms knob,
        # autotuned by the ParameterManager, becomes the IDLE backstop
        # rather than a floor under every dispatch's latency).
        self._wake = threading.Event()
        self._last_cycle_had_work = False
        # Pipelined negotiate/dispatch (double-buffered background loop):
        # device-plane responses are handed to a dedicated dispatcher
        # thread so cycle i+1's negotiation overlaps cycle i's XLA dispatch
        # host work.  Host-TCP responses still execute inline (they share
        # the mesh sockets with negotiation; interleaving would cross
        # frames) after a drain barrier, preserving the identical-order
        # dispatch invariant on every rank.
        self._dispatch_queue: Optional[queue.SimpleQueue] = None
        self._dispatch_thread: Optional[threading.Thread] = None
        self._dispatch_inflight = 0
        self._dispatch_cv = threading.Condition()
        self.pipeline_dispatch = True
        self.timeline = None  # attached by core.timeline when enabled
        self.parameter_manager = None  # attached when autotune enabled
        self.cycle_count = 0
        # Finalizer pool (reference gpu_operations.h:98-127 finalizer
        # threads, one per stream via ThreadPool operations.cc:421):
        # completes async device collectives so the negotiation loop never
        # blocks; HOROVOD_NUM_FINALIZER_THREADS (NUM_NCCL_STREAMS analog)
        # lets multiple in-flight fused batches finalize concurrently.
        self._finalizer_pool = None
        # Sticky failure from the eager-complete watchdog (NCCL
        # async-error-watchdog role): raised by the next enqueue.
        self.async_error: Optional[str] = None

    # ------------------------------------------------------------------

    def initialize(self, store: Optional[Store] = None,
                   topology: Optional[ProcessTopology] = None) -> None:
        """``InitializeHorovodOnce`` analog (``operations.cc:693-739``):
        spawn the background thread, block until transport + controller are
        up."""
        if self.initialized.is_set():
            return
        self.async_error = None
        self.topo = topology or from_env()
        self._store = store
        self.cycle_time_ms = env_mod.get_float(
            env_mod.HOROVOD_CYCLE_TIME, env_mod.DEFAULT_CYCLE_TIME_MS)
        # Pipelining pays only when there is negotiation latency to hide;
        # at size 1 it would just add a thread hop per dispatch.
        self.pipeline_dispatch = self.topo.size > 1 and env_mod.get_bool(
            env_mod.HOROVOD_PIPELINE_DISPATCH, True)
        self.tensor_queue.set_wake_event(self._wake)
        self.background = threading.Thread(
            target=self._background_loop, name="horovod-background", daemon=True)
        self.background.start()
        self.initialized.wait()
        if self.init_error is not None:
            # Leave the object retryable: the background thread is dead and
            # nothing must look initialized.
            err, self.init_error = self.init_error, None
            self.initialized.clear()
            self.background = None
            raise HorovodInternalError(f"initialization failed: {err}") from err
        atexit.register(self.shutdown)

    def _build_transport(self) -> None:
        topo = self.topo
        from ..backend import xla as xla_backend

        if xla_backend.data_plane_requested() in ("xla", "auto") \
                and topo.size > 1:
            # jax.distributed must already be up (frameworks.jax.basics
            # initializes it before starting this thread).
            xla_backend.context().initialize(topo)
        else:
            xla_backend.context().reset()
        startup_timeout = env_mod.get_float(
            env_mod.HOROVOD_MESH_STARTUP_TIMEOUT, 60.0)
        epoch = env_mod.get_epoch()
        store = None
        if topo.size == 1:
            self.mesh = None
        else:
            store = self._store
            if store is None:
                addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
                port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
                if not addr or not port:
                    raise HorovodInternalError(
                        "size > 1 requires a rendezvous store "
                        "(HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT, set by the launcher)")
                store = HTTPStoreClient(addr, port)
            # Epoch-scoped keys so elastic re-init never reads stale peer
            # addresses from a previous incarnation of the job.
            # Check-in mark for the launcher's --start-timeout watchdog
            # (reference: workers surface through the rendezvous server and
            # horovodrun aborts if they don't within the timeout).
            store.set("worker_started", str(topo.rank), b"1")
            # Per-link transport selection (transport/select.py): shm for
            # intra-host links, TCP cross-host, per HOROVOD_TRANSPORT.
            # Under the "tcp" policy this IS a plain TcpMesh.
            self.mesh = build_link_mesh(
                topo, store, epoch=epoch, timeout=startup_timeout)
        fusion = env_mod.get_int(
            env_mod.HOROVOD_FUSION_THRESHOLD, env_mod.DEFAULT_FUSION_THRESHOLD)
        stall_secs = 0 if env_mod.get_bool(env_mod.HOROVOD_STALL_CHECK_DISABLE) \
            else env_mod.get_float(env_mod.HOROVOD_STALL_CHECK_TIME_SECONDS,
                                   env_mod.DEFAULT_STALL_CHECK_TIME_SECONDS)
        if env_mod.get_bool(env_mod.HOROVOD_AUTOTUNE) and topo.rank == 0:
            from .parameter_manager import ParameterManager

            self.parameter_manager = ParameterManager(
                enabled=True,
                warmup_samples=env_mod.get_int(
                    env_mod.HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
                steps_per_sample=env_mod.get_int(
                    env_mod.HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10),
                initial_fusion_bytes=fusion,
                initial_cycle_ms=self.cycle_time_ms,
                log_path=env_mod.get_str(env_mod.HOROVOD_AUTOTUNE_LOG) or None,
                tune_codec=env_mod.get_bool(env_mod.HOROVOD_AUTOTUNE_CODEC))
        self.controller = Controller(
            topo, self.mesh,
            fusion_threshold_bytes=fusion,
            stall_warning_secs=stall_secs,
            stall_shutdown_secs=env_mod.get_float(
                env_mod.HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            cache_capacity=env_mod.get_int(env_mod.HOROVOD_CACHE_CAPACITY,
                                           env_mod.DEFAULT_CACHE_CAPACITY),
            parameter_manager=self.parameter_manager)
        # Resolved store (caller-provided OR the HTTP fallback built
        # above) kept for teardown-path writes: the stale-aggregator
        # veto must land BEFORE the abort broadcast tears the job down.
        self._active_store = store
        if store is not None:
            self._sync_controller_topology(store, epoch, startup_timeout)
        timeline_path = env_mod.get_str(env_mod.HOROVOD_TIMELINE)
        if timeline_path:
            # EVERY rank writes a trace (pid = rank; rank 0 keeps the
            # configured path, others get <path>.rankN) so
            # tools/trace_merge.py can build the one cross-rank view; the
            # coordinator-side negotiation lanes still exist only on rank
            # 0 (the message table lives there, reference
            # operations.cc:424-432).
            from .timeline import (
                Timeline,
                estimate_server_clock_offset_ns,
                rank_trace_path,
            )

            self.timeline = Timeline(
                rank_trace_path(timeline_path, topo.rank),
                mark_cycles=env_mod.get_bool(
                    env_mod.HOROVOD_TIMELINE_MARK_CYCLES),
                rank=topo.rank,
                clock_offset_ns=estimate_server_clock_offset_ns())
            if topo.rank == 0:
                self.controller.timeline = self.timeline
        metrics.registry.register_view("controller",
                                       self._controller_metrics_view)
        if store is not None:
            self._start_metrics_pusher(store)
        self._register_default_ops()

    def _sync_controller_topology(self, store, epoch: int,
                                  timeout: float) -> None:
        """Publish rank 0's negotiated controller fan-out through the
        rendezvous store and validate every worker against it.

        The star/tree choice is derived per-rank from
        ``HOROVOD_CONTROLLER_TOPOLOGY``; a multi-host launch with partial
        env propagation could give ranks different answers, and a
        star-vs-tree mismatch deadlocks the first negotiation round with no
        diagnostic (each side recv-blocks on a peer that will never send).
        Making rank 0's choice authoritative-and-checked turns that silent
        hang into a loud bring-up error naming the env fix.

        The negotiation fan-in decision (docs/data_plane.md "Negotiation
        fan-in") rides the same scope: rank 0 resolves the mode, folds in
        any still-cooling stale-aggregator vetoes, and publishes
        ``{"mode": ..., "vetoed": [host indices]}``; workers ADOPT the
        record (no per-rank comparison — the record plus the shared
        topology numbers determine every role arithmetically), then each
        rank installs its FaninPlan before the first cycle.  Mid-epoch
        installs are impossible by construction: the lockstep recv sets
        must agree from cycle one."""
        import json

        from . import negotiation_fanin as fanin_mod

        scope = f"controller.{epoch}"
        chosen = self.controller.fanout_topology
        if self.topo.rank == 0:
            mode = fanin_mod.resolve_mode(self.topo)
            vetoed = self._read_fanin_vetoes(store, epoch) \
                if mode == "on" else []
            decision = {"mode": mode, "vetoed": vetoed}
            store.batch([
                ("set", scope, "topology", chosen.encode()),
                ("set", scope, "fanin", json.dumps(decision).encode()),
            ])
        else:
            try:
                got = store.wait(scope, ["topology", "fanin"],
                                 timeout=timeout)
                agreed = got["topology"].decode()
                decision = json.loads(got["fanin"].decode())
            except Exception as e:  # noqa: BLE001
                raise HorovodInternalError(
                    f"rank {self.topo.rank} could not read rank 0's "
                    f"controller topology/fan-in decision from the "
                    f"rendezvous store: {e}") from e
            if agreed != chosen:
                raise HorovodInternalError(
                    f"controller topology mismatch: rank 0 negotiates over "
                    f"{agreed!r} but rank {self.topo.rank} derived "
                    f"{chosen!r} from its environment — "
                    f"HOROVOD_CONTROLLER_TOPOLOGY (or world size) differs "
                    f"across ranks; propagate the same value to every host "
                    f"(a star/tree mismatch would deadlock the first "
                    f"negotiation round)")
        self._configure_negotiation_fanin(decision, store)

    def _read_fanin_vetoes(self, store, epoch: int) -> List[int]:
        """Cross-rank indices of hosts under an active stale-aggregator
        veto (rank 0 only).  Best-effort end to end — a veto is an
        optimization hint (keep a convicted host off the tree), never a
        correctness dependency, so store trouble or an unresolvable
        hostname silently yields no veto."""
        import json

        from ..transport.scopes import (
            NEGOTIATION_VETO_SCOPE,
            RANK_AND_SIZE_SCOPE,
        )
        from .negotiation_fanin import active_vetoes

        try:
            names = store.keys(NEGOTIATION_VETO_SCOPE)
            if not names:
                return []
            records = {}
            for name in names:
                raw = store.get(NEGOTIATION_VETO_SCOPE, name)
                if raw is not None:
                    records[name] = json.loads(bytes(raw).decode())
            hostnames = active_vetoes(records, epoch)
            if not hostnames:
                return []
            # hostname → host index via the driver's slot table
            # (identities are ``hostname:local_rank`` keys).
            vetoed = set()
            for key in store.keys(RANK_AND_SIZE_SCOPE):
                hostname = key.rsplit(":", 1)[0]
                if hostname not in hostnames:
                    continue
                raw = store.get(RANK_AND_SIZE_SCOPE, key)
                if raw is None:
                    continue
                slot = json.loads(bytes(raw).decode())
                if slot.get("epoch", 0) != epoch or slot.get("rank", -1) < 0:
                    continue
                vetoed.add(int(slot["rank"]) // self.topo.local_size)
            if vetoed:
                log.info("negotiation fan-in: hosts %s run DIRECT this "
                         "epoch (stale-aggregator veto cooldown)",
                         sorted(vetoed))
            return sorted(vetoed)
        except Exception as e:  # noqa: BLE001 — hint, not load-bearing
            log.warning("negotiation fan-in veto read failed (%s); "
                        "no hosts vetoed", e)
            return []

    def _configure_negotiation_fanin(self, decision, store) -> None:
        from . import negotiation_fanin as fanin_mod

        if not decision or decision.get("mode") != "on":
            self.controller.configure_fanin(None)
            return
        plan = fanin_mod.build_plan(self.topo,
                                    decision.get("vetoed") or ())
        job_key = getattr(store, "_base", None) or "in-process"
        heartbeat = fanin_mod.make_heartbeat(plan, self.topo, str(job_key))
        self.controller.configure_fanin(plan, heartbeat)

    def _write_fanin_veto(self, error: BaseException) -> None:
        """Best-effort veto on the way down: a member that convicted its
        aggregator as wedged (AggregatorStaleError) records the verdict
        in the store BEFORE the abort broadcast, so the recovered epoch's
        rank 0 keeps this host on the direct path for the cooldown
        window.  Every failure here is swallowed — the abort must
        proceed, and a lost veto only means the next epoch re-trees (and
        re-convicts within ~1.5 heartbeat periods if still wedged)."""
        from ..common.exceptions import AggregatorStaleError

        if not isinstance(error, AggregatorStaleError):
            return
        store = getattr(self, "_active_store", None)
        if store is None:
            return
        import json

        from ..transport.scopes import NEGOTIATION_VETO_SCOPE

        hostname = env_mod.get_str(env_mod.HOROVOD_HOSTNAME) \
            or f"host-{self.topo.cross_rank}"
        try:
            store.set(NEGOTIATION_VETO_SCOPE, hostname, json.dumps({
                "epoch": env_mod.get_epoch(),
                "aggregator_rank": error.aggregator_rank,
                "reason": str(error)[:300],
            }).encode())
            log.warning("negotiation fan-in veto posted for host %s "
                        "(aggregator rank %d convicted as wedged)",
                        hostname, error.aggregator_rank)
        except Exception as e:  # noqa: BLE001 — teardown must proceed
            log.warning("negotiation fan-in veto write failed: %s", e)

    def _controller_metrics_view(self) -> dict:
        """Metrics-registry view over the controller's fast-path counters
        (registered at init; re-registration on elastic re-init replaces
        the stale closure).  Runs only at snapshot time — the negotiation
        hot path pays nothing for these."""
        c = self.controller
        if c is None:
            return {}
        cycles = max(1, self.cycle_count)
        fast = c.fast_cycle_count + c.idle_fast_cycle_count
        counters = {
            "controller_cycles_total": self.cycle_count,
            "controller_fast_cycles_total": c.fast_cycle_count,
            "controller_idle_fast_cycles_total": c.idle_fast_cycle_count,
            "controller_serialized_requests_total":
                c.serialized_request_count,
            # Negotiation fan-in instrumentation (plain controller ints,
            # folded here so the per-cycle hot path never touches the
            # registry).  Ingress counters exist on every rank but only
            # the coordinator's move; exporting them everywhere keeps the
            # view shape uniform for the aggregating scrape.
            metrics.flat("negotiation_fanin_frames_total", path="tree"):
                c.fanin_tree_frame_count,
            metrics.flat("negotiation_fanin_frames_total", path="direct"):
                c.fanin_direct_frame_count,
            "negotiation_fanin_fallbacks_total": c.fanin_fallback_count,
            "controller_ingress_frames_total": c.ingress_frame_count,
            "controller_ingress_bytes_total": c.ingress_byte_count,
        }
        return {
            "counters": counters,
            "gauges": {"controller_fast_cycle_ratio": fast / cycles},
        }

    def _start_metrics_pusher(self, store) -> None:
        """Periodically push this rank's metrics snapshot to the
        rendezvous KV (``PUT /metrics/rank-N``) so the server's
        ``GET /metrics`` can serve a cross-rank aggregate of a LIVE job,
        and renew this identity's liveness lease on the same cadence
        (``PUT /lease/<identity>`` — the elastic driver's dead-vs-
        partitioned signal, docs/control_plane.md).  The snapshot+lease
        pair rides one batched transaction; with host fan-in enabled
        (``elastic/fanin.py``) colocated ranks hand their pair to the
        host aggregator instead, so the store sees one request per HOST
        per period.  0 disables."""
        period = env_mod.get_float(env_mod.HOROVOD_METRICS_PUSH_SECS,
                                   env_mod.DEFAULT_METRICS_PUSH_SECS)
        if period <= 0 or not metrics.ENABLED:
            return
        import json as json_mod

        from ..elastic import fanin as fanin_mod
        from ..elastic.rendezvous_client import lease_renew_ops

        fanin = fanin_mod.maybe_create(store, period)

        rank = self.topo.rank
        done = self.shutdown_complete
        identity = (
            f"{env_mod.get_str(env_mod.HOROVOD_HOSTNAME) or 'localhost'}:"
            f"{env_mod.get_int(env_mod.HOROVOD_LOCAL_RANK, 0)}")
        # Store-outage state machine: pushes are best-effort.  Each
        # attempt rebuilds the snapshot (so the NEWEST one is what lands
        # when the store returns — nothing stale is ever replayed), we
        # log once per outage instead of once per period, and the blind
        # window is accumulated into counters the first post-outage
        # snapshot carries out.  Boxed floats: closure-mutable state.
        outage_since = [None]   # monotonic start of the current outage
        counted_upto = [0.0]    # outage seconds already accounted
        renewals = [0]          # lease value must CHANGE every renewal

        def _push() -> None:
            renewals[0] += 1
            snap = metrics.registry.snapshot()
            snap["rank"] = rank
            # Epoch-stamped so the scrape can drop snapshots from
            # ranks that left at an elastic re-rendezvous (their last
            # push would otherwise be served forever).
            snap["epoch"] = env_mod.get_epoch()
            ops = lease_renew_ops(identity, rank, env_mod.get_epoch(),
                                  renewals[0],
                                  json_mod.dumps(snap).encode())
            try:
                # Fan-in first: True means the ops were delivered (or
                # spooled under a live host aggregator); False means no
                # aggregator is alive — push directly, same as before.
                if fanin is None or not fanin.submit(ops):
                    store.batch(ops)
            except Exception as e:  # noqa: BLE001 — a scrape/lease gap
                # must never hurt the job; the store may be restarting.
                now = time.monotonic()
                metrics.inc("lease_renew_failures_total")
                if outage_since[0] is None:
                    outage_since[0] = now
                    log.warning(
                        "rendezvous store unreachable (%s); metrics/lease "
                        "pushes degrade to best-effort until it returns", e)
                else:
                    metrics.inc("store_outage_seconds_total",
                                now - counted_upto[0])
                counted_upto[0] = now
                return
            if outage_since[0] is not None:
                now = time.monotonic()
                metrics.inc("store_outage_seconds_total",
                            now - counted_upto[0])
                log.info("rendezvous store reachable again after %.1fs; "
                         "resuming normal pushes",
                         now - outage_since[0])
                outage_since[0] = None

        def _push_loop() -> None:
            _push()
            while not done.wait(period):
                _push()
            _push()  # final snapshot so short jobs still land one

        threading.Thread(target=_push_loop,
                         name=f"hvd-metrics-push-r{rank}",
                         daemon=True).start()

    def _register_default_ops(self) -> None:
        topo, mesh = self.topo, self.mesh
        self.op_manager = OperationManager()
        # One persistent staging arena shared by every host-side op
        # (reference: one FusionBufferManager in HorovodGlobalState).
        self.fusion_buffers = cpu_ring.FusionBufferManager()
        fbm = self.fusion_buffers
        # XLA device ops lead each chain (reference registration order,
        # operations.cc:145-252: most-specialized backend first); their
        # enabled() checks the negotiated device set, so every rank makes
        # the same choice.
        from ..backend import xla as xla_backend

        self.op_manager.register(
            ResponseType.ALLREDUCE, xla_backend.XlaAllreduce(topo))
        self.op_manager.register(
            ResponseType.ALLGATHER, xla_backend.XlaAllgather(topo))
        self.op_manager.register(
            ResponseType.BROADCAST, xla_backend.XlaBroadcast(topo))
        self.op_manager.register(
            ResponseType.ALLTOALL, xla_backend.XlaAlltoall(topo))
        # Hierarchical ahead of the flat ring (reference chain order,
        # operations.cc:145-252: NCCL-hierarchical before NCCL); applicable()
        # is pure topology, so every rank registers identically.
        if cpu_ring.HierarchicalAllreduce.applicable(topo):
            self.op_manager.register(
                ResponseType.ALLREDUCE,
                cpu_ring.HierarchicalAllreduce(topo, mesh, fbm))
        self.op_manager.register(
            ResponseType.ALLREDUCE, cpu_ring.RingAllreduce(topo, mesh, fbm))
        self.op_manager.register(
            ResponseType.ALLGATHER, cpu_ring.RingAllgather(topo, mesh, fbm))
        self.op_manager.register(
            ResponseType.BROADCAST, cpu_ring.TreeBroadcast(topo, mesh))
        self.op_manager.register(
            ResponseType.ALLTOALL, cpu_ring.PairwiseAlltoall(topo, mesh))
        from ..backend.adasum import AdasumAllreduce, AdasumRingFallback

        # Device VHDD ahead of the host backends (like the reference's
        # AdasumGpu ahead of AdasumMPI, operations.cc registration order).
        self.op_manager.register(
            ResponseType.ADASUM, xla_backend.XlaAdasum(topo))
        self.op_manager.register(
            ResponseType.ADASUM, AdasumAllreduce(topo, mesh, fbm))
        # Non-power-of-two worlds fall back to an averaging ring allreduce
        # (the reference simply rejects them; averaging approximates
        # Adasum's identical-gradient behavior and keeps hvd.Adasum usable).
        self.op_manager.register(
            ResponseType.ADASUM, AdasumRingFallback(topo, mesh, fbm))

    # ------------------------------------------------------------------
    # background loop
    # ------------------------------------------------------------------

    def _background_loop(self) -> None:
        try:
            self._build_transport()
        except BaseException as e:  # noqa: BLE001
            self.init_error = e
            self.initialized.set()
            return
        self.initialized.set()

        try:
            while True:
                start = time.monotonic()
                # Clear BEFORE popping: an add landing between pop and a
                # clear-afterwards would lose its wakeup.
                self._wake.clear()
                if not self._run_loop_once():
                    break
                if self._last_cycle_had_work:
                    # Spin: a busy cycle usually has an immediate follow-up
                    # (the next microbatch, unfused stragglers) — skip the
                    # sleep and negotiate again at once.  The blocking TCP
                    # recv provides the backstop: an eager rank parks in
                    # the kernel waiting for its peers, it does not burn
                    # CPU.
                    continue
                # Idle: park on the wake event with the (autotuned) cycle
                # time as the backstop, so an enqueue starts the next
                # negotiation immediately instead of after the residue of
                # a fixed sleep.
                cycle = self.cycle_time_ms / 1000.0
                elapsed = time.monotonic() - start
                if elapsed < cycle:
                    self._wake.wait(cycle - elapsed)
        except BaseException as e:  # noqa: BLE001
            log.error("background loop died: %s", e, exc_info=True)
            # Sticky failure (NCCL async-watchdog role): the NEXT enqueue on
            # this rank raises the same error a synchronous failure would,
            # so the elastic run_fn retry loop picks it up identically.
            if self.async_error is None:
                self.async_error = str(e)
            self._write_fanin_veto(e)
            self._broadcast_abort(e)
            self._dump_flight_recorder(e)
            self._stop_dispatcher()
            self._fail_all_pending(str(e))
        else:
            # Clean shutdown must also unblock waiters: entries that never
            # negotiated get SHUT_DOWN_ERROR-style callbacks, like the
            # reference draining the tensor table on shutdown.
            self._stop_dispatcher()
            self._fail_all_pending("Horovod has been shut down")
        finally:
            if self._finalizer_pool is not None:
                # In-flight device work must complete (and fire callbacks)
                # before shutdown is declared done.
                self._finalizer_pool.shutdown(timeout=60)
            if self.mesh is not None:
                self.mesh.close()
            if self.timeline is not None:
                self.timeline.close()
            self.shutdown_complete.set()

    def _dump_flight_recorder(self, error: BaseException) -> None:
        """Loop-death post-mortem: dump the flight-recorder ring + metrics
        snapshot (+ held locks under lockdep) to the per-rank JSON.  Runs
        after the abort broadcast — peers must hear the abort within one
        poll quantum; the dump is for the human who arrives later."""
        try:
            path = flight_recorder.recorder.dump(
                f"background loop death: {type(error).__name__}: {error}")
            if path:
                log.error("flight-recorder post-mortem written to %s", path)
        except Exception as e:  # noqa: BLE001 — diagnostics must never
            # mask the error being diagnosed
            log.warning("flight-recorder dump failed: %s", e)

    def _broadcast_abort(self, error: BaseException) -> None:
        """Coordinated abort: tell every surviving peer WHY this rank's
        loop died so they fail loudly with the original reason instead of
        hanging (or timing out) on a silent mesh.  A received
        CoordinatedAbortError is re-broadcast too — that is what propagates
        an abort through tree-mode relays — but with the ORIGIN's identity
        preserved; receivers already aborted ignore duplicates via their
        mesh abort flag."""
        if self.mesh is None:
            return
        try:
            if isinstance(error, CoordinatedAbortError):
                self.mesh.send_abort(error.reason, epoch=error.epoch,
                                     origin_rank=error.origin_rank)
            else:
                self.mesh.send_abort(
                    f"rank {self.topo.rank}: {error}")
        except Exception as e:  # noqa: BLE001 — teardown must proceed
            log.warning("abort broadcast failed: %s", e)

    def _run_loop_once(self) -> bool:
        """One cycle (``RunLoopOnce``, ``operations.cc:595-689``): negotiate,
        then execute every agreed response. Returns False to stop.

        Device-plane responses are handed to the dispatcher thread so this
        loop can start negotiating the next cycle while cycle i's XLA
        dispatch host work runs — the double-buffered schedule.  Everything
        else (host-TCP collectives, which share the mesh with negotiation;
        JOIN/ERROR/BARRIER bookkeeping) executes inline behind a drain
        barrier so the cross-rank execution order stays identical."""
        from .timeline import phase_stats

        requests = self.tensor_queue.pop_messages()
        t0 = time.monotonic()
        if self.timeline is not None:
            # Tag this round's spans with the lockstep cycle id BEFORE
            # negotiating — the same id names the same global round on
            # every rank (trace_merge matches lanes on it).
            self.timeline.set_cycle(self.cycle_count + 1)
        response_list = self.controller.compute_response_list(
            requests, self.shutdown_requested.is_set())
        self.cycle_count += 1
        self._last_cycle_had_work = bool(requests) \
            or bool(response_list.responses)
        metrics.set_gauge("tensor_queue_depth", self.tensor_queue.size())
        if self._last_cycle_had_work:
            # Busy cycles only: timing idle lockstep parks would swamp the
            # negotiate lane with waiting, not negotiating.
            dt = time.monotonic() - t0
            phase_stats.add("negotiate", dt)
            metrics.observe("controller_cycle_seconds", dt)
            flight_recorder.record("cycle", n=self.cycle_count,
                                   requests=len(requests),
                                   responses=len(response_list.responses))
        if response_list.tuned_params is not None:
            # Autotuner moved (reference SynchronizeParameters): adopt the
            # broadcast cycle time on every rank.
            self.cycle_time_ms = response_list.tuned_params[1]
        if self.timeline is not None:
            self.timeline.mark_cycle()
        for response in response_list.responses:
            # The cycle this response was negotiated in (pipelined device
            # dispatches execute under the NEXT cycle's negotiation, so
            # the timeline/metrics must not read the live counter).
            response._cycle = self.cycle_count
            if self.pipeline_dispatch and self._device_plane_response(response):
                self._dispatch_async(response)
            else:
                self._dispatch_drain()
                self._perform_operation(response)
        if response_list.shutdown:
            return False
        return True

    def _device_plane_response(self, response: Response) -> bool:
        """True when this response will execute on the XLA device plane
        (safe to dispatch from the pipeline thread: it never touches the
        TCP mesh the negotiation loop is using).  Mirrors the op chain's
        enabled() preconditions; any response this misjudges simply takes
        the inline path after a drain — correctness is unaffected, only
        overlap."""
        from ..backend import xla as xla_backend

        if response.response_type not in (
                ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                ResponseType.BROADCAST, ResponseType.ALLTOALL,
                ResponseType.ADASUM):
            return False
        if response.devices != [xla_backend.XLA_DEVICE_ID]:
            return False
        if not xla_backend.context().ready:
            return False
        if self.joined:
            # Zero-substituted entries are host buffers; the op chain will
            # fall back to the TCP ring on this rank.
            return False
        if response.response_type == ResponseType.ADASUM:
            p = self.topo.size
            if p & (p - 1):
                return False  # XlaAdasum needs a power-of-two world
        return True

    # -- pipelined dispatcher -------------------------------------------

    def _dispatch_async(self, response: Response) -> None:
        if self._dispatch_thread is None or not self._dispatch_thread.is_alive():
            self._dispatch_queue = queue.SimpleQueue()
            self._dispatch_thread = threading.Thread(
                target=self._dispatch_loop, name="horovod-dispatch",
                daemon=True)
            self._dispatch_thread.start()
        with self._dispatch_cv:
            self._dispatch_inflight += 1
        self._dispatch_queue.put(response)

    def _dispatch_loop(self) -> None:
        while True:
            response = self._dispatch_queue.get()
            if response is None:
                return
            try:
                self._perform_operation(response, require_device=True)
            except BaseException as e:  # noqa: BLE001 — the negotiation
                # loop must survive a dispatch failure; entries' callbacks
                # already fired with an error inside _perform_operation for
                # op-level faults, so anything reaching here is
                # infrastructure — surface it like an async device error.
                log.error("pipelined dispatch failed: %s", e, exc_info=True)
                self.async_error = f"pipelined dispatch failed: {e}"
            finally:
                with self._dispatch_cv:
                    self._dispatch_inflight -= 1
                    if self._dispatch_inflight == 0:
                        self._dispatch_cv.notify_all()

    def _dispatch_drain(self, timeout: float = 300.0,
                        must_drain: bool = True) -> None:
        """Barrier: wait until every queued device dispatch has been issued
        (NOT until the device finished — completion stays with the
        finalizer).  Precedes any inline execution so the per-rank
        dispatch order stays the negotiated order.

        A drain timeout with ``must_drain`` RAISES: proceeding would run a
        host op out of order against a still-queued device dispatch and
        silently desync the cross-rank dispatch sequence — a loud loop
        failure (which fails every pending entry) is strictly better."""
        with self._dispatch_cv:
            drained = self._dispatch_cv.wait_for(
                lambda: self._dispatch_inflight == 0, timeout=timeout)
        if not drained and must_drain:
            raise HorovodInternalError(
                f"pipelined dispatch did not drain within {timeout:.0f}s "
                f"({self._dispatch_inflight} responses still in flight); "
                "refusing to execute a host op out of dispatch order")

    def _stop_dispatcher(self) -> None:
        # Shutdown path: a wedged dispatch must not mask the original
        # failure — log and move on rather than raise.
        try:
            self._dispatch_drain(timeout=60.0)
        except HorovodInternalError as e:
            log.error("dispatcher did not drain at shutdown: %s", e)
        if self._dispatch_thread is not None \
                and self._dispatch_thread.is_alive():
            self._dispatch_queue.put(None)
            self._dispatch_thread.join(timeout=10)
        self._dispatch_thread = None

    def _perform_operation(self, response: Response,
                           require_device: bool = False) -> None:
        """``PerformOperation`` analog (``operations.cc:256-336``).

        ``require_device`` is set on the pipelined-dispatch path: a
        response routed there must execute on the XLA plane — running a
        host-TCP op from the dispatcher thread would interleave frames
        with the concurrent negotiation on the same mesh sockets, so a
        mis-route fails the entries cleanly instead of executing."""
        if faults.ACTIVE:
            faults.inject("dispatch.collective",
                          rank=self.topo.rank if self.topo else None)
        if response.response_type == ResponseType.JOIN:
            self.joined = False
            if self.join_event is not None:
                self.join_event.set()
                self.join_event = None
            return

        entries = self.tensor_queue.get_entries_for_response(response)

        # Lifecycle spans: close each tensor's LC_SUBMITTED (opened at
        # enqueue) and stamp the cycle-tagged LC_NEGOTIATED instant.
        # Zero-substituted entries (built below) never enqueued, so they
        # correctly get neither.
        if timeline_mod.ACTIVE is not None and timeline_mod.LIFECYCLE_ENABLED:
            cyc = getattr(response, "_cycle", None)
            for e in entries:
                timeline_mod.lifecycle_end(e.tensor_name, "LC_SUBMITTED")
                timeline_mod.lifecycle_instant(e.tensor_name, "LC_NEGOTIATED",
                                               cycle=cyc)

        if response.response_type == ResponseType.ERROR:
            for e in entries:
                e.callback(Status.error(response.error_message), e)
            return

        if response.response_type == ResponseType.BARRIER:
            for e in entries:
                e.callback(Status.OK(), e)
            return

        # Zero-substitution: a joined rank executes collectives it never
        # submitted, contributing zeros (reference tensor_queue.h:39-41).
        if len(entries) != len(response.tensor_names):
            by_name = {e.tensor_name: e for e in entries}
            aligned: List[TensorTableEntry] = []
            for i, name in enumerate(response.tensor_names):
                if name in by_name:
                    aligned.append(by_name[name])
                else:
                    n = response.tensor_sizes[i] if i < len(response.tensor_sizes) else 0
                    aligned.append(cpu_ring.zero_entry_for(response, i, 0, n))
            entries = aligned

        if require_device:
            from ..backend.xla import XlaOp

            op = self.op_manager.select(response, entries)
            if not isinstance(op, XlaOp):
                for e in entries:
                    self._fire_callback(e, Status.error(
                        "pipelined dispatch expected a device-plane op for "
                        f"{response.response_type.name} but the chain "
                        f"selected {type(op).__name__}; host ops cannot run "
                        "concurrently with negotiation"))
                return
        if self.timeline is not None:
            self.timeline.op_start(response, entries)
        t_op = time.monotonic()
        try:
            status = self.op_manager.execute(response, entries)
        except (PeerGoneError, CoordinatedAbortError) as e:
            # A dead mesh is FATAL, not an entry-level error: if this rank
            # kept cycling, its next negotiation frames would be consumed
            # by peers still blocked mid-collective on the same sockets —
            # positional framing desyncs and survivors read control bytes
            # as tensor data.  Fail THIS response's entries first (they
            # were already popped from the tensor queue, so the loop-death
            # _fail_all_pending sweep cannot see them — skipping this
            # strands their waiters), then re-raise so the background loop
            # dies, broadcasts the coordinated abort, and fails everything
            # still queued.
            for en in entries:
                self._fire_callback(en, Status.error(str(e)))
            raise
        except HorovodInternalError as e:
            status = Status.error(str(e))
        except Exception as e:  # noqa: BLE001
            log.error("op execution failed: %s", e, exc_info=True)
            status = Status.error(f"{type(e).__name__}: {e}")
        if self.timeline is not None:
            # For async (pending) ops this marks dispatch end; completion
            # happens on the finalizer thread.
            self.timeline.op_end(response, entries)
        if status.ok:
            self._record_collective_latency(response,
                                            time.monotonic() - t_op)
        if status.pending:
            # Async device work dispatched: a finalizer-pool worker waits
            # for readiness, so this loop moves straight on to the next
            # negotiation cycle.  In eager_complete mode (XLA plane:
            # outputs are immutable jax futures) the callbacks fire NOW
            # with unready arrays — downstream jax work chains on array
            # readiness without a host round trip — and the finalizer
            # degrades to a failure watchdog (sticky error surfaced on the
            # next enqueue, the NCCL async-watchdog design).
            if self._finalizer_pool is None:
                from .thread_pool import ThreadPool

                self._finalizer_pool = ThreadPool(
                    env_mod.get_int(env_mod.HOROVOD_NUM_FINALIZER_THREADS, 1),
                    name="horovod-finalizer")
            if status.eager_complete:
                for e in entries:
                    self._fire_callback(e, Status.OK())
                self._finalizer_pool.execute(
                    lambda ents=entries: self._watch_entries(ents))
            else:
                self._finalizer_pool.execute(
                    lambda ents=entries: self._finalize_entries(ents))
            return
        for e in entries:
            timeline_mod.lifecycle_begin(e.tensor_name, "LC_CALLBACK")
            e.callback(status, e)
            timeline_mod.lifecycle_end(e.tensor_name, "LC_CALLBACK")

    _TIMED_RESPONSES = (ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                        ResponseType.BROADCAST, ResponseType.ALLTOALL,
                        ResponseType.ADASUM)

    def _record_collective_latency(self, response: Response,
                                   seconds: float) -> None:
        """Per-collective latency histogram by op/dtype/size bucket.  For
        host-plane ops this is dispatch-to-done; device-async ops record
        the host dispatch cost (device completion belongs to the
        finalizer) — the catalog documents the distinction."""
        if not metrics.ENABLED \
                or response.response_type not in self._TIMED_RESPONSES \
                or response.tensor_type is None:
            return
        # _payload_bytes (coordinator-computed, controller.py) is the true
        # byte count — ALLGATHER/ALLTOALL tensor_sizes are first dims /
        # splits, not element counts.  The wire Response doesn't carry it,
        # so worker ranks fall back to the flat-sum approximation (exact
        # for ALLREDUCE/ADASUM/BROADCAST, a lower bound for the others —
        # same compromise _fuse_responses makes).
        nbytes = getattr(
            response, "_payload_bytes",
            sum(response.tensor_sizes) * response.tensor_type.itemsize)
        metrics.observe(
            "collective_latency_seconds", seconds,
            op=response.response_type.name,
            dtype=response.tensor_type.name,
            size=metrics.size_bucket_label(nbytes))

    @staticmethod
    def _fire_callback(e, status) -> None:
        timeline_mod.lifecycle_begin(e.tensor_name, "LC_CALLBACK")
        try:
            e.callback(status, e)
        except Exception:  # noqa: BLE001 — a raising callback must not
            # kill the dispatching thread (later collectives would strand
            # on unfired callbacks)
            log.error("callback for %r raised", e.tensor_name, exc_info=True)
        finally:
            timeline_mod.lifecycle_end(e.tensor_name, "LC_CALLBACK")

    @staticmethod
    def _finalize_entries(entries) -> None:
        try:
            import jax

            jax.block_until_ready(
                [e.output for e in entries if e.output is not None])
            status = Status.OK()
        except Exception as e:  # noqa: BLE001
            status = Status.error(f"XLA collective failed: {e}")
        for e in entries:
            HorovodGlobalState._fire_callback(e, status)

    def _watch_entries(self, entries) -> None:
        """Failure watchdog for eager-complete dispatches: callbacks
        already fired with unready arrays; here we only wait for the
        device and convert an async failure into a sticky error that the
        next enqueue raises (elastic's retry loop picks it up exactly
        like a synchronous collective failure)."""
        try:
            import jax

            jax.block_until_ready(
                [e.output for e in entries if e.output is not None])
        except Exception as e:  # noqa: BLE001
            names = ", ".join(en.tensor_name for en in entries[:3])
            log.error("async XLA collective failed (%s...): %s", names, e)
            self.async_error = f"async XLA collective failed: {e}"

    def _fail_all_pending(self, msg: str) -> None:
        # Close first: an add racing the drain must fail fast, not strand.
        self.tensor_queue.close()
        for name in self.tensor_queue.names():
            entry = self.tensor_queue.remove(name)
            if entry is not None:
                entry.callback(Status.error(msg), entry)
        # A thread blocked in hvd.join() must not sleep forever either.
        if self.join_event is not None:
            self.joined = False
            self.join_event.set()
            self.join_event = None

    # ------------------------------------------------------------------
    # framework-facing enqueue API (EnqueueTensor*, operations.cc:942-1170)
    # ------------------------------------------------------------------

    def _stage_tensor(self, tensor):
        """(tensor, device_id): keep jax arrays on-device when the XLA data
        plane is (or can be lazily made) ready; host numpy otherwise."""
        from ..backend import xla as xla_backend

        if xla_backend.is_jax_array(tensor):
            ctx = xla_backend.context()
            if not ctx.ready and self.topo.size == 1:
                # Single-process mesh is always safe; build it lazily the
                # first time a device tensor shows up (avoids touching jax
                # device state for numpy-only users).
                ctx.initialize(self.topo)
            if ctx.ready:
                if not getattr(tensor, "is_fully_addressable", True):
                    # Replicated cross-process arrays (e.g. a previous
                    # collective result fed straight back in) enter as
                    # this rank's full local copy — the fuse jit is a
                    # local computation.  A SHARDED global array has no
                    # local equivalent: substituting the shard would
                    # silently reduce shards instead of the value.
                    if getattr(tensor.sharding, "is_fully_replicated",
                               False):
                        tensor = xla_backend._localize(tensor)
                    else:
                        raise HorovodInternalError(
                            "a non-replicated multi-process global array "
                            "was passed to an eager collective; gather or "
                            "reshard it first (eager ops operate on each "
                            "rank's local value).")
                return tensor, xla_backend.XLA_DEVICE_ID
        return np.asarray(tensor), -1

    def _check_initialized(self) -> None:
        if not self.initialized.is_set() or self.topo is None:
            raise HorovodInternalError(
                "horovod_tpu has not been initialized; call hvd.init() first.")
        if self.async_error is not None:
            raise HorovodInternalError(self.async_error)
        if self.init_error is not None:
            raise HorovodInternalError(f"initialization failed: {self.init_error}")
        if self.shutdown_complete.is_set() or \
                (self.background is not None and not self.background.is_alive()):
            # The loop died (peer failure / shutdown): enqueues must fail
            # fast — nothing will ever complete them.  Elastic's run
            # wrapper turns this into a rollback + re-init.
            raise HorovodInternalError(
                "Horovod background loop is not running (shut down or "
                "failed); reinitialize before submitting collectives")

    def enqueue_allreduce(self, name: str, tensor: np.ndarray,
                          callback: Callable[[Status], None],
                          prescale_factor: float = 1.0,
                          postscale_factor: float = 1.0,
                          op: RequestType = RequestType.ALLREDUCE) -> None:
        self._check_initialized()
        tensor, device = self._stage_tensor(tensor)
        entry = TensorTableEntry(
            tensor_name=name, tensor=tensor, callback=callback,
            request_type=op, device=device,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor)
        req = Request(
            request_rank=self.topo.rank, request_type=op,
            tensor_name=name, tensor_type=DataType.from_numpy(tensor.dtype),
            tensor_shape=list(tensor.shape), device=device,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor)
        self.tensor_queue.add(entry, req)

    def enqueue_allgather(self, name: str, tensor: np.ndarray,
                          callback: Callable[[Status], None]) -> None:
        self._check_initialized()
        tensor, device = self._stage_tensor(tensor)
        if device == -1:
            tensor = np.atleast_1d(tensor)
        elif tensor.ndim == 0:
            tensor = tensor.reshape(1)
        entry = TensorTableEntry(tensor_name=name, tensor=tensor,
                                 callback=callback, device=device,
                                 request_type=RequestType.ALLGATHER)
        req = Request(
            request_rank=self.topo.rank, request_type=RequestType.ALLGATHER,
            tensor_name=name, tensor_type=DataType.from_numpy(tensor.dtype),
            tensor_shape=list(tensor.shape), device=device)
        self.tensor_queue.add(entry, req)

    def enqueue_broadcast(self, name: str, tensor: np.ndarray, root_rank: int,
                          callback: Callable[[Status], None]) -> None:
        self._check_initialized()
        tensor, device = self._stage_tensor(tensor)
        entry = TensorTableEntry(tensor_name=name, tensor=tensor,
                                 root_rank=root_rank, callback=callback,
                                 device=device,
                                 request_type=RequestType.BROADCAST)
        req = Request(
            request_rank=self.topo.rank, request_type=RequestType.BROADCAST,
            tensor_name=name, tensor_type=DataType.from_numpy(tensor.dtype),
            tensor_shape=list(tensor.shape), root_rank=root_rank,
            device=device)
        self.tensor_queue.add(entry, req)

    def enqueue_alltoall(self, name: str, tensor: np.ndarray,
                         splits: Optional[List[int]],
                         callback: Callable[[Status], None]) -> None:
        self._check_initialized()
        tensor, device = self._stage_tensor(tensor)
        if device == -1:
            tensor = np.atleast_1d(tensor)
        elif tensor.ndim == 0:
            tensor = tensor.reshape(1)
        if splits is None:
            if tensor.shape[0] % self.topo.size != 0:
                raise ValueError(
                    f"alltoall first dim {tensor.shape[0]} not divisible by "
                    f"size {self.topo.size}; pass explicit splits")
            splits = [tensor.shape[0] // self.topo.size] * self.topo.size
        entry = TensorTableEntry(tensor_name=name, tensor=tensor,
                                 splits=list(splits), callback=callback,
                                 device=device,
                                 request_type=RequestType.ALLTOALL)
        req = Request(
            request_rank=self.topo.rank, request_type=RequestType.ALLTOALL,
            tensor_name=name, tensor_type=DataType.from_numpy(tensor.dtype),
            tensor_shape=list(tensor.shape), splits=list(splits),
            device=device)
        self.tensor_queue.add(entry, req)

    def enqueue_join(self) -> threading.Event:
        """Rank is done with its data: contribute zeros until everyone joins
        (``EnqueueJoin``, ``operations.cc:1146-1170``)."""
        self._check_initialized()
        event = threading.Event()
        if self.topo.size == 1:
            event.set()
            return event
        self.joined = True
        self.join_event = event
        req = Request(request_rank=self.topo.rank, request_type=RequestType.JOIN,
                      tensor_name=JOIN_TENSOR_NAME)
        # JOIN carries no tensor entry; push the request directly.
        self.tensor_queue.push_messages([req])
        if self.shutdown_complete.is_set():
            # Loop died between the liveness check and the push: unblock.
            event.set()
        return event

    def enqueue_barrier(self, callback: Callable[[Status], None],
                        name: Optional[str] = None) -> None:
        self._check_initialized()
        name = name or BARRIER_TENSOR_NAME
        entry = TensorTableEntry(tensor_name=name, callback=callback,
                                 tensor=np.zeros(0, dtype=np.uint8),
                                 request_type=RequestType.BARRIER)
        req = Request(request_rank=self.topo.rank,
                      request_type=RequestType.BARRIER, tensor_name=name)
        self.tensor_queue.add(entry, req)

    # ------------------------------------------------------------------

    def shutdown(self) -> None:
        """Graceful global shutdown (``horovod_shutdown``,
        ``operations.cc:752-778``)."""
        if not self.initialized.is_set() or self.shutdown_complete.is_set():
            return
        self.shutdown_requested.set()
        self.shutdown_complete.wait(timeout=60)
        try:
            atexit.unregister(self.shutdown)
        except Exception:  # noqa: BLE001
            pass

    def reset(self) -> None:
        """Forget everything — used between elastic re-initializations and
        by tests."""
        self.shutdown()
        self.__init__()  # type: ignore[misc]


_global_state = HorovodGlobalState()


def global_state() -> HorovodGlobalState:
    return _global_state


def abort_for_reshard(epoch: Optional[int] = None) -> None:
    """Prompt-abort hook for a reshard-marked notify ping (elastic
    worker service → here): flip this rank's mesh abort flag and relay
    the abort, so a survivor blocked in a collective on a dead peer
    raises ``CoordinatedAbortError`` within one poll quantum instead of
    riding out the TCP progress deadline — the dominant term in legacy
    churn-to-first-step latency.  Best-effort by contract (the retry
    wrapper's normal reset path is the backstop) and epoch-filtered:
    a ping carrying an epoch ≤ the one we already run at is stale
    (the same consume-time staleness rule ``notify_hosts_updated``
    applies) and must not poison the CURRENT world's collectives."""
    from ..common import env as env_mod

    if epoch is not None and epoch <= env_mod.get_epoch():
        return
    st = _global_state
    if st.mesh is None or not st.initialized.is_set():
        return
    try:
        st.mesh.send_abort(
            f"elastic reshard to epoch {epoch}: re-rendezvous in place")
    except Exception as e:  # noqa: BLE001 — best-effort fast path; the
        # progress deadline still unblocks the slow way
        log.debug("reshard abort broadcast failed: %s", e)


def reset_global_state() -> HorovodGlobalState:
    global _global_state
    _global_state.reset()
    _global_state = HorovodGlobalState()
    return _global_state
