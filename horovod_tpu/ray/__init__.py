"""Ray integration: actor-per-slot execution of horovod_tpu jobs.

Role of the reference's ``horovod/ray/runner.py`` (``RayExecutor``,
``BaseHorovodWorker``, ``Coordinator``, ``NodeColocator``) and
``horovod/ray/elastic.py`` (``RayHostDiscovery``, ``ElasticRayExecutor``):
the Ray cluster replaces ssh as the process-placement fabric — one Ray
actor per slot, pinned to its node, with the rank/rendezvous env injected
before the user function runs.  The control plane is unchanged: the same
RendezvousServer, TCP mesh, and (for elastic) ElasticDriver as the CLI
launcher; only worker *spawning* is delegated to Ray.

TPU-first differences: no NIC-negotiation dance (workers advertise all
candidate addresses, ``transport/tcp.py``), per-chip TPU visibility env
comes from ``runner.tpu_topology`` when a node hosts multiple slots, and
``use_gpu``/GPU resource knobs are replaced by ``use_tpu``.

``import horovod_tpu.ray`` works without ray installed; only constructing
an executor requires it.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..common import env as env_mod
from ..common import secret as secret_mod
from ..common.logging_util import get_logger
from ..elastic.discovery import HostDiscovery
from ..runner.hosts import HostInfo, SlotInfo, get_host_assignments
from ..runner.rendezvous import RendezvousServer

log = get_logger("horovod_tpu.ray")


def _ray():
    try:
        import ray
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.ray requires the `ray` package "
            "(pip install horovod-tpu[ray])") from e
    return ray


@dataclass
class RaySettings:
    """Executor knobs (reference ``MiniSettings``, ``ray/runner.py:22-41``)."""

    timeout_s: float = 30.0
    placement_timeout_s: float = 100.0
    cpus_per_slot: int = 1
    use_tpu: bool = False
    extra_env_vars: Dict[str, str] = field(default_factory=dict)


class BaseHorovodWorker:
    """The per-slot Ray actor (reference ``ray/runner.py:48-88``).

    Instantiated remotely via ``ray.remote``; every method call executes in
    the actor's own process, so env mutations land before ``hvd.init``.
    """

    def __init__(self):
        self.executable = None

    def hostname(self) -> str:
        return socket.gethostname()

    def node_ip(self) -> str:
        from ..transport.tcp import _default_advertise_addr

        return _default_advertise_addr()

    def update_env_vars(self, env_vars: Dict[str, str]) -> None:
        os.environ.update({k: str(v) for k, v in env_vars.items()})

    def env_vars(self) -> Dict[str, str]:
        return dict(os.environ)

    def start_executable(self, executable_cls=None, executable_args=None,
                         executable_kwargs=None) -> None:
        if executable_cls is not None:
            self.executable = executable_cls(*(executable_args or []),
                                             **(executable_kwargs or {}))

    def execute(self, fn: Callable) -> Any:
        """Run ``fn(executable)`` (or ``fn()`` when no executable was
        started) inside the actor."""
        if self.executable is not None:
            return fn(self.executable)
        return fn()

    def shutdown_horovod(self) -> None:
        import horovod_tpu as hvd

        if hvd.is_initialized():
            hvd.shutdown()


class RayExecutor:
    """Static Ray job: N actors, one per slot (reference
    ``ray/runner.py:250-480``).

    Usage::

        executor = RayExecutor(RaySettings(), num_workers=4)
        executor.start()
        results = executor.run(train_fn, args=(cfg,))
        executor.shutdown()
    """

    @classmethod
    def create_settings(cls, timeout_s: float = 30.0,
                        **kwargs) -> RaySettings:
        return RaySettings(timeout_s=timeout_s, **kwargs)

    def __init__(self, settings: Optional[RaySettings] = None,
                 num_workers: Optional[int] = None,
                 num_hosts: Optional[int] = None,
                 num_slots: Optional[int] = None,
                 cpus_per_slot: Optional[int] = None,
                 use_tpu: Optional[bool] = None):
        self.settings = settings or RaySettings()
        if cpus_per_slot is not None:
            self.settings.cpus_per_slot = cpus_per_slot
        if use_tpu is not None:
            self.settings.use_tpu = use_tpu
        if num_workers is None and (num_hosts is None or num_slots is None):
            raise ValueError(
                "specify num_workers, or num_hosts together with num_slots "
                "(reference RayExecutor has the same contract)")
        self.num_workers = num_workers or (num_hosts * num_slots)
        self.num_hosts = num_hosts
        self.num_slots = num_slots
        self.workers: List = []
        self.slots: List[SlotInfo] = []
        self._server: Optional[RendezvousServer] = None

    # -- lifecycle ------------------------------------------------------

    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None,
              extra_env_vars: Optional[Dict[str, str]] = None) -> None:
        ray = _ray()
        remote_cls = ray.remote(BaseHorovodWorker)
        opts = {"num_cpus": self.settings.cpus_per_slot}
        self.workers = [remote_cls.options(**opts).remote()
                        for _ in range(self.num_workers)]

        # Coordinator role (reference ray/runner.py:178-249): learn where
        # Ray placed each actor, derive host-major rank coordinates.
        hostnames = ray.get([w.hostname.remote() for w in self.workers],
                            timeout=self.settings.placement_timeout_s)
        by_host: Dict[str, int] = {}
        for h in hostnames:
            by_host[h] = by_host.get(h, 0) + 1
        if self.num_hosts is not None and len(by_host) != self.num_hosts:
            log.warning("requested %d hosts, Ray placed actors on %d",
                        self.num_hosts, len(by_host))
        host_infos = [HostInfo(h, n) for h, n in by_host.items()]
        self.slots = get_host_assignments(host_infos, self.num_workers)

        # Actors were created unpinned; order them host-major to match the
        # slot table (actor i ↔ slot i).
        order: Dict[str, List[int]] = {}
        for i, h in enumerate(hostnames):
            order.setdefault(h, []).append(i)
        arranged = []
        for slot in self.slots:
            arranged.append(self.workers[order[slot.hostname].pop(0)])
        self.workers = arranged

        # Rendezvous + per-job secret live in the driver process.
        job_secret = secret_mod.ensure_job_secret()
        self._server = RendezvousServer(bind_addr="0.0.0.0",
                                        job_secret=job_secret.encode())
        port = self._server.start()
        self._server.publish_slots([{
            "hostname": s.hostname, "rank": s.rank,
            "local_rank": s.local_rank, "cross_rank": s.cross_rank,
            "size": s.size, "local_size": s.local_size,
            "cross_size": s.cross_size,
        } for s in self.slots])

        from ..transport.tcp import _default_advertise_addr

        rdv_addr = _default_advertise_addr()
        env_refs = []
        for slot, worker in zip(self.slots, self.workers):
            env = dict(slot.to_env())
            env.update({
                env_mod.HOROVOD_RENDEZVOUS_ADDR: rdv_addr,
                env_mod.HOROVOD_RENDEZVOUS_PORT: str(port),
                env_mod.HOROVOD_CONTROLLER: "tcp",
                env_mod.HOROVOD_SECRET_KEY: job_secret,
            })
            if self.settings.use_tpu and slot.local_size > 1:
                from ..runner import tpu_topology
                from ..runner.launch import host_slots_of

                env.update(tpu_topology.slot_tpu_env(
                    slot.rank, slot.local_rank, host_slots_of(self.slots)))
            env.update(self.settings.extra_env_vars)
            env.update(extra_env_vars or {})
            env_refs.append(worker.update_env_vars.remote(env))
        ray.get(env_refs, timeout=self.settings.timeout_s)
        ray.get([w.start_executable.remote(executable_cls, executable_args,
                                           executable_kwargs)
                 for w in self.workers], timeout=self.settings.timeout_s)

    # -- execution ------------------------------------------------------

    def execute(self, fn: Callable) -> List[Any]:
        """Run ``fn`` on every worker; returns per-rank results."""
        ray = _ray()
        return ray.get([w.execute.remote(fn) for w in self.workers])

    def run(self, fn: Callable, args: Optional[list] = None,
            kwargs: Optional[dict] = None) -> List[Any]:
        args, kwargs = args or [], kwargs or {}
        return self.execute(lambda *exe: fn(*args, **kwargs))

    def run_remote(self, fn: Callable, args: Optional[list] = None,
                   kwargs: Optional[dict] = None) -> List[Any]:
        """Non-blocking flavor: returns Ray object refs."""
        args, kwargs = args or [], kwargs or {}
        return [w.execute.remote(lambda *exe: fn(*args, **kwargs))
                for w in self.workers]

    def execute_single(self, fn: Callable) -> Any:
        ray = _ray()
        return ray.get(self.workers[0].execute.remote(fn))

    def shutdown(self) -> None:
        ray = _ray()
        try:
            ray.get([w.shutdown_horovod.remote() for w in self.workers],
                    timeout=self.settings.timeout_s)
        except Exception:  # noqa: BLE001 — best-effort drain
            pass
        for w in self.workers:
            ray.kill(w)
        self.workers = []
        if self._server is not None:
            self._server.stop()
            self._server = None


class RayHostDiscovery(HostDiscovery):
    """Ray cluster state as the elastic discovery source (reference
    ``ray/elastic.py:36-60``): alive nodes with enough CPUs (or TPU
    resources) become hosts; slots = resource count / per-slot demand."""

    def __init__(self, use_tpu: bool = False, cpus_per_slot: int = 1):
        self.use_tpu = use_tpu
        self.cpus_per_slot = cpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        ray = _ray()
        hosts: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            resources = node.get("Resources", {})
            if self.use_tpu:
                slots = int(resources.get("TPU", 0))
            else:
                slots = int(resources.get("CPU", 0) // self.cpus_per_slot)
            hostname = node.get("NodeManagerHostname") or \
                node.get("NodeManagerAddress")
            if slots > 0 and hostname:
                hosts[hostname] = slots
        return hosts


class ElasticRayExecutor:
    """Elastic job over Ray actors (reference ``ray/elastic.py:61-300``):
    the shared ElasticDriver handles discovery/rank-reshuffle/blacklists;
    worker creation spawns a Ray actor per slot instead of an ssh child."""

    def __init__(self, settings: Optional[RaySettings] = None,
                 min_np: int = 1, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 discovery: Optional[HostDiscovery] = None):
        self.settings = settings or RaySettings()
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.discovery = discovery or RayHostDiscovery(
            use_tpu=self.settings.use_tpu,
            cpus_per_slot=self.settings.cpus_per_slot)
        self.driver = None
        self._server: Optional[RendezvousServer] = None
        self._results: Dict[int, Any] = {}
        self._actors: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def start(self) -> None:
        from ..elastic.discovery import HostManager
        from ..elastic.driver import ElasticDriver

        self._job_secret = secret_mod.ensure_job_secret()
        self._server = RendezvousServer(
            bind_addr="0.0.0.0", job_secret=self._job_secret.encode())
        self._server.start()
        self.driver = ElasticDriver(
            self._server, HostManager(self.discovery),
            min_np=self.min_np, max_np=self.max_np,
            reset_limit=self.reset_limit)

    def run(self, worker_fn: Callable) -> List[Any]:
        """Run ``worker_fn`` elastically; returns results of the ranks that
        finished successfully (reference ``elastic.py:266-300``)."""
        assert self.driver is not None, "call start() first"
        ray = _ray()
        from ..elastic.registration import FAILURE, SUCCESS
        from ..transport.tcp import _default_advertise_addr

        rdv_addr = _default_advertise_addr()
        port = self._server.port
        remote_cls = ray.remote(BaseHorovodWorker)

        def create_worker(slot: SlotInfo, epoch: int) -> None:
            actor = remote_cls.options(
                num_cpus=self.settings.cpus_per_slot).remote()
            identity = f"{slot.hostname}:{slot.local_rank}"
            env = dict(slot.to_env())
            env.update({
                env_mod.HOROVOD_RENDEZVOUS_ADDR: rdv_addr,
                env_mod.HOROVOD_RENDEZVOUS_PORT: str(port),
                env_mod.HOROVOD_CONTROLLER: "tcp",
                env_mod.HOROVOD_SECRET_KEY: self._job_secret,
                env_mod.HOROVOD_ELASTIC: "1",
                env_mod.HOROVOD_EPOCH: str(epoch),
            })
            env.update(self.settings.extra_env_vars)
            with self._lock:
                self._actors[identity] = actor
            ref = actor.execute.remote(_elastic_worker_main(
                worker_fn, env))

            def monitor():
                code = 0
                try:
                    result = ray.get(ref)
                    with self._lock:
                        self._results[slot.rank] = result
                except Exception as e:  # noqa: BLE001 — actor died/failed
                    log.info("elastic ray worker %s failed: %s", identity, e)
                    code = 1
                finally:
                    with self._lock:
                        self._actors.pop(identity, None)
                    self.driver.record_worker_exit(slot, code)
                    ray.kill(actor)

            threading.Thread(target=monitor, daemon=True,
                             name=f"ray-monitor-{identity}").start()

        try:
            self.driver.start(create_worker)
            while True:
                time.sleep(0.5)
                with self._lock:
                    alive = len(self._actors)
                successes = self.driver._registry.count(SUCCESS)
                failures = self.driver._registry.count(FAILURE)
                if successes and successes >= len(self.driver.current_slots) \
                        and alive == 0:
                    break
                if alive == 0 and failures and \
                        self.driver.hosts.total_slots() < self.min_np:
                    raise RuntimeError(
                        f"elastic ray job lost all capacity "
                        f"({failures} failures)")
                if self.driver.stopped_error:
                    raise RuntimeError(self.driver.stopped_error)
        finally:
            self.driver.stop()
        with self._lock:
            return [self._results[r] for r in sorted(self._results)]

    def shutdown(self) -> None:
        if self.driver is not None:
            self.driver.stop()
        if self._server is not None:
            self._server.stop()
            self._server = None


def _elastic_worker_main(worker_fn: Callable, env: Dict[str, str]):
    """Build the closure an elastic Ray actor executes: env first (before
    any horovod import state latches), then the user fn."""

    def main():
        os.environ.update(env)
        return worker_fn()

    return main
