"""`import horovod_tpu.keras as hvd` — reference-parity alias for the
Keras binding (reference exposes `horovod.keras`)."""

from .frameworks.keras import *  # noqa: F401,F403
from .frameworks.keras import __all__  # noqa: F401


def __getattr__(name):
    if name == "elastic":
        from .frameworks.keras import elastic

        return elastic
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
