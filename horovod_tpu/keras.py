"""`import horovod_tpu.keras as hvd` — reference-parity alias for the
Keras binding (reference exposes `horovod.keras`)."""

from .frameworks.keras import *  # noqa: F401,F403
from .frameworks.keras import __all__  # noqa: F401
