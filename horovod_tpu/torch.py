"""`import horovod_tpu.torch as hvd` — reference-parity alias for the
PyTorch binding (reference exposes `horovod.torch`)."""

from .frameworks.torch import *  # noqa: F401,F403
from .frameworks.torch import __all__  # noqa: F401


def __getattr__(name):
    if name in ("elastic", "SyncBatchNorm"):
        from .frameworks import torch as _impl

        return getattr(_impl, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
