"""One-shot metrics snapshot of a LIVE job via the rendezvous server.

The workers push registry snapshots to the rendezvous KV every
``HOROVOD_METRICS_PUSH_SECS`` (core/state.py); the server aggregates them
at ``GET /metrics`` (runner/rendezvous.py).  This tool is the operator's
curl-with-a-brain: fetch the scrape, either raw (Prometheus text, exactly
what a Prometheus scraper would ingest) or pretty-printed per rank.

Usage::

    python -m horovod_tpu.tools.metrics_dump              # addr from env
    python -m horovod_tpu.tools.metrics_dump --addr 10.0.0.2 --port 41999
    python -m horovod_tpu.tools.metrics_dump --raw        # Prometheus text
    tools/metrics_dump.py --json                          # raw snapshots
    tools/metrics_dump.py --watch 2                       # re-scrape every 2s
    tools/metrics_dump.py --watch 2 --rate                # per-second deltas

Address defaults come from the launcher-propagated
``HOROVOD_GLOO_RENDEZVOUS_ADDR``/``PORT`` env, so running it on any job
host with the job's environment just works.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Optional, Sequence

from ..common import env as env_mod


def fetch(addr: str, port: int, fmt: str = "text",
          timeout: float = 5.0) -> str:
    suffix = "?format=json" if fmt == "json" else ""
    with urllib.request.urlopen(
            f"http://{addr}:{port}/metrics{suffix}", timeout=timeout) as r:
        return r.read().decode()


# Control-plane snapshots (docs/observability.md "Control-plane
# attribution"): the server folds its own registry into the scrape under
# rank="server", and in external mode the driver pushes rank="driver".
# Render them as a distinct section after the worker ranks.
_CONTROL_RANKS = frozenset({"server", "driver"})


def _order(snaps: dict):
    def key_fn(key):
        rank = str(snaps[key].get("rank", key))
        return (1, rank) if rank in _CONTROL_RANKS else (0, str(key))
    return sorted(snaps, key=key_fn)


def _header(snap: dict, key, suffix: str) -> str:
    rank = snap.get("rank", key)
    if str(rank) in _CONTROL_RANKS:
        return f"== control plane: {rank}{suffix} =="
    return f"== rank {rank}{suffix} =="


def _pretty(snaps: dict) -> str:
    out = []
    for key in _order(snaps):
        snap = snaps[key]
        out.append(_header(
            snap, key,
            f" (pushed at unix_ns={snap.get('ts_unix_ns', '?')})"))
        for kind in ("counters", "gauges"):
            for name in sorted(snap.get(kind, {})):
                out.append(f"  {name} = {snap[kind][name]}")
        for name in sorted(snap.get("histograms", {})):
            h = snap["histograms"][name]
            n = max(1, h.get("count", 0))
            out.append(f"  {name}: count={h.get('count', 0)} "
                       f"sum={h.get('sum', 0.0):.6g} "
                       f"mean={h.get('sum', 0.0) / n:.6g}")
    return "\n".join(out)


def _rates(prev: dict, cur: dict, dt: float) -> str:
    """Per-second counter deltas between two snapshot scrapes (gauges are
    levels, not rates — shown as their current value)."""
    out = []
    for key in _order(cur):
        snap = cur[key]
        before = prev.get(key, {})
        out.append(_header(snap, key, f" (Δ over {dt:.1f}s)"))
        prev_c = before.get("counters", {})
        for name in sorted(snap.get("counters", {})):
            d = snap["counters"][name] - prev_c.get(name, 0)
            if d:
                out.append(f"  {name} = +{d / dt:.6g}/s")
        for name in sorted(snap.get("gauges", {})):
            out.append(f"  {name} = {snap['gauges'][name]} (gauge)")
        prev_h = before.get("histograms", {})
        for name in sorted(snap.get("histograms", {})):
            h = snap["histograms"][name]
            p = prev_h.get(name, {})
            dc = h.get("count", 0) - p.get("count", 0)
            if dc:
                ds = h.get("sum", 0.0) - p.get("sum", 0.0)
                out.append(f"  {name}: +{dc / dt:.6g} obs/s "
                           f"mean={ds / dc:.6g}")
    return "\n".join(out)


def _render_once(addr: str, port: int, args,
                 prev: Optional[dict], dt: float) -> Optional[dict]:
    """One scrape + print; returns the parsed snapshots (None in raw
    mode, where rates don't apply)."""
    if args.raw:
        print(fetch(addr, port, "text"), end="")
        return None
    if args.json:
        text = fetch(addr, port, "json")
        print(text)
        return json.loads(text)
    snaps = json.loads(fetch(addr, port, "json"))
    if not snaps:
        print("metrics-dump: no rank has pushed a snapshot yet "
              "(HOROVOD_METRICS_PUSH_SECS=0, or the job just started)")
    elif args.rate and prev is not None:
        print(_rates(prev, snaps, dt))
    else:
        print(_pretty(snaps))
    return snaps


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="metrics-dump",
        description="one-shot cross-rank metrics snapshot of a live "
                    "horovod_tpu job (docs/observability.md)")
    ap.add_argument("--addr", default=None,
                    help="rendezvous server address (default: "
                         "HOROVOD_GLOO_RENDEZVOUS_ADDR)")
    ap.add_argument("--port", type=int, default=None,
                    help="rendezvous server port (default: "
                         "HOROVOD_GLOO_RENDEZVOUS_PORT)")
    ap.add_argument("--raw", action="store_true",
                    help="print the Prometheus text scrape verbatim")
    ap.add_argument("--json", action="store_true",
                    help="print the raw per-rank snapshot JSON")
    ap.add_argument("--watch", type=float, default=None, metavar="N",
                    help="re-scrape every N seconds until interrupted")
    ap.add_argument("--rate", action="store_true",
                    help="with --watch: print per-second counter deltas "
                         "between scrapes instead of absolute values")
    args = ap.parse_args(argv)
    if args.rate and not args.watch:
        ap.error("--rate requires --watch (rates need two scrapes)")

    addr = args.addr or env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
    port = args.port or env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
    if not addr or not port:
        print("metrics-dump: no rendezvous server (pass --addr/--port or "
              "run inside a job's environment)", file=sys.stderr)
        return 2
    prev: Optional[dict] = None
    t_prev = time.monotonic()
    while True:
        try:
            now = time.monotonic()
            prev = _render_once(addr, port, args, prev,
                                max(now - t_prev, 1e-9))
            t_prev = now
        except OSError as e:
            print(f"metrics-dump: scrape of {addr}:{port} failed: {e}",
                  file=sys.stderr)
            if not args.watch:
                return 1
        if not args.watch:
            return 0
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0
        print(f"---- {time.strftime('%H:%M:%S')} ----")


if __name__ == "__main__":
    sys.exit(main())
