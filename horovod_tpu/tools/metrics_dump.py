"""One-shot metrics snapshot of a LIVE job via the rendezvous server.

The workers push registry snapshots to the rendezvous KV every
``HOROVOD_METRICS_PUSH_SECS`` (core/state.py); the server aggregates them
at ``GET /metrics`` (runner/rendezvous.py).  This tool is the operator's
curl-with-a-brain: fetch the scrape, either raw (Prometheus text, exactly
what a Prometheus scraper would ingest) or pretty-printed per rank.

Usage::

    python -m horovod_tpu.tools.metrics_dump              # addr from env
    python -m horovod_tpu.tools.metrics_dump --addr 10.0.0.2 --port 41999
    python -m horovod_tpu.tools.metrics_dump --raw        # Prometheus text
    tools/metrics_dump.py --json                          # raw snapshots

Address defaults come from the launcher-propagated
``HOROVOD_GLOO_RENDEZVOUS_ADDR``/``PORT`` env, so running it on any job
host with the job's environment just works.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Optional, Sequence

from ..common import env as env_mod


def fetch(addr: str, port: int, fmt: str = "text",
          timeout: float = 5.0) -> str:
    suffix = "?format=json" if fmt == "json" else ""
    with urllib.request.urlopen(
            f"http://{addr}:{port}/metrics{suffix}", timeout=timeout) as r:
        return r.read().decode()


def _pretty(snaps: dict) -> str:
    out = []
    for key in sorted(snaps, key=str):
        snap = snaps[key]
        rank = snap.get("rank", key)
        out.append(f"== rank {rank} (pushed at unix_ns="
                   f"{snap.get('ts_unix_ns', '?')}) ==")
        for kind in ("counters", "gauges"):
            for name in sorted(snap.get(kind, {})):
                out.append(f"  {name} = {snap[kind][name]}")
        for name in sorted(snap.get("histograms", {})):
            h = snap["histograms"][name]
            n = max(1, h.get("count", 0))
            out.append(f"  {name}: count={h.get('count', 0)} "
                       f"sum={h.get('sum', 0.0):.6g} "
                       f"mean={h.get('sum', 0.0) / n:.6g}")
    return "\n".join(out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="metrics-dump",
        description="one-shot cross-rank metrics snapshot of a live "
                    "horovod_tpu job (docs/observability.md)")
    ap.add_argument("--addr", default=None,
                    help="rendezvous server address (default: "
                         "HOROVOD_GLOO_RENDEZVOUS_ADDR)")
    ap.add_argument("--port", type=int, default=None,
                    help="rendezvous server port (default: "
                         "HOROVOD_GLOO_RENDEZVOUS_PORT)")
    ap.add_argument("--raw", action="store_true",
                    help="print the Prometheus text scrape verbatim")
    ap.add_argument("--json", action="store_true",
                    help="print the raw per-rank snapshot JSON")
    args = ap.parse_args(argv)

    addr = args.addr or env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
    port = args.port or env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
    if not addr or not port:
        print("metrics-dump: no rendezvous server (pass --addr/--port or "
              "run inside a job's environment)", file=sys.stderr)
        return 2
    try:
        if args.raw:
            print(fetch(addr, port, "text"), end="")
        elif args.json:
            print(fetch(addr, port, "json"))
        else:
            snaps = json.loads(fetch(addr, port, "json"))
            if not snaps:
                print("metrics-dump: no rank has pushed a snapshot yet "
                      "(HOROVOD_METRICS_PUSH_SECS=0, or the job just "
                      "started)")
            else:
                print(_pretty(snaps))
    except OSError as e:
        print(f"metrics-dump: scrape of {addr}:{port} failed: {e}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
