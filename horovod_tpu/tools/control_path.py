"""Control-plane attribution over merged server+driver+worker traces.

The churn analog of ``tools/critical_path.py``: where that tool answers
"where did a training step's time go", this one answers **"where did a
churn event's time go"** — the question ROADMAP item 2 (bending the
~185 ms/event curve in ``controller_churn_np64.json``) needs answered
before batched rendezvous ops or tree fan-in can be justified.

Inputs are the control-plane complete ("X") spans the runtime emits
(``core/timeline.py``; all cheap retroactive spans, so concurrent server
handler threads can land overlapping records on one lane without B/E
stack mis-nesting):

- ``CHURN_EVENT`` — one span per epoch transition, emitted by the elastic
  driver (``elastic/driver.py``, cause-tagged) or by
  ``benchmarks/controller_sim.py --churn``.  Each defines an **event
  window**.
- ``RVC_SET/GET/KEYS/DELETE/BATCH`` — client-side HTTP round-trips
  (``transport/store.py``), and ``RV_PUT/GET/…`` — the server-side
  handler spans (``runner/rendezvous.py``, merging unshifted because the
  server is trace_merge's clock base).  ``RVC_WIRE`` — injected shaped-
  wire delay from the simulated-cluster harness (``horovod_tpu/sim/``);
  simulated propagation time is honestly round-trip time.
- ``RV_BATCH`` — the server applying one batched transaction
  (``POST /batch``): decode, ONE store-lock acquisition, one journaled
  record group.  Its own phase (``batch_apply``), because transaction
  application is server compute, not wire time — lumping it into
  ``http_roundtrip`` would hide exactly the cost batching moved.
- ``RV_LOCK_WAIT`` — store-lock contention on the server.
- ``JR_FSYNC/JR_COMPACT/JR_REPLAY`` — journal durability work
  (``transport/journal.py``).
- ``DRV_SPAWN`` / ``DRV_WAIT`` — driver worker respawns and idle
  tick-waits (``elastic/driver.py``).

Within each event window the phases are carved into **disjoint**
intervals in cost order — lock wait and fsync first (they nest inside the
batch application / HTTP round-trips that caused them), then batch
apply, HTTP, respawn, tick wait — so
the per-phase times sum to the covered fraction of the window and
``coverage`` honestly reports how much of the event's wall time the
instrumentation explains (the PR acceptance floor is 0.90).

Usage::

    hvd-control-path merged_timeline.json             # text report
    hvd-control-path server_trace.json tl.json.driver --json cp.json
    tools/control_path.py /tmp/server.json /tmp/tl.json*   # repo shim
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .critical_path import _subtract, _total, _union
from .trace_merge import load_trace, merge

EVENT_SPAN = "CHURN_EVENT"

#: Attribution order matters: each phase's intervals are clipped to the
#: event window and reduced by everything already attributed, so nested
#: costs (a lock wait inside an HTTP round-trip) count once, under the
#: most specific name.
PHASES = ("store_lock_wait", "journal_fsync", "batch_apply",
          "http_roundtrip", "respawn", "driver_tick_wait")

_JOURNAL_SPANS = {"JR_FSYNC", "JR_COMPACT", "JR_REPLAY"}


def _phase_of(name: str) -> Optional[str]:
    if name == "RV_LOCK_WAIT":
        return "store_lock_wait"
    if name in _JOURNAL_SPANS:
        return "journal_fsync"
    if name == "RV_BATCH":
        return "batch_apply"
    if name.startswith("RVC_") or name.startswith("RV_"):
        return "http_roundtrip"
    if name == "DRV_SPAWN":
        return "respawn"
    if name == "DRV_WAIT":
        return "driver_tick_wait"
    return None


def collect_spans(events: List[dict]) -> List[dict]:
    """Complete-event spans as ``{name, pid, b, e, args}`` dicts.  The
    control plane emits only "X" records; B/E worker spans in a merged
    trace belong to hvd-critical-path and are ignored here."""
    spans = []
    for e in events:
        if e.get("ph") != "X" or "ts" not in e:
            continue
        b = float(e["ts"])
        spans.append({"name": e.get("name", ""), "pid": e.get("pid"),
                      "b": b, "e": b + float(e.get("dur", 0.0)),
                      "args": e.get("args") or {}})
    return spans


def _clip(intervals: List[Tuple[float, float]], w0: float, w1: float
          ) -> List[Tuple[float, float]]:
    return [(max(b, w0), min(e, w1)) for b, e in intervals
            if e > w0 and b < w1]


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def analyze(events: List[dict]) -> dict:
    """Produce the per-churn-event attribution document."""
    spans = collect_spans(events)
    windows = sorted((s for s in spans if s["name"] == EVENT_SPAN),
                     key=lambda s: s["b"])
    by_phase: Dict[str, List[Tuple[float, float]]] = \
        {p: [] for p in PHASES}
    for s in spans:
        p = _phase_of(s["name"])
        if p is not None:
            by_phase[p].append((s["b"], s["e"]))
    unions = {p: _union(iv) for p, iv in by_phase.items()}

    out_events = []
    totals = dict.fromkeys(PHASES, 0.0)
    covered_total = 0.0
    wall_total = 0.0
    for i, w in enumerate(windows):
        w0, w1 = w["b"], w["e"]
        wall = w1 - w0
        covered: List[Tuple[float, float]] = []
        phases_us = {}
        for p in PHASES:
            exclusive = _subtract(_union(_clip(unions[p], w0, w1)), covered)
            phases_us[p] = _total(exclusive)
            totals[p] += phases_us[p]
            covered = _union(covered + exclusive)
        cov_us = _total(covered)
        covered_total += cov_us
        wall_total += wall
        out_events.append({
            "event": i,
            "cause": w["args"].get("cause"),
            "epoch": w["args"].get("epoch"),
            "pid": w["pid"],
            "t0_us": round(w0, 1),
            "duration_us": round(wall, 1),
            "phases_us": {p: round(v, 1) for p, v in phases_us.items()},
            "unattributed_us": round(wall - cov_us, 1),
            "coverage": round(cov_us / wall, 4) if wall > 0 else 1.0,
        })

    walls = sorted(e["duration_us"] for e in out_events)
    return {
        "format": "hvd-control-path-v1",
        "event_count": len(out_events),
        "events": out_events,
        "phase_totals_us": {p: round(v, 1) for p, v in totals.items()},
        "phase_share": {p: round(v / wall_total, 4) if wall_total else 0.0
                        for p, v in totals.items()},
        "wall_us": {"total": round(wall_total, 1),
                    "p50": round(_percentile(walls, 0.5), 1),
                    "p99": round(_percentile(walls, 0.99), 1)},
        "coverage": round(covered_total / wall_total, 4)
        if wall_total else 1.0,
        "pids_seen": sorted({s["pid"] for s in spans
                             if s["pid"] is not None}),
    }


def render_text(doc: dict, top: int = 10) -> str:
    lines = []
    n = doc["event_count"]
    lines.append(f"control-path: {n} churn event(s), "
                 f"pids {doc['pids_seen']}")
    if not n:
        lines.append("no CHURN_EVENT spans found — trace an elastic run "
                     "with HOROVOD_TIMELINE (+ HOROVOD_SERVER_TIMELINE "
                     "for the server side), or use "
                     "benchmarks/controller_sim.py --churn")
        return "\n".join(lines)
    w = doc["wall_us"]
    lines.append(f"event wall: p50 {w['p50'] / 1e3:.3f}ms  "
                 f"p99 {w['p99'] / 1e3:.3f}ms  "
                 f"total {w['total'] / 1e3:.3f}ms  "
                 f"coverage {doc['coverage'] * 100:.1f}%")
    lines.append("")
    lines.append("aggregate attribution (disjoint carve, nested costs "
                 "count once under the most specific phase):")
    lines.append(f"  {'phase':>17} {'ms':>10} {'share':>7}")
    for p in PHASES:
        lines.append(f"  {p:>17} {doc['phase_totals_us'][p] / 1e3:>10.3f} "
                     f"{doc['phase_share'][p] * 100:>6.1f}%")
    unattr = w["total"] - sum(doc["phase_totals_us"].values())
    lines.append(f"  {'(unattributed)':>17} {unattr / 1e3:>10.3f} "
                 f"{(1 - doc['coverage']) * 100:>6.1f}%")
    lines.append("")
    slowest = sorted(doc["events"], key=lambda e: -e["duration_us"])[:top]
    lines.append(f"slowest {len(slowest)} event(s):")
    lines.append(f"  {'event':>6} {'ms':>10} {'cause':>14} {'cov':>6} "
                 f"{'dominant':>22}")
    for e in slowest:
        dom_p = max(PHASES, key=lambda p: e["phases_us"][p])
        dom = f"{dom_p} {e['phases_us'][dom_p] / 1e3:.3f}ms"
        lines.append(f"  {e['event']:>6} {e['duration_us'] / 1e3:>10.3f} "
                     f"{str(e['cause'] or '-'):>14} "
                     f"{e['coverage'] * 100:>5.1f}% {dom:>22}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="control-path",
        description="per-churn-event control-plane attribution over "
                    "horovod_tpu timeline traces (merged or separate "
                    "server/driver/worker files)")
    ap.add_argument("inputs", nargs="+",
                    help="a merged trace, or server/driver/worker traces")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the full report as JSON")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest events to list in the text report "
                         "(default: %(default)s)")
    args = ap.parse_args(argv)

    traces = [load_trace(p) for p in args.inputs]
    events = traces[0] if len(traces) == 1 else merge(traces)
    doc = analyze(events)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
    print(render_text(doc, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
