"""Critical-path extraction over merged timeline traces.

Consumes the output of ``tools/trace_merge.py`` (or raw per-rank trace
files, merged on the fly) and answers the attribution question the raw
Perfetto view leaves to eyeballing: **for each lockstep step (negotiation
cycle), which rank ended last, and where did that rank's — and every
rank's — time go?**

Every span the runtime emits is cycle-tagged (``core/timeline.py``):
``NEGOTIATE_*`` spans on the coordinator with per-rank readiness instants,
and the ``LC_*`` lifecycle spans (submitted → negotiated → fused → wire →
reduced → callback) on every rank.  This tool reconstructs B/E span trees
per (pid, tid), groups spans by their negotiation cycle id, and emits per
step:

- the step window (first begin → last end across ranks) and its duration,
- the **critical rank** — the pid whose span ends the step,
- a per-rank attribution over the phases ``{negotiation_wait, fusion,
  wire, digest, reduce, dispatch}``, computed as the union of that rank's
  span intervals per phase (union, not sum — a fused batch emits the same
  wire span on every member tensor's lane and must count once).

Phase mapping:

- ``NEGOTIATE_*`` → ``negotiation_wait``, attributed to the **last-ready
  rank**: the span's duration up to its final per-rank readiness instant
  is charged to that instant's rank — the one everyone actually waited
  for — not to the coordinator that emitted the span.  Mask-path
  negotiations (no table spans) contribute nothing; run the workload with
  unique tensor names per step to see negotiation attribution.
- ``FANIN_*`` → ``fanin``: the tree-negotiation hop (a host aggregator
  collecting, folding and relaying its members' mask frames,
  ``core/negotiation_fanin.py``) gets its own disjoint phase so the
  O(hosts) ingress optimisation is attributable separately from both the
  coordinator's negotiation wait and dispatch.
- ``LC_FUSE``/``LC_UNFUSE``/``MEMCPY*`` → ``fusion``
- ``LC_WIRE_ALLGATHER``/``LC_WIRE_CROSS``/``LC_AG_STEP`` → ``wire``
- ``*DIGEST*`` → ``digest`` (reserved: the shadow digest pipeline does
  not emit spans yet, so this column reads 0 today)
- ``LC_WIRE_REDUCE_SCATTER``/``LC_RS_STEP`` → ``reduce``
- op spans (``ALLREDUCE``...) and ``LC_CALLBACK`` → ``dispatch``, minus
  the sub-intervals already attributed to fusion/wire/digest/reduce.

Usage::

    hvd-critical-path merged_timeline.json            # text report
    hvd-critical-path tl.json tl.json.rank1 --json cp.json --top 5
    tools/critical_path.py /tmp/tl.json*              # repo-root shim
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .trace_merge import load_trace, merge

PHASES = ("negotiation_wait", "fanin", "fusion", "wire", "digest",
          "reduce", "dispatch")

_OP_SPANS = {"ALLREDUCE", "ALLGATHER", "BROADCAST", "ALLTOALL", "ADASUM",
             "BARRIER", "JOIN", "LC_CALLBACK"}
_FUSION_SPANS = {"LC_FUSE", "LC_UNFUSE"}
_WIRE_SPANS = {"LC_WIRE_ALLGATHER", "LC_WIRE_CROSS", "LC_AG_STEP"}
_REDUCE_SPANS = {"LC_WIRE_REDUCE_SCATTER", "LC_RS_STEP"}


def _phase_of(name: str) -> Optional[str]:
    if name.startswith("FANIN_"):
        return "fanin"
    if name in _FUSION_SPANS or "MEMCPY" in name:
        return "fusion"
    if name in _WIRE_SPANS:
        return "wire"
    if "DIGEST" in name:
        return "digest"
    if name in _REDUCE_SPANS:
        return "reduce"
    if name in _OP_SPANS:
        return "dispatch"
    return None  # LC_SUBMITTED, NEGOTIATE_* (special-cased), unknown


class Span:
    __slots__ = ("name", "pid", "tid", "b", "e", "cycle", "instants")

    def __init__(self, name: str, pid, tid, b: float, cycle: Optional[int]):
        self.name = name
        self.pid = pid
        self.tid = tid
        self.b = b
        self.e: Optional[float] = None
        self.cycle = cycle
        # (ts, name) instants that fired while this span was innermost —
        # for NEGOTIATE spans these are the per-rank readiness ticks.
        self.instants: List[Tuple[float, str]] = []


def reconstruct(events: List[dict]) -> List[Span]:
    """Rebuild duration spans from B/E records per (pid, tid).  A span
    with no cycle tag inherits the nearest enclosing tagged span's cycle.
    Unclosed spans (crash-truncated trace) are closed at their lane's
    last timestamp."""
    lanes: Dict[Tuple, List[dict]] = {}
    for e in events:
        if e.get("ph") in ("B", "E", "i") and "ts" in e:
            lanes.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    spans: List[Span] = []
    for (pid, tid), evs in lanes.items():
        evs.sort(key=lambda r: r["ts"])
        stack: List[Span] = []
        for r in evs:
            ph = r["ph"]
            if ph == "B":
                cycle = (r.get("args") or {}).get("cycle")
                if cycle is None and stack:
                    cycle = stack[-1].cycle
                s = Span(r.get("name", ""), pid, tid, r["ts"], cycle)
                stack.append(s)
                spans.append(s)
            elif ph == "E":
                if stack:
                    stack.pop().e = r["ts"]
            else:  # instant
                if stack:
                    stack[-1].instants.append((r["ts"], r.get("name", "")))
        if stack:
            last_ts = evs[-1]["ts"]
            for s in stack:
                s.e = last_ts
    return [s for s in spans if s.e is not None and s.e >= s.b]


def _union(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for b, e in intervals[1:]:
        if b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]


def _total(intervals: List[Tuple[float, float]]) -> float:
    return sum(e - b for b, e in intervals)


def _subtract(base: List[Tuple[float, float]],
              cut: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """base \\ cut, both already unioned/sorted."""
    out: List[Tuple[float, float]] = []
    ci = 0
    for b, e in base:
        cur = b
        while ci < len(cut) and cut[ci][1] <= cur:
            ci += 1
        j = ci
        while j < len(cut) and cut[j][0] < e:
            cb, ce = cut[j]
            if cb > cur:
                out.append((cur, cb))
            cur = max(cur, ce)
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def analyze(events: List[dict]) -> dict:
    """Produce the per-step critical-path attribution document."""
    spans = reconstruct(events)
    by_cycle: Dict[int, List[Span]] = {}
    for s in spans:
        if s.cycle is not None:
            by_cycle.setdefault(s.cycle, []).append(s)

    steps = []
    totals: Dict[int, Dict[str, float]] = {}
    critical_counts: Dict[int, int] = {}
    covered_total = 0.0
    wall_total = 0.0
    for cycle in sorted(by_cycle):
        group = by_cycle[cycle]
        t0 = min(s.b for s in group)
        t1 = max(s.e for s in group)
        critical = max(group, key=lambda s: s.e)
        phases: Dict[int, Dict[str, float]] = {}
        # step-window intervals that got a phase attribution, any rank —
        # their union vs the wall clock is the step's coverage (the
        # control_path.py idiom: unattributed time is where the tool is
        # blind, and regressions there must be loud).
        covered_iv: List[Tuple[float, float]] = []

        def charge(rank, phase, us):
            if us <= 0:
                return
            phases.setdefault(rank, dict.fromkeys(PHASES, 0.0))[phase] += us
            totals.setdefault(rank, dict.fromkeys(PHASES, 0.0))[phase] += us

        # negotiation wait → the last-ready rank (the one everyone
        # actually waited for), read off the readiness instants the
        # coordinator stamps inside each NEGOTIATE span.
        for s in group:
            if not s.name.startswith("NEGOTIATE_"):
                continue
            ready = [(ts, int(n)) for ts, n in s.instants if n.isdigit()]
            if ready:
                ts_last, rank_last = max(ready)
                charge(rank_last, "negotiation_wait", ts_last - s.b)
                if ts_last > s.b:
                    covered_iv.append((s.b, ts_last))

        ranks = {s.pid for s in group}
        for rank in ranks:
            per_phase: Dict[str, List[Tuple[float, float]]] = \
                {p: [] for p in PHASES}
            for s in group:
                if s.pid != rank:
                    continue
                p = _phase_of(s.name)
                if p is not None:
                    per_phase[p].append((s.b, s.e))
            unions = {p: _union(iv) for p, iv in per_phase.items()}
            # dispatch = op-span time not already attributed elsewhere
            cut = _union([iv
                          for p in ("fanin", "fusion", "wire", "digest",
                                    "reduce")
                          for iv in unions[p]])
            unions["dispatch"] = _subtract(unions["dispatch"], cut)
            for p in ("fanin", "fusion", "wire", "digest", "reduce",
                      "dispatch"):
                charge(rank, p, _total(unions[p]))
                covered_iv.extend(unions[p])

        dominant = {"rank": None, "phase": None, "us": 0.0}
        for rank, d in phases.items():
            for p, us in d.items():
                if us > dominant["us"]:
                    dominant = {"rank": rank, "phase": p, "us": us}
        critical_counts[critical.pid] = \
            critical_counts.get(critical.pid, 0) + 1
        wall = t1 - t0
        cov_us = _total(_union(
            [(max(b, t0), min(e, t1)) for b, e in covered_iv if e > b]))
        cov_us = min(cov_us, wall)
        covered_total += cov_us
        wall_total += wall
        steps.append({
            "cycle": cycle,
            "t0_us": round(t0, 1),
            "duration_us": round(wall, 1),
            "critical_rank": critical.pid,
            "critical_span": critical.name,
            "dominant": {**dominant, "us": round(dominant["us"], 1)},
            "unattributed_us": round(wall - cov_us, 1),
            "coverage": round(cov_us / wall, 4) if wall > 0 else 1.0,
            "phases_us": {str(r): {p: round(us, 1) for p, us in d.items()}
                          for r, d in sorted(phases.items())},
        })

    return {
        "format": "hvd-critical-path-v1",
        "steps": steps,
        "ranks_seen": sorted({s.pid for s in spans if s.pid is not None}),
        "critical_step_counts": {str(r): n for r, n
                                 in sorted(critical_counts.items())},
        "totals_us": {str(r): {p: round(us, 1) for p, us in d.items()}
                      for r, d in sorted(totals.items())},
        "coverage": round(covered_total / wall_total, 4)
        if wall_total > 0 else 1.0,
    }


def render_text(doc: dict, top: int = 10) -> str:
    lines = []
    steps = doc["steps"]
    lines.append(f"critical-path: {len(steps)} step(s), "
                 f"ranks {doc['ranks_seen']}")
    if not steps:
        lines.append("no cycle-tagged spans found — was the run traced "
                     "with HOROVOD_TIMELINE (and lifecycle spans on)?")
        return "\n".join(lines)
    counts = doc["critical_step_counts"]
    worst_rank = max(counts, key=lambda r: counts[r])
    lines.append(f"critical rank by step count: rank {worst_rank} "
                 f"({counts[worst_rank]}/{len(steps)} steps)")
    if "coverage" in doc:
        lines.append(f"attribution coverage: {doc['coverage']:.1%} of "
                     "step wall time carries a phase")
    lines.append("")
    lines.append("aggregate attribution (ms, union of span time per "
                 "rank/phase):")
    hdr = f"  {'rank':>4} " + "".join(f"{p:>17}" for p in PHASES)
    lines.append(hdr)
    for r, d in doc["totals_us"].items():
        lines.append(f"  {r:>4} "
                     + "".join(f"{d[p] / 1e3:>17.3f}" for p in PHASES))
    lines.append("")
    slowest = sorted(steps, key=lambda s: -s["duration_us"])[:top]
    lines.append(f"slowest {len(slowest)} step(s):")
    lines.append(f"  {'cycle':>6} {'ms':>10} {'crit-rank':>9} "
                 f"{'dominant':>28}")
    for s in slowest:
        d = s["dominant"]
        dom = (f"rank {d['rank']} {d['phase']} "
               f"{d['us'] / 1e3:.3f}ms" if d["rank"] is not None else "-")
        lines.append(f"  {s['cycle']:>6} {s['duration_us'] / 1e3:>10.3f} "
                     f"{s['critical_rank']:>9} {dom:>28}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="critical-path",
        description="per-step critical-path attribution over horovod_tpu "
                    "timeline traces (merged or per-rank)")
    ap.add_argument("inputs", nargs="+",
                    help="a merged trace, or per-rank trace files")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the full report as JSON")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest steps to list in the text report "
                         "(default: %(default)s)")
    args = ap.parse_args(argv)

    traces = [load_trace(p) for p in args.inputs]
    events = traces[0] if len(traces) == 1 else merge(traces)
    doc = analyze(events)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
    print(render_text(doc, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
