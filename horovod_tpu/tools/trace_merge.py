"""Merge per-rank Chrome-trace timelines into one cross-rank view.

Each rank writes its own catapult JSON (``core/timeline.py``: ``pid =
rank``, spans tagged with the lockstep negotiation ``cycle`` id, and a
``clock_sync`` metadata record carrying ``wall_base_ns`` — the wall-clock
instant of that trace's ``ts=0`` — plus ``server_offset_ns``, the
Cristian-style offset estimate against the rendezvous server's
``GET /clock``).  This tool rebases every event onto the common
(server) clock and concatenates, so one Perfetto view shows every rank's
NEGOTIATE/op lanes for the same collective — the Dapper-shaped answer to
"which rank is late and why" (docs/observability.md).

Usage::

    python -m horovod_tpu.tools.trace_merge tl.json tl.json.rank1 \\
        -o merged.json
    tools/trace_merge.py /tmp/tl.json*          # repo-root shim, globbed

Alignment: a trace's event at local ``ts`` µs happened at server time
``wall_base_ns/1e3 + ts - server_offset_ns/1e3`` µs; the merged axis is
that, rebased to the earliest trace.  When a file predates clock_sync (or
the offset estimate failed), the merge still works but emits a warning
and falls back to concatenation without shifting — lanes remain correct
per rank, only cross-rank alignment degrades to assumed-synced clocks.

Truncated traces (a rank killed mid-write never wrote the closing ``]``)
are repaired on load: the valid prefix is kept, which is exactly the
writer's crash contract.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence, Tuple

# Shared with the emitter: a rename there must break here at import, not
# silently degrade every merge to the unaligned fallback.
from ..core.timeline import CLOCK_SYNC_EVENT


def load_trace(path: str) -> List[dict]:
    """Load one catapult JSON array, repairing a truncated tail (missing
    ``]``, trailing comma, or a half-written last record)."""
    with open(path) as f:
        text = f.read()
    try:
        events = json.loads(text)
    except ValueError:
        # Crash-truncated trace: drop the partial last record and close
        # the array — every complete record ends its line.
        lines = [ln.rstrip().rstrip(",") for ln in text.splitlines()
                 if ln.strip() and ln.strip() not in ("[", "]")]
        events = []
        for ln in lines:
            try:
                events.append(json.loads(ln))
            except ValueError:
                continue
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a catapult JSON event array")
    return events


def _clock_sync(events: List[dict]) -> Optional[Tuple[float, int]]:
    """(base_us_on_server_clock, rank) from the trace's clock_sync meta:
    the server-clock µs corresponding to this trace's ts=0."""
    for e in events:
        if e.get("name") == CLOCK_SYNC_EVENT and e.get("ph") == "M":
            args = e.get("args", {})
            wall = args.get("wall_base_ns")
            if wall is None:
                return None
            offset = args.get("server_offset_ns") or 0
            return (wall - offset) / 1e3, e.get("pid", args.get("rank", 0))
    return None


def merge(traces: List[List[dict]],
          warn=lambda msg: print(msg, file=sys.stderr)) -> List[dict]:
    """Merge event lists onto one time axis (see module docstring)."""
    syncs = [_clock_sync(t) for t in traces]
    align = all(s is not None for s in syncs) and bool(traces)
    if not align and traces:
        warn("trace_merge: clock_sync metadata missing from at least one "
             "trace; concatenating WITHOUT cross-rank clock alignment")
    t0 = min(s[0] for s in syncs) if align else 0.0
    merged: List[dict] = []
    seen_pids = set()
    for trace, sync in zip(traces, syncs):
        shift = (sync[0] - t0) if align else 0.0
        if sync is not None:
            if sync[1] in seen_pids:
                warn(f"trace_merge: duplicate pid {sync[1]} across input "
                     "traces; lanes will overlap")
            seen_pids.add(sync[1])
        for e in trace:
            if "ts" in e:
                e = dict(e)
                e["ts"] = e["ts"] + shift
            merged.append(e)
    merged.sort(key=lambda e: e.get("ts", -1))
    return merged


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace-merge",
        description="merge per-rank horovod_tpu timeline traces into one "
                    "clock-aligned Chrome/Perfetto trace")
    ap.add_argument("inputs", nargs="+",
                    help="per-rank trace files (tl.json tl.json.rank1 ...)")
    ap.add_argument("-o", "--out", default="merged_timeline.json",
                    help="merged output path (default: %(default)s)")
    args = ap.parse_args(argv)

    traces = [load_trace(p) for p in args.inputs]
    merged = merge(traces)
    with open(args.out, "w") as f:
        json.dump(merged, f)
        f.write("\n")
    ranks = sorted({e.get("pid") for e in merged if "pid" in e})
    print(f"trace-merge: {len(args.inputs)} trace(s), {len(merged)} "
          f"events, pids {ranks} -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
