"""Seeded elastic-protocol bugs ``hvd-mck proto`` must kill.

Same contract as the shm kill suite (mutations.py): each mutation wraps
one REAL step generator — the store's batch kernel or one of the
driver's judgment kernels — and perturbs its op stream into a protocol
bug this control plane was specifically designed against.  The
exhaustive run must kill every one with a named violation and a
reproducing schedule; a surviving mutant means the bounds or the
invariants got too weak, and CI fails the build rather than shrink the
claim.

Wrappers take ``(gen, ctx)``: ``ctx`` is the driver's state dict for
driver-side roles (the stale-epoch mutant needs the current epoch to
forge with) and None for the store.
"""

from __future__ import annotations

import json
from typing import Dict

from ...elastic.driver import (
    STEP_BLACKLIST,
    STEP_GRACE,
    STEP_POLL_HOSTS,
    STEP_TXN,
)
from ...transport.store import STEP_JOURNAL, STEP_REPLY
from .fanin_model import V_FANIN_BIT_LOST, fanin_bits_dropped_wrap
from .mutations import Mutation
from .proto_model import (
    V_ACKED_LOST,
    V_DEMOTED_HOST_KEPT,
    V_LIVE_DROPPED,
    V_RESHARD_EARLY_COMMIT,
    V_RESHARD_FALLBACK_MISSED,
    V_STALE_ACTED,
    V_TORN_GROUP,
)


def _apply_before_journal(gen, ctx):
    """Defer the group-journal append until after the reply: the classic
    WAL inversion.  A crash between the ack and the deferred append
    loses a write the client was promised."""
    held = None
    resp = None
    while True:
        try:
            step = gen.send(resp)
        except StopIteration as fin:
            if held is not None:
                yield held
            return fin.value
        if step[0] == STEP_JOURNAL:
            held = step
            resp = None
            continue
        resp = yield step
        if step[0] == STEP_REPLY and held is not None:
            yield held
            held = None


def _group_split(gen, ctx):
    """Journal a batched transaction as per-op records instead of one
    group frame: a crash between records recovers half the transaction
    — the atomicity the single-frame group encoding exists to buy."""
    resp = None
    while True:
        try:
            step = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        if step[0] == STEP_JOURNAL and len(step[1]) > 1:
            for record in step[1]:
                yield (STEP_JOURNAL, (record,))
            resp = None
            continue
        resp = yield step


def _stale_epoch_check_removed(gen, ctx):
    """Erase the staleness filter by forging every fetched reset request
    and demotion report to carry the current epoch — equivalent to
    deleting the ``epoch == current`` checks from the parsers.  The
    store-side ground truth still holds the real (stale) stamps, so any
    advance these forged reports cause is caught."""
    resp = None
    while True:
        try:
            step = gen.send(resp)
        except StopIteration as fin:
            fetched = fin.value
            for scope in ("reset", "demotion"):
                rewritten = {}
                for ident, raw in (fetched.get(scope) or {}).items():
                    if raw is not None:
                        try:
                            doc = json.loads(bytes(raw).decode())
                            doc["epoch"] = ctx["epoch"]
                            raw = json.dumps(doc).encode()
                        except (ValueError, TypeError):
                            pass
                    rewritten[ident] = raw
                fetched[scope] = rewritten
            return fetched
        resp = yield step


def _blacklist_after_poll(gen, ctx):
    """Move the demotion blacklist AFTER the discovery poll: the shed
    host is still in the very host set the advance is judged on, so the
    new epoch re-rendezvouses with the straggler it just convicted."""
    held = []
    resp = None
    while True:
        try:
            step = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        if step[0] == STEP_BLACKLIST:
            held.append(step)
            resp = None
            continue
        if step[0] == STEP_POLL_HOSTS:
            poll = yield step
            for blk in held:
                yield blk
            held = []
            resp = poll
            continue
        resp = yield step


def _reshard_commit_unguarded(gen, ctx):
    """Forge every fetched survivor epoch-ack to the pending epoch —
    equivalent to deleting the acked-at-epoch guard from
    ``reshard_commit_steps``.  The commit record lands the moment the
    probe runs; the store's ground-truth acks are still real, so the
    early commit is caught server-side."""
    resp = None
    while True:
        try:
            step = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        resp = yield step
        if step[0] == STEP_TXN and step[2] == "reshard_acks":
            epoch = ctx["reshard_pending"]["epoch"]
            resp = [str(epoch).encode() for _ in resp]


def _reshard_fallback_dropped(plan, ctx):
    """Delete the legacy-fallback branch from the publish plan: the
    marker is kept even while a previous reshard sits uncommitted, so
    survivors of the failed reshard — possibly holding blank,
    never-synced state — are strung along instead of degraded to the
    full-teardown path.  NOTE: role ``driver_plan`` wraps the plan DICT
    (not a generator) — the model applies it to ``reshard_plan``'s
    return value at each publish."""
    if not plan["fallback"]:
        return plan
    out = dict(plan)
    out["fallback"] = False
    out["eligible"] = bool(out["survivors"])
    return out


def _regrace_dropped(gen, ctx):
    """Swallow the re-grace arm after a store outage: replayed leases
    read as last-renewed before the outage, so a live worker whose
    renewals could not get through is expired as dead the moment the
    store is back."""
    resp = None
    while True:
        try:
            step = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        if step[0] == STEP_GRACE:
            resp = None
            continue
        resp = yield step


PROTO_MUTATIONS: Dict[str, Mutation] = {m.name: m for m in (
    Mutation(
        "apply_before_journal", role="store", scenario="txn_crash",
        expected=frozenset({V_ACKED_LOST}),
        description="group journal record deferred until after the "
                    "reply ack (WAL ordering inverted)",
        wrap=_apply_before_journal),
    Mutation(
        "group_split", role="store", scenario="txn_crash",
        expected=frozenset({V_TORN_GROUP}),
        description="batched transaction journaled as per-op records "
                    "instead of one atomic group frame",
        wrap=_group_split),
    Mutation(
        "stale_epoch_check_removed", role="driver_reads",
        scenario="stale_race",
        expected=frozenset({V_STALE_ACTED}),
        description="fetched reset/demotion reports forged to the "
                    "current epoch (staleness filter deleted)",
        wrap=_stale_epoch_check_removed),
    Mutation(
        "blacklist_after_poll", role="driver_judgment",
        scenario="np4_demotion",
        expected=frozenset({V_DEMOTED_HOST_KEPT}),
        description="demotion blacklist reordered to after the "
                    "discovery poll it must precede",
        wrap=_blacklist_after_poll),
    Mutation(
        "regrace_dropped", role="driver_recovery",
        scenario="outage_regrace",
        expected=frozenset({V_LIVE_DROPPED}),
        description="lease re-grace window dropped after store-outage "
                    "recovery",
        wrap=_regrace_dropped),
    Mutation(
        "reshard_commit_unguarded", role="driver_reshard",
        scenario="reshard_commit",
        expected=frozenset({V_RESHARD_EARLY_COMMIT}),
        description="survivor epoch-acks forged at the commit probe "
                    "(all-survivors-acked guard deleted)",
        wrap=_reshard_commit_unguarded),
    Mutation(
        "reshard_fallback_dropped", role="driver_plan",
        scenario="reshard_fallback",
        expected=frozenset({V_RESHARD_FALLBACK_MISSED}),
        description="reshard marker kept while a previous reshard is "
                    "still uncommitted (legacy-fallback branch deleted)",
        wrap=_reshard_fallback_dropped),
    Mutation(
        "fanin_bits_dropped", role="fanin_forward",
        scenario="fanin_degrade",
        expected=frozenset({V_FANIN_BIT_LOST}),
        description="aggregator zeroes one member's mask on forward "
                    "while still covering its rank (bits dropped from "
                    "the host fold)",
        wrap=fanin_bits_dropped_wrap),
)}
