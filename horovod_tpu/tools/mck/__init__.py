"""hvd-mck — exhaustive-interleaving model checker for the shm ring.

The shm data plane's correctness argument is an ordering argument: data
bytes land before the head/tail that publishes them, the bell is read
before the ring state it guards, every bell store is chased by a
FUTEX_WAKE, and x86-64's TSO store ordering carries those program
orders to the other core.  Prose arguments about lock-free protocols
have a famous failure rate, so this tool checks the REAL protocol code
— :func:`~horovod_tpu.transport.shm.sender_steps` /
``receiver_steps``, the same generators the production drivers execute
against live segments — by driving it through every schedule up to a
preemption bound under an explicit store-buffer memory model.

Two memory models, selected by ``--mode``:

- ``tso`` (the deployment claim): store buffers drain strictly in FIFO
  order.  The exhaustive run must be clean — no missed wakeup, no lost
  or reordered byte, no unpublished read, no deadlock, every bell store
  paired with a wake, abort reachable from every blocked state.
- ``weak`` (the counterfactual): buffered stores may drain in ANY
  order, i.e. store-store reordering is allowed.  The run must FAIL,
  exhibiting the concrete missed-wakeup schedule the doorbell protocol
  would suffer on a weaker machine (or if a "harmless" refactor let the
  compiler hoist the bell store).  A checker that cannot find the bug
  the protocol was designed against proves nothing by passing.

``--mutants`` runs the seeded-bug suite (mutations.py): four classic
ring-protocol bugs injected into the op stream, each of which the
exhaustive run must kill with a named violation and a minimal
reproducing schedule.  CI wires all three runs into ci/lint.sh; see
docs/static_analysis.md for the full invariant list and how to add a
protocol.

``hvd-mck proto`` (proto_cli.py) is the second protocol under the same
engine: message-reordering + crash model checking of the elastic epoch
control plane — the driver's judgment kernels, the store's batched-
transaction WAL, and the worker-post payload builders, all production
code driven against a model cluster.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from .explore import ExploreResult, check
from .mutations import MUTATIONS
from .report import render_result, summary_line, write_json
from .scenarios import SCENARIOS


def _parser() -> argparse.ArgumentParser:
    par = argparse.ArgumentParser(
        prog="hvd-mck",
        description="bounded-exhaustive model checker for the shm ring "
                    "+ futex doorbell protocol")
    par.add_argument("--mode", choices=("tso", "weak"), default="tso",
                     help="memory model: tso (deployment claim, must "
                          "pass) or weak (store-store reordering, must "
                          "find the missed wakeup)")
    par.add_argument("--scenario", action="append", default=None,
                     metavar="NAME",
                     help="scenario to check (repeatable; default: all)")
    par.add_argument("--preemptions", type=int, default=None,
                     help="override the per-scenario preemption bound")
    par.add_argument("--max-schedules", type=int, default=50000,
                     help="schedule cap per run; hitting it reports the "
                          "run as TRUNCATED, never as proved")
    par.add_argument("--max-steps", type=int, default=600,
                     help="per-schedule action budget (livelock trip)")
    par.add_argument("--mutation", metavar="NAME",
                     help="run one seeded mutation from the kill suite")
    par.add_argument("--mutants", action="store_true",
                     help="run the full mutation-kill suite: exit 0 iff "
                          "every seeded bug is caught")
    par.add_argument("--smoke", action="store_true",
                     help="CI gate: all scenarios under the given mode; "
                          "exit 2 if any run truncated (an incomplete "
                          "exploration must not pass as exhaustive)")
    par.add_argument("--json", metavar="PATH",
                     help="write the machine-readable report here")
    par.add_argument("--no-sleep-sets", action="store_true",
                     help="disable sleep-set pruning (slower; debug aid "
                          "for auditing the reduction)")
    par.add_argument("--list", action="store_true",
                     help="list scenarios and mutations, then exit")
    par.add_argument("-q", "--quiet", action="store_true",
                     help="print only the summary line and violations")
    return par


def _print_listing() -> None:
    print("scenarios:")
    for sc in SCENARIOS.values():
        print(f"  {sc.name:8s} cap={sc.cap} "
              f"send={sc.send_calls} recv={sc.recv_calls} "
              f"abort={sc.abort} preemptions<={sc.preemptions}")
        print(f"           {sc.description}")
    print("mutations (kill suite):")
    for mut in MUTATIONS.values():
        print(f"  {mut.name:22s} [{mut.role} @ {mut.scenario}] "
              f"-> {', '.join(sorted(mut.expected))}")
        print(f"           {mut.description}")


def _run_mutants(args, names: List[str]) -> int:
    results: List[ExploreResult] = []
    unkilled: List[str] = []
    for name in names:
        mut = MUTATIONS[name]
        scenario = SCENARIOS[mut.scenario]
        res = check(scenario, args.mode, mutation=mut,
                    bound=args.preemptions,
                    max_schedules=args.max_schedules,
                    max_steps=args.max_steps,
                    sleep_sets=not args.no_sleep_sets)
        results.append(res)
        caught = set(res.violations) & mut.expected
        if caught:
            if not args.quiet:
                print(render_result(res))
                print(f"  KILLED by {', '.join(sorted(caught))}")
        else:
            unkilled.append(name)
            print(render_result(res))
            found = ", ".join(sorted(res.violations)) or "nothing"
            print(f"  NOT KILLED: expected one of "
                  f"{', '.join(sorted(mut.expected))}, found {found}")
    if args.json:
        write_json(results, args.mode, args.json)
    print(summary_line(results))
    if unkilled:
        print(f"hvd-mck: mutation suite FAILED — surviving mutants: "
              f"{', '.join(unkilled)} (the checker's bounds no longer "
              f"catch seeded bugs)")
        return 1
    print(f"hvd-mck: mutation suite passed — "
          f"{len(names)}/{len(names)} mutants killed")
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "proto":
        # The elastic-epoch-protocol mode: message reordering + crash
        # exploration of the control-plane kernels (proto_cli.py).
        from .proto_cli import proto_main

        return proto_main(argv[1:])
    args = _parser().parse_args(argv)
    if args.list:
        _print_listing()
        return 0

    if args.mutation or args.mutants:
        if args.mutation:
            if args.mutation not in MUTATIONS:
                print(f"hvd-mck: unknown mutation {args.mutation!r} "
                      f"(have: {', '.join(MUTATIONS)})", file=sys.stderr)
                return 2
            names = [args.mutation]
        else:
            names = list(MUTATIONS)
        return _run_mutants(args, names)

    names = args.scenario or list(SCENARIOS)
    for name in names:
        if name not in SCENARIOS:
            print(f"hvd-mck: unknown scenario {name!r} "
                  f"(have: {', '.join(SCENARIOS)})", file=sys.stderr)
            return 2
    results = []
    for name in names:
        res = check(SCENARIOS[name], args.mode, bound=args.preemptions,
                    max_schedules=args.max_schedules,
                    max_steps=args.max_steps,
                    sleep_sets=not args.no_sleep_sets)
        results.append(res)
        if not args.quiet or not res.ok:
            print(render_result(res))
    if args.json:
        write_json(results, args.mode, args.json)
    print(summary_line(results))
    if any(not r.ok for r in results):
        return 1
    if args.smoke and any(r.truncated for r in results):
        print("hvd-mck: smoke run truncated — raise --max-schedules or "
              "shrink the scenario; an incomplete exploration is not a "
              "proof", file=sys.stderr)
        return 2
    return 0
