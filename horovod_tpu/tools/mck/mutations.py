"""Seeded protocol bugs the checker must catch — the checker's checker.

A model checker that silently explores too little is worse than none:
it stamps "proved" on unexplored space.  Each mutation here wraps one
side's REAL step generator and perturbs its op stream into a classic
lock-free-ring bug; ``hvd-mck --mutants`` (and tests/test_mck.py)
asserts the exhaustive run kills every one of them with a named
violation and a minimal reproducing schedule.  If a refactor of the
explorer or the scenarios ever stops killing a mutant, the bounds got
too weak — fail the build, don't shrink the claim.

The wrappers sit between the driver and the generator, so the
production protocol code itself stays untouched: a mutation is "what if
the protocol did X instead", expressed in the same op vocabulary.
"""

from __future__ import annotations

from typing import Callable, Dict, FrozenSet

from ...transport.shm import LOC_BELL_PEER, LOC_HEAD, LOC_TAIL, \
    OP_LOAD, OP_STORE, OP_WAKE
from .model import RECEIVER, SENDER, V_FUTEX_PAIRING, V_LIVELOCK, \
    V_LOST_BYTES, V_MISSED_WAKEUP, V_STALE_BELL, V_STARVATION, \
    V_UNPUBLISHED_READ


class Mutation:
    """One seeded bug: which side it infects, the scenario that best
    exposes it, and the violation classes that count as a kill."""

    __slots__ = ("name", "role", "scenario", "expected", "description",
                 "wrap")

    def __init__(self, name: str, role: str, scenario: str,
                 expected: FrozenSet[str], description: str,
                 wrap: Callable):
        self.name = name
        self.role = role
        self.scenario = scenario
        self.expected = expected
        self.description = description
        self.wrap = wrap

    def to_dict(self) -> dict:
        return {"name": self.name, "role": self.role,
                "scenario": self.scenario,
                "expected": sorted(self.expected),
                "description": self.description}


def _swap_publish_bump(gen):
    """Publish the head AFTER the doorbell wake instead of before: the
    woken peer reads a stale head, finds nothing, and goes back to sleep
    with data already committed — the missed-wakeup the publish-before-
    bump ordering exists to prevent."""
    held = []
    resp = None
    while True:
        try:
            op = gen.send(resp)
        except StopIteration as fin:
            for h in held:
                yield h
            return fin.value
        if op[0] == OP_STORE and op[1] in (LOC_HEAD, LOC_TAIL):
            held.append(op)
            resp = None
            continue
        resp = yield op
        if op[0] == OP_WAKE:
            for h in held:
                yield h
            held = []


def _drop_bell_precheck(gen):
    """Reuse the first bell read forever instead of re-reading before
    every wait: a bump between the stale read and FUTEX_WAIT is
    invisible, so the wait can no longer be cut short — the lost-wakeup
    window the load-bell-BEFORE-ring-state ordering closes."""
    cached = None
    resp = None
    while True:
        try:
            op = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        if op[0] == OP_LOAD and op[1] == LOC_BELL_PEER \
                and op[2] == "precheck":
            if cached is None:
                cached = yield op
            resp = cached
            continue
        resp = yield op


def _free_space_off_by_one(gen):
    """Report the consumer one byte ahead of where it is: free-space
    comes out one too high, the sender overwrites the oldest unread
    byte at the wrap seam, and the receiver lands a wrong sequence
    number — the classic ring off-by-one."""
    resp = None
    while True:
        try:
            op = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        resp = yield op
        if op[0] == OP_LOAD and op[1] == LOC_TAIL:
            resp = resp + 1


def _skip_final_wake(gen):
    """Swallow the FUTEX_WAKE of the final bell bump: the bell moves but
    no sleeper is ever kicked, so a peer already parked on the old value
    burns the full bounded wait — a store without its paired wake."""
    resp = None
    while True:
        try:
            op = gen.send(resp)
        except StopIteration as fin:
            return fin.value
        if op[0] == OP_WAKE and op[1] == "final":
            resp = None
            continue
        resp = yield op


MUTATIONS: Dict[str, Mutation] = {m.name: m for m in (
    Mutation(
        "swap_publish_bump", role=SENDER, scenario="basic",
        expected=frozenset({V_MISSED_WAKEUP, V_STARVATION,
                            V_UNPUBLISHED_READ}),
        description="head published after the doorbell wake instead of "
                    "before it",
        wrap=_swap_publish_bump),
    Mutation(
        "drop_bell_precheck", role=RECEIVER, scenario="wrap",
        expected=frozenset({V_STALE_BELL, V_MISSED_WAKEUP, V_LIVELOCK}),
        description="bell re-read before each wait replaced by the first "
                    "read, cached forever",
        wrap=_drop_bell_precheck),
    Mutation(
        "free_space_off_by_one", role=SENDER, scenario="wrap",
        expected=frozenset({V_LOST_BYTES, V_UNPUBLISHED_READ}),
        description="free-space computed against tail+1: one unread "
                    "byte overwritten at the wrap seam",
        wrap=_free_space_off_by_one),
    Mutation(
        "skip_final_wake", role=SENDER, scenario="basic",
        expected=frozenset({V_FUTEX_PAIRING, V_MISSED_WAKEUP}),
        description="final bell bump stores the new value but never "
                    "issues FUTEX_WAKE",
        wrap=_skip_final_wake),
)}
