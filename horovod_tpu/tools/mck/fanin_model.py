"""``hvd-mck proto`` fan-in mode — crash/reorder checking of the
negotiation fan-in degrade protocol (core/negotiation_fanin.py).

One host's negotiation tree under the same bounded-exhaustive engine as
the epoch protocol: two members and their aggregator announce full
cache-bit masks every cycle, the aggregator folds them through the REAL
production ``fold_host`` kernel into one ``HostMaskFrame`` bundle, and
the coordinator ingests bundles/direct frames, ANDs them into the
agreed mask, and fans replies back (bundle replies relay through the
aggregator).  The explorer crashes the aggregator at every step (free,
like proto crashes) and advances a model clock that stales the
aggregator's heartbeat, driving the degrade path at every possible
point of the cycle.

Checked invariants (the ISSUE's "no bit lost / double-counted"):

- **fanin-bit-lost**: at every completed round the agreed mask must
  contain every bit that ALL covered ranks announced — a bit the whole
  host was ready for must never be silenced by the fold or the degrade.
- **fanin-bit-double**: the agreed mask must never contain a bit some
  covered rank did NOT announce (the coordinator would fire a
  collective on a rank that never declared readiness), and no rank may
  be covered by two frames in one round.
- **fanin-rank-silenced**: every live rank finishes all its cycles —
  degrade-to-direct must leave no member stuck behind a dead or wedged
  aggregator.

Degrade model: members check the heartbeat before acting; staleness
(the clock advanced since the aggregator's last relay, or the dead
aggregator can never touch it again) convicts — a coordinated abort
discards the torn round, vetoes the host, and every survivor re-enters
DIRECT.  Statelessness is what makes this safe and is exactly what the
checker leans on: workers re-announce their FULL mask every cycle, so
the retry round re-delivers everything the aborted round consumed.  A
send to an already-dead aggregator (``PeerGoneError`` in production →
abort → reshard → re-tree) collapses to the same veto-direct outcome
here: the respawned re-treed epoch is bit-equivalent to a fresh model
run, so re-exploring it would add schedules but no new states.

The kill-suite mutant (``fanin_bits_dropped``, proto_mutations.py)
wraps the aggregator's fold stream and zeroes one member's mask on
forward while keeping its rank covered — the classic
missing-treated-as-ready-for-nothing fold bug — and must die by
``fanin-bit-lost``.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

from ...core.messages import HostMaskFrame, MaskFrame, is_host_mask_frame, \
    is_mask_frame
from ...core.negotiation_fanin import fold_host
from .model import Violation

__all__ = [
    "FANIN_DEGRADE",
    "FaninExecution",
    "FaninScenario",
    "V_FANIN_BIT_DOUBLE",
    "V_FANIN_BIT_LOST",
    "V_FANIN_SILENCED",
    "fanin_bits_dropped_wrap",
]

V_FANIN_BIT_LOST = "fanin-bit-lost"
V_FANIN_BIT_DOUBLE = "fanin-bit-double"
V_FANIN_SILENCED = "fanin-rank-silenced"
V_FANIN_MODEL_ERROR = "model-error"  # shared name with proto_model


class FaninScenario:
    """A fan-in degrade scenario — duck-types the attribute surface the
    proto CLI listing and the explorer read (``name`` / ``description``
    / ``preemptions`` / ``ticks`` / ``slots`` / ``store_crashes`` /
    ``driver_crashes``), plus the fan-in specifics.  ``kind`` routes
    :func:`proto_model.proto_execution_factory` here."""

    kind = "fanin"

    __slots__ = ("name", "description", "preemptions", "ticks", "slots",
                 "masks", "clock_steps", "agg_crashes", "store_crashes",
                 "driver_crashes")

    def __init__(self, name: str, description: str, *, preemptions: int,
                 ticks: int, slots: Dict[str, Tuple[int, str]],
                 masks: Dict[str, int], clock_steps: Tuple[float, ...] = (),
                 agg_crashes: int = 0):
        self.name = name
        self.description = description
        self.preemptions = preemptions
        self.ticks = ticks                # negotiation cycles per worker
        self.slots = dict(slots)          # name -> (rank, host)
        self.masks = dict(masks)          # name -> announced mask int
        self.clock_steps = tuple(clock_steps)
        self.agg_crashes = agg_crashes
        self.store_crashes = 0            # proto-listing compatibility
        self.driver_crashes = 0


class FaninExecution:
    """One schedule of the fan-in protocol — duck-types the explorer's
    :class:`Execution` contract (``enabled_actions`` / ``touches`` /
    ``step`` / ``final_check`` / ``violation`` / ``steps``); actions use
    the proto vocabulary so ``proto_unit`` prices them (process steps
    cost preemptions, clock advances and crashes are free environment
    moves)."""

    _TOUCH = frozenset({("w", "fanin")})

    def __init__(self, scenario: FaninScenario, mutation=None,
                 max_steps: int = 600):
        self.scenario = scenario
        self.mutation = mutation
        self.max_steps = max_steps
        self.steps = 0
        self.violation: Optional[Violation] = None
        self.trace: List[str] = []

        # name -> per-worker state; "agg" is the aggregator, the rest
        # are its colocated members.  mode "tree" flips to "direct" for
        # everyone at once on the veto (a vetoed HOST runs direct).
        self.workers: Dict[str, dict] = {}
        for name, (rank, _host) in scenario.slots.items():
            self.workers[name] = {
                "rank": rank, "mask": scenario.masks[name],
                "state": "idle", "via": None, "cycles": 0,
            }
        self.rank_of = {n: w["rank"] for n, w in self.workers.items()}
        self.name_of = {r: n for n, r in self.rank_of.items()}
        self.mode = "tree"
        self.vetoed = False
        self.fallbacks = 0

        # aggregator internals
        self.agg_alive = True
        self.agg_crashes_used = 0
        self.agg_collected: Dict[str, Tuple[int, bytes]] = {}
        self.agg_forwarded = False
        self.relay_pending: Optional[Tuple[int, Tuple[int, ...]]] = None
        self.hb_at = 0.0

        # coordinator internals
        self.coord_inbox: List[Tuple[int, bytes]] = []
        self.replies: Dict[str, int] = {}
        self.completions: List[dict] = []

        # model clock
        self.now = 0.0
        self.clock_idx = 0

    # -- protocol predicates -------------------------------------------

    def _payload(self, name: str) -> bytes:
        return MaskFrame(
            mask=self.workers[name]["mask"].to_bytes(2, "little"),
            shutdown=False).to_bytes()

    def _finished(self, name: str) -> bool:
        return self.workers[name]["cycles"] >= self.scenario.ticks

    def _stale(self) -> bool:
        """Member-side heartbeat verdict.  The clock advancing past the
        aggregator's last relay touch convicts (the 1.5-period window
        collapsed to one model tick); a dead aggregator with the clock
        budget spent convicts too — in production its silence WILL
        outlive any finite window, and without this escape the model
        would deadlock on an artifact of the bounded clock."""
        if self.mode == "direct":
            return False
        if self.now > self.hb_at:
            return True
        return (not self.agg_alive
                and self.clock_idx >= len(self.scenario.clock_steps))

    def _accounted(self) -> Dict[int, int]:
        """rank -> number of inbox frames covering it this round."""
        counts: Dict[int, int] = {}
        for sender, payload in self.coord_inbox:
            if is_host_mask_frame(payload):
                for r in HostMaskFrame.from_bytes(payload).covered:
                    counts[r] = counts.get(r, 0) + 1
            else:
                counts[sender] = counts.get(sender, 0) + 1
        return counts

    def _round_ready(self) -> bool:
        """The coordinator's fixed recv set is satisfied: every live
        unfinished worker's frame landed (directly or via a bundle)."""
        if not self.coord_inbox:
            return False
        accounted = self._accounted()
        for name, w in self.workers.items():
            if self._finished(name):
                continue
            if name == "agg" and not self.agg_alive:
                continue  # a dead rank is excused, not silenced
            if w["rank"] not in accounted:
                return False
        return True

    # -- scheduling interface (explorer-facing) ------------------------

    def enabled_actions(self) -> List[tuple]:
        if self.violation is not None or self.steps >= self.max_steps:
            return []
        acts: List[tuple] = []
        for name in sorted(self.workers):
            if name == "agg":
                if self._agg_enabled():
                    acts.append(("p", name))
            elif self._member_enabled(name):
                acts.append(("p", name))
        if self._round_ready():
            acts.append(("p", "coord"))
        if self.clock_idx < len(self.scenario.clock_steps):
            acts.append(("k", self.clock_idx))
        if (self.agg_alive
                and self.agg_crashes_used < self.scenario.agg_crashes
                and not all(self._finished(n) for n in self.workers)):
            acts.append(("c", "agg"))
        return acts

    def _member_enabled(self, name: str) -> bool:
        w = self.workers[name]
        if self._finished(name):
            return False
        if w["state"] == "idle":
            return True
        # posted: runnable once the reply landed, or once the stale
        # heartbeat lets it convict its way out from behind the tree.
        return name in self.replies or (w["via"] == "agg" and self._stale())

    def _agg_enabled(self) -> bool:
        if not self.agg_alive or self._finished("agg"):
            return False
        w = self.workers["agg"]
        if self.mode == "direct":
            return w["state"] == "idle" or "agg" in self.replies
        if w["state"] == "idle":
            # fold-and-forward: blocks until every member of the FIXED
            # plan has pushed this round's frame (the plan never shrinks
            # mid-epoch — a member that convicts instead aborts everyone).
            members = [n for n in self.workers if n != "agg"
                       and not self._finished(n)]
            return bool(members) and all(n in self.agg_collected
                                         for n in members) \
                and not self.agg_forwarded
        return self.relay_pending is not None

    def touches(self, action: tuple) -> FrozenSet[tuple]:
        """Per-action location footprints for sleep-set pruning,
        computed at the CURRENT state (the ProtoExecution idiom):

        - ``proc:<name>`` — a worker's own state machine.  The abort
          path writes every proc, which is what keeps a conviction
          dependent on everything it resets.
        - ``collect:<name>`` / ``inbox:<name>`` / ``reply:<name>`` —
          the per-sender slices of the aggregator's collect set, the
          coordinator's inbox, and the reply fan-out, so two members
          pushing frames commute (the fold is an AND — order-free).
        - ``agg`` — aggregator liveness + forward/relay bookkeeping:
          crashes, tree-path member sends (they observe liveness), the
          fold, the relay, and the coordinator's reply routing.
        - ``clock`` / ``hb`` — staleness inputs: written by clock
          advances and the relay's heartbeat touch, read by every
          tree-path member action.

        Over-approximation stays sound; UNDER-approximation is guarded
        by tests/test_mck_proto.py's reduced-vs-unreduced diff on this
        scenario."""
        kind = action[0]
        if kind == "k":
            return frozenset({("w", "clock")})
        if kind == "c":
            touch = {("w", "agg"), ("w", "proc:agg"), ("w", "reply:agg")}
            for n in self.workers:
                touch.add(("w", f"collect:{n}"))
            return frozenset(touch)
        name = action[1]
        if name == "coord":
            touch = {("w", "proc:coord"), ("w", "agg")}
            for n in self.workers:
                touch.add(("w", f"inbox:{n}"))
                touch.add(("w", f"reply:{n}"))
            return frozenset(touch)
        w = self.workers[name]
        if name == "agg":
            if self.mode == "direct":
                if w["state"] == "idle":
                    return frozenset({("w", "proc:agg"),
                                      ("w", "inbox:agg")})
                return frozenset({("w", "proc:agg"), ("w", "reply:agg")})
            if w["state"] == "idle":
                touch = {("w", "proc:agg"), ("w", "agg"),
                         ("w", "inbox:agg")}
                for n in self.workers:
                    touch.add(("w", f"collect:{n}"))
                return frozenset(touch)
            touch = {("w", "proc:agg"), ("w", "agg"), ("w", "hb"),
                     ("r", "clock")}
            for n in self.workers:
                touch.add(("w", f"reply:{n}"))
            return frozenset(touch)
        # members
        if w["state"] == "idle" and self.mode == "direct":
            return frozenset({("w", f"proc:{name}"),
                              ("w", f"inbox:{name}")})
        if w["state"] == "posted" and name in self.replies:
            return frozenset({("w", f"proc:{name}"),
                              ("w", f"reply:{name}")})
        if w["state"] == "idle" and self.agg_alive and not self._stale():
            # tree-path push: observes liveness + heartbeat, lands in
            # the aggregator's collect slice
            return frozenset({("w", f"proc:{name}"),
                              ("w", f"collect:{name}"), ("r", "agg"),
                              ("r", "clock"), ("r", "hb")})
        # conviction / dead-aggregator send: the coordinated abort
        # resets everyone — it conflicts with the world.
        touch = {("w", "agg"), ("r", "clock"), ("r", "hb")}
        for n in self.workers:
            touch.add(("w", f"proc:{n}"))
            touch.add(("w", f"collect:{n}"))
            touch.add(("w", f"inbox:{n}"))
            touch.add(("w", f"reply:{n}"))
        return frozenset(touch)

    def step(self, action: tuple) -> None:
        self.steps += 1
        kind = action[0]
        if kind == "p" and action[1] == "coord":
            self.trace.append("p:coord")
            self._coord_step()
        elif kind == "p" and action[1] == "agg":
            self.trace.append("p:agg")
            self._agg_step()
        elif kind == "p":
            self.trace.append(f"p:{action[1]}")
            self._member_step(action[1])
        elif kind == "k":
            delta = self.scenario.clock_steps[action[1]]
            self.trace.append(f"k:+{delta:g}")
            self.clock_idx += 1
            self.now += delta
        elif kind == "c":
            self.trace.append("c:agg-crash")
            self.agg_crashes_used += 1
            self.agg_alive = False
            # frames it collected but never forwarded die with it, as
            # does an unrelayed reply — exactly the consumed-but-lost
            # window statelessness must heal.
            self.agg_collected = {}
            self.relay_pending = None
            self.replies.pop("agg", None)
        else:
            self._fail(V_FANIN_MODEL_ERROR, f"unknown action {action!r}")

    # -- member / aggregator / coordinator steps -----------------------

    def _member_step(self, name: str) -> None:
        w = self.workers[name]
        if w["state"] == "idle":
            if self.mode == "direct":
                self.coord_inbox.append((w["rank"], self._payload(name)))
                w["state"], w["via"] = "posted", "coord"
            elif self._stale():
                self._abort_and_veto(f"{name} convicted a stale heartbeat")
            elif not self.agg_alive:
                # PeerGoneError on the send: coordinated abort; the
                # production re-treed retry collapses to direct here
                # (see module docstring).
                self._abort_and_veto(f"{name} hit a dead aggregator")
            else:
                self.agg_collected[name] = (w["rank"], self._payload(name))
                w["state"], w["via"] = "posted", "agg"
            return
        if name in self.replies:
            self.replies.pop(name)
            w["state"], w["via"] = "idle", None
            w["cycles"] += 1
        elif w["via"] == "agg" and self._stale():
            self._abort_and_veto(
                f"{name} convicted a stale heartbeat waiting for the relay")
        else:
            self._fail(V_FANIN_MODEL_ERROR,
                       f"{name} stepped with nothing to do")

    def _agg_step(self) -> None:
        w = self.workers["agg"]
        if self.mode == "direct":
            if w["state"] == "idle":
                self.coord_inbox.append((w["rank"], self._payload("agg")))
                w["state"], w["via"] = "posted", "coord"
            else:
                self.replies.pop("agg")
                w["state"], w["via"] = "idle", None
                w["cycles"] += 1
            return
        if w["state"] == "idle":
            entries = [(w["rank"], self._payload("agg"))]
            entries += [self.agg_collected[n]
                        for n in sorted(self.agg_collected)]
            stream = iter(entries)
            if self.mutation is not None \
                    and self.mutation.role == "fanin_forward":
                stream = self.mutation.wrap(stream,
                                            {"agg_rank": w["rank"]})
            # the REAL production fold — the kernel under check
            self.coord_inbox.extend(fold_host(list(stream)))
            self.agg_collected = {}
            self.agg_forwarded = True
            w["state"] = "posted"
            return
        # relay: fan the agreed mask down to every covered member,
        # consume the aggregator's own share, and touch the heartbeat —
        # a relay that completed IS the liveness signal.
        agreed, covered = self.relay_pending
        self.relay_pending = None
        for r in covered:
            name = self.name_of.get(r)
            if name is None or name == "agg":
                continue
            self.replies[name] = agreed
        w["state"], w["via"] = "idle", None
        w["cycles"] += 1
        self.agg_forwarded = False
        self.hb_at = self.now

    def _coord_step(self) -> None:
        inbox, self.coord_inbox = self.coord_inbox, []
        agreed: Optional[int] = None
        counts: Dict[int, int] = {}
        bundle_covered: Tuple[int, ...] = ()
        for sender, payload in inbox:
            if is_host_mask_frame(payload):
                frame = HostMaskFrame.from_bytes(payload)
                for r in frame.covered:
                    counts[r] = counts.get(r, 0) + 1
                bundle_covered = tuple(frame.covered)
                mask = frame.mask_int
            elif is_mask_frame(payload):
                counts[sender] = counts.get(sender, 0) + 1
                mask = MaskFrame.from_bytes(payload).mask_int
            else:
                self._fail(V_FANIN_MODEL_ERROR,
                           f"coordinator ingested a non-mask frame "
                           f"from rank {sender}")
                return
            agreed = mask if agreed is None else agreed & mask

        doubled = sorted(r for r, c in counts.items() if c > 1)
        if doubled:
            self._fail(V_FANIN_BIT_DOUBLE,
                       f"rank(s) {doubled} covered by more than one frame "
                       "in a single round — their bits were counted twice")
            return
        truth = None
        for r in counts:
            name = self.name_of.get(r)
            if name is None:
                self._fail(V_FANIN_BIT_DOUBLE,
                           f"round covered unknown rank {r} — bits were "
                           "invented for a rank that never announced")
                return
            m = self.workers[name]["mask"]
            truth = m if truth is None else truth & m
        if truth & ~agreed:
            self._fail(V_FANIN_BIT_LOST,
                       f"agreed mask {agreed:#06x} lost bit(s) "
                       f"{truth & ~agreed:#06x} that every covered rank "
                       "announced — a ready-everywhere tensor was silenced "
                       "by the fold")
            return
        if agreed & ~truth:
            self._fail(V_FANIN_BIT_DOUBLE,
                       f"agreed mask {agreed:#06x} carries bit(s) "
                       f"{agreed & ~truth:#06x} outside some covered "
                       "rank's announced set — a collective would fire on "
                       "a rank that never declared readiness")
            return
        self.completions.append({
            "round": len(self.completions), "agreed": agreed,
            "covered": tuple(sorted(counts)), "ingress_frames": len(inbox),
        })
        for sender, payload in inbox:
            if is_host_mask_frame(payload):
                # the bundle reply rides back through the aggregator
                self.relay_pending = (agreed, bundle_covered)
            else:
                self.replies[self.name_of[sender]] = agreed

    # -- degrade -------------------------------------------------------

    def _abort_and_veto(self, why: str) -> None:
        """Coordinated abort + veto: the torn round is discarded on
        every path (inbox, collected frames, undelivered replies), the
        host is convicted, and every survivor re-enters DIRECT at its
        current cycle — where it re-announces its FULL mask, which is
        why nothing the dead round consumed is lost."""
        self.trace.append(f"abort:{why}")
        self.fallbacks += 1
        self.vetoed = True
        self.mode = "direct"
        self.coord_inbox = []
        self.agg_collected = {}
        self.agg_forwarded = False
        self.relay_pending = None
        self.replies = {}
        for w in self.workers.values():
            if w["cycles"] < self.scenario.ticks:
                w["state"], w["via"] = "idle", None

    # -- verdicts ------------------------------------------------------

    def final_check(self) -> Optional[Violation]:
        if self.violation is not None:
            return self.violation
        for name in sorted(self.workers):
            if name == "agg" and not self.agg_alive:
                continue
            if not self._finished(name):
                return Violation(
                    V_FANIN_SILENCED,
                    f"rank {self.rank_of[name]} ({name}) finished only "
                    f"{self.workers[name]['cycles']}/{self.scenario.ticks} "
                    f"cycles (steps={self.steps}/{self.max_steps}) — the "
                    "degrade path left it stuck behind the aggregator",
                    list(self.trace))
        if len(self.completions) < self.scenario.ticks:
            return Violation(
                V_FANIN_MODEL_ERROR,
                f"only {len(self.completions)} completed rounds for "
                f"{self.scenario.ticks} cycles", list(self.trace))
        return None

    def _fail(self, name: str, detail: str) -> None:
        if self.violation is None:
            self.violation = Violation(name, detail, list(self.trace))


def fanin_bits_dropped_wrap(gen, ctx):
    """The seeded fold bug: zero the FIRST member MaskFrame in the
    aggregator's forward stream while keeping its rank covered — the
    member's announced bits silently vanish from the AND, so the agreed
    mask loses bits the whole host was ready for (``fanin-bit-lost``)."""
    dropped = False
    for rank, payload in gen:
        if not dropped and rank != ctx["agg_rank"] and is_mask_frame(payload):
            frame = MaskFrame.from_bytes(payload)
            yield rank, MaskFrame(mask=b"", shutdown=frame.shutdown).to_bytes()
            dropped = True
        else:
            yield rank, payload


#: Distinct per-rank masks so any fold corruption is attributable: the
#: exact agreed mask of a clean round is 0b0010 (the only bit all three
#: ranks announce); dropping m4's bits zeroes it (bit-lost), dropping
#: m4's ENTRY would resurrect 0b0100 (bit-double).
FANIN_DEGRADE = FaninScenario(
    "fanin_degrade",
    "one host's negotiation tree (aggregator + 2 members) over 2 "
    "cycles with the aggregator crashed at any step and the heartbeat "
    "staled by a clock jump: every degrade interleaving must fall back "
    "to direct pushes with no mask bit lost or double-counted and no "
    "rank silenced",
    preemptions=3, ticks=2,
    slots={"agg": (3, "h001"), "m4": (4, "h001"), "m5": (5, "h001")},
    masks={"agg": 0b0111, "m4": 0b1011, "m5": 0b1110},
    clock_steps=(1.0,), agg_crashes=1)
