"""Bounded workloads the checker proves the ring protocol over.

Each scenario pins a ring capacity, a list of per-CALL segment-length
lists for each side (one generator instance per call — the per-call
bell discipline is part of the contract under test), whether a mesh
abort may fire, and the preemption budget the exhaustive run uses.
Small on purpose: the protocol's state machine has no data-dependent
branching beyond "is there room / is there data", so capacity-wrap,
multi-call FIFO, full-ring blocking, and abort-while-blocked between
them exercise every edge the production ring can take, at depths the
exhaustive explorer finishes in seconds.

The preemption budgets are one above where each scenario's search space
stops yielding new behavior classes — and the mutation-kill suite
(tests/test_mck.py) demonstrates every seeded bug is caught within
them.
"""

from __future__ import annotations

from typing import Dict

from .model import Scenario

SCENARIOS: Dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        "basic", cap=8, send_calls=[[2]], recv_calls=[[2]], abort=False,
        description="one small segment, no wraparound: doorbell "
                    "handshake and final-bump pairing in isolation",
        preemptions=3),
    Scenario(
        "wrap", cap=2, send_calls=[[3]], recv_calls=[[3]], abort=False,
        description="3 bytes through a 2-byte ring: position wraparound, "
                    "free-space math at the seam, full-ring sender waits",
        preemptions=3),
    Scenario(
        "frames", cap=2, send_calls=[[1], [2]], recv_calls=[[1], [2]],
        abort=False,
        description="two back-to-back calls per side (second wraps): "
                    "per-call bell bump discipline and FIFO across "
                    "call boundaries",
        preemptions=2),
    Scenario(
        "abort", cap=2, send_calls=[[3]], recv_calls=[[3]], abort=True,
        description="mesh abort may fire at any point, including with "
                    "the sender blocked on a full ring: bounded-wait "
                    "abort reachability, no abandoned sleeper",
        preemptions=2),
)}
