"""Bounded workloads for ``hvd-mck proto``.

Each scenario is one small cluster — a driver ticking the production
judgment kernels, workers posting through the production payload
builders, optionally the coordinator's real DemotionPolicy — plus
explicit crash and clock budgets.  Crash and clock actions are
environment moves (preemption-free), so a scenario with
``store_crashes=1`` explores the crash at EVERY schedule position,
including between a batched transaction's journal append and its ack.

The clean suite must pass COMPLETE (never truncated); the kill suite
(proto_mutations.py) asserts each seeded protocol bug dies in the
scenario named here.  Sizing note: scenarios are deliberately tiny —
the explorer replays prefixes generator-by-generator, and the claim is
per-protocol-phase, not per-fleet.  Grow a scenario only with a bound
check (``--smoke`` trips exit 2 on truncation).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...elastic.rendezvous_client import (
    DEMOTION_REPORT_SCOPE,
    RANK_AND_SIZE_SCOPE,
    RESET_REQUEST_SCOPE,
    demotion_report_payload,
    reset_request_payload,
)
from ...transport.scopes import LEASE_SCOPE


class ProtoScenario:
    """One bounded cluster workload (see module docstring)."""

    __slots__ = ("name", "description", "preemptions", "ticks", "epoch0",
                 "lease_timeout", "slots", "workers", "coordinator",
                 "seeds", "clock_steps", "store_crashes", "driver_crashes",
                 "active_np", "reshard")

    def __init__(self, name: str, description: str, preemptions: int,
                 ticks: int, slots: Dict[str, Tuple[int, str]],
                 epoch0: int = 0, lease_timeout: float = 10.0,
                 workers: Optional[List[dict]] = None,
                 coordinator: Optional[dict] = None,
                 seeds: Optional[List[List[tuple]]] = None,
                 clock_steps: Optional[List[float]] = None,
                 store_crashes: int = 0, driver_crashes: int = 0,
                 active_np: Optional[int] = None,
                 reshard: bool = False):
        self.name = name
        self.description = description
        self.preemptions = preemptions
        self.ticks = ticks
        self.epoch0 = epoch0
        self.lease_timeout = lease_timeout
        self.slots = dict(slots)
        self.workers = list(workers or [])
        self.coordinator = coordinator
        self.seeds = [list(s) for s in (seeds or [])]
        self.clock_steps = list(clock_steps or [])
        self.store_crashes = store_crashes
        self.driver_crashes = driver_crashes
        self.active_np = len(slots) if active_np is None else active_np
        # Zero-restart resharding enabled for the model driver: epoch
        # publishes run the real reshard_plan (marker / fallback) and
        # each tick probes reshard_commit_steps.  Off by default so the
        # PR-18 scenarios keep their exact proven state spaces.
        self.reshard = reshard


def _lease_seed(identity: str, rank: int, epoch: int) -> tuple:
    import json

    return ("set", LEASE_SCOPE, identity,
            json.dumps({"rank": rank, "epoch": epoch,
                        "renewals": 0}).encode())


def _slot_seed(identity: str, rank: int, epoch: int, host: str) -> tuple:
    import json

    return ("set", RANK_AND_SIZE_SCOPE, identity,
            json.dumps({"rank": rank, "epoch": epoch,
                        "hostname": host}).encode())


PROTO_SCENARIOS: Dict[str, ProtoScenario] = {s.name: s for s in (
    ProtoScenario(
        "tick_posts",
        "two workers renew leases while one posts a current-epoch reset "
        "request, racing two driver ticks: the tick-vs-worker-posts "
        "interleavings, including a post landing between a tick's fetch "
        "and its judgment",
        preemptions=2, ticks=2,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",), ("renew",)]},
            {"name": "w1", "identity": "h1:0", "rank": 1, "epoch": 0,
             "script": [("reset", 0, "corruption abort"), ("renew",)]},
        ]),
    ProtoScenario(
        "txn_crash",
        "one 2-op batched transaction (metrics snapshot + lease renewal) "
        "with a store crash explored at every micro-step: the WAL "
        "ordering and group-atomicity proof (acked writes durable, no "
        "recoverable half-transaction)",
        preemptions=2, ticks=1,
        slots={"h0:0": (0, "h0")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",)]},
        ],
        store_crashes=1),
    ProtoScenario(
        "stale_race",
        "a reset request and a demotion report from epoch 0 sit in the "
        "store while the driver judges at epoch 1: stale reports must "
        "never advance anything",
        preemptions=2, ticks=1, epoch0=1,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 1,
             "script": [("renew",)]},
        ],
        seeds=[
            [("set", RESET_REQUEST_SCOPE, "h0:0",
              reset_request_payload(0, "corruption abort"))],
            [("set", DEMOTION_REPORT_SCOPE, "h1:0",
              demotion_report_payload(0, 1, "h1", 9.9, 1.0, 2, 0.0))],
        ],
        active_np=4),
    ProtoScenario(
        "lease_expiry",
        "one worker keeps renewing while another stops, and the clock "
        "jumps past the lease timeout between ticks: expiry-vs-renewal "
        "races, with expiry legitimate only outside a re-grace window",
        preemptions=2, ticks=3, lease_timeout=10.0,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",), ("renew",), ("renew",)]},
            {"name": "w1", "identity": "h1:0", "rank": 1, "epoch": 0,
             "script": [("renew",)]},
        ],
        clock_steps=[11.0]),
    ProtoScenario(
        "outage_regrace",
        "the store crashes (possibly failing a driver fetch) and the "
        "clock jumps past the lease timeout: after an observed outage "
        "the driver must re-grace every lease before it may expire one",
        preemptions=2, ticks=3, lease_timeout=10.0,
        slots={"h0:0": (0, "h0")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",), ("renew",)]},
        ],
        clock_steps=[11.0], store_crashes=1),
    ProtoScenario(
        "np2_demotion",
        "a 2-rank world with one rank chronically over threshold: the "
        "real DemotionPolicy must never post a verdict (one slow rank "
        "IS half the world), and the store flags any report that lands",
        preemptions=2, ticks=1,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        coordinator={"identity": "h0:0", "epoch": 0, "demote_secs": 1.0,
                     "demote_cycles": 2, "active": (0, 1),
                     "observations": [{1: 9.0}, {1: 9.0}, {1: 9.0}]},
        active_np=2),
    ProtoScenario(
        "np4_demotion",
        "a 4-rank world where rank 3 stays over threshold for the full "
        "streak: the real DemotionPolicy convicts it, the driver must "
        "blacklist the host STRICTLY before this tick's discovery poll, "
        "then advance cause-tagged demotion",
        preemptions=2, ticks=2,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1"),
               "h2:0": (2, "h2"), "h3:0": (3, "h3")},
        coordinator={"identity": "h0:0", "epoch": 0, "demote_secs": 1.0,
                     "demote_cycles": 2, "active": (0, 1, 2, 3),
                     "observations": [{3: 10.0}, {3: 10.0}]},
        active_np=4),
    ProtoScenario(
        "driver_crash_recovery",
        "a current-epoch reset request drives an advance while the "
        "driver may crash at any step and restart through recover_steps: "
        "the restarted driver must adopt exactly the journal-replayed "
        "epoch and never act on the now-stale request twice",
        preemptions=2, ticks=2, lease_timeout=10.0,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("reset", 0, "rollback"), ("renew",)]},
        ],
        seeds=[
            [_slot_seed("h0:0", 0, 0, "h0"), _lease_seed("h0:0", 0, 0)],
            [_slot_seed("h1:0", 1, 0, "h1"), _lease_seed("h1:0", 1, 0)],
        ],
        driver_crashes=1),
    ProtoScenario(
        "reshard_commit",
        "zero-restart reshard round-trip with a store crash explored at "
        "every step: one worker goes silent and expires after a clock "
        "jump, the advance publishes the reshard-marked table, the "
        "survivor acks the epoch, and the driver's commit probe may "
        "write the commit record ONLY once every survivor's ack is on "
        "record (publish -> survivor-ack -> topology-commit)",
        preemptions=2, ticks=3, lease_timeout=10.0,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",), ("ack", 1)]},
        ],
        seeds=[
            [_slot_seed("h0:0", 0, 0, "h0"), _lease_seed("h0:0", 0, 0)],
            [_slot_seed("h1:0", 1, 0, "h1"), _lease_seed("h1:0", 1, 0)],
        ],
        clock_steps=[11.0], store_crashes=1, reshard=True),
    ProtoScenario(
        "reshard_driver_crash",
        "the driver may crash at any step of a reshard (before the "
        "marked publish, between publish and commit, after commit) and "
        "restart through recover_steps: the pending reshard dies with "
        "the driver's memory and the recovery republish (unmarked, at "
        "the adopted epoch) must retire it — a crashed driver degrades "
        "the reshard to the legacy path, never strings survivors along",
        preemptions=2, ticks=3, lease_timeout=10.0,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",), ("ack", 1)]},
        ],
        seeds=[
            [_slot_seed("h0:0", 0, 0, "h0"), _lease_seed("h0:0", 0, 0)],
            [_slot_seed("h1:0", 1, 0, "h1"), _lease_seed("h1:0", 1, 0)],
        ],
        clock_steps=[11.0], driver_crashes=1, reshard=True),
    ProtoScenario(
        "reshard_fallback",
        "a survivor crashes mid-reshard (its epoch ack never lands "
        "before a current-epoch reset forces the next advance): the "
        "still-pending reshard must drop the marker from the next "
        "publish — the degradation to the legacy full-teardown path is "
        "load-bearing, survivors of a failed reshard may hold blank "
        "never-synced state",
        preemptions=2, ticks=3, lease_timeout=10.0,
        slots={"h0:0": (0, "h0"), "h1:0": (1, "h1")},
        workers=[
            {"name": "w0", "identity": "h0:0", "rank": 0, "epoch": 0,
             "script": [("renew",), ("reset", 1, "peer hard-crash"),
                        ("ack", 1)]},
        ],
        seeds=[
            [_slot_seed("h0:0", 0, 0, "h0"), _lease_seed("h0:0", 0, 0)],
            [_slot_seed("h1:0", 1, 0, "h1"), _lease_seed("h1:0", 1, 0)],
        ],
        clock_steps=[11.0], reshard=True),
)}

# The negotiation fan-in degrade scenario rides the same registry so the
# CLI, the smoke gate, and the kill suite cover it with zero extra
# plumbing; its execution model lives in fanin_model.py and is routed by
# scenario.kind in proto_model.proto_execution_factory.
from .fanin_model import FANIN_DEGRADE  # noqa: E402

PROTO_SCENARIOS[FANIN_DEGRADE.name] = FANIN_DEGRADE
