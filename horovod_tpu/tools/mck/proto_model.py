"""Message-passing + crash execution model for the elastic epoch protocol.

``hvd-mck proto`` checks the control plane the same way the shm mode
checks the ring (model.py): the protocol logic under test is the REAL
production code — the driver's epoch-judgment generators
(:mod:`horovod_tpu.elastic.driver`: ``tick_read_steps`` /
``tick_judgment_steps`` / ``outage_recovery_steps`` / ``recover_steps``),
the store's batched-transaction kernel
(:func:`horovod_tpu.transport.store.batch_steps`), the worker-post
payload builders (:mod:`horovod_tpu.elastic.rendezvous_client`), and the
straggler :class:`~horovod_tpu.core.controller.DemotionPolicy` — driven
here against a model cluster instead of live sockets:

- **Processes** (driver "drv", workers "w*", coordinator "coord") are
  glue generators that yield ``("send", ops, tag)`` to put one batched
  transaction on the store's wire, or ``("pause", label)`` at a protocol
  phase boundary.  Each yield is a scheduling point.
- **The store** is one sequential server with a keyed inbox: delivery
  order is a scheduling choice (``("s", (client, seq))`` picks ANY
  queued request), which models message reordering across senders, and
  the keying makes enqueue order irrelevant to the state — two clients'
  sends genuinely commute, which the sleep-set footprints
  (:meth:`ProtoExecution.touches`) rely on; service itself advances one
  ``batch_steps`` micro-op per ``("t",)`` action, so a crash can land
  between any two store steps — including between the group-journal
  append and the reply ack.
- **The journal** is a byte blob of ``pack_frame`` frames, exactly the
  on-disk format (transport/journal.py).  Crash recovery replays it with
  the production longest-valid-prefix rule.  A byte-level torn tail
  truncates to a frame boundary, so checking every FRAME-boundary prefix
  state covers every byte-level crash point (tests/test_mck_proto.py
  asserts this equivalence on a real blob, byte by byte).
- **Crashes** are explicit actions: ``("c", "st")`` kills the store at
  the current micro-step (in-flight and queued requests error back to
  their callers; state recovers by journal replay), ``("c", "drv")``
  kills the driver and restarts it through the production
  ``recover_steps`` kernel.  ``("k", i)`` advances the lease clock by
  the scenario's i-th increment.  All three are environment actions —
  free under the preemption bound — so every schedule in a crash-budget
  scenario includes the crash, at an explored position.

Invariants (violation vocabulary below):

- epoch monotonicity at the store, and at most one STEP_ADVANCE per
  judged tick at the driver;
- every transaction the store ACKED is durable across a crash at every
  point (the WAL ordering: group journal strictly before first apply,
  reply strictly after);
- every journal frame boundary is a transaction boundary (group
  atomicity — no torn half-transaction state is ever recoverable);
- a stale (prior-epoch) reset request or demotion report never advances
  the epoch, judged against the STORE's ground truth of what it served,
  which a driver-side mutant cannot rewrite;
- a demotion report never lands at np <= 2 (structural: the
  whole-world-slow guard makes one slow rank half the world);
- a live-leased identity is never dropped inside the post-outage
  re-grace window;
- a restarted driver adopts exactly the epoch the journal-backed store
  served it — never 0, never a stale predecessor;
- a zero-restart reshard commit record never lands before every
  survivor the marked publish listed has acked that epoch, judged on
  the store's own data (V_RESHARD_EARLY_COMMIT);
- a reshard-marked slot table never publishes while an older marked
  epoch sits uncommitted — the degradation to the legacy full-teardown
  path is mandatory, not best-effort (V_RESHARD_FALLBACK_MISSED).
"""

from __future__ import annotations

import json
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ...core.controller import DemotionPolicy
from ...elastic.driver import (
    DRIVER_SCOPE,
    STEP_ADVANCE,
    STEP_BLACKLIST,
    STEP_CLOCK,
    STEP_EXPIRE,
    STEP_GATE,
    STEP_GRACE,
    STEP_POLL_HOSTS,
    STEP_TXN,
    outage_recovery_steps,
    recover_steps,
    reshard_commit_steps,
    reshard_plan,
    tick_judgment_steps,
    tick_read_steps,
)
from ...elastic.rendezvous_client import (
    DEMOTION_REPORT_SCOPE,
    EPOCH_ACK_SCOPE,
    RANK_AND_SIZE_SCOPE,
    RESET_REQUEST_SCOPE,
    demotion_report_payload,
    lease_renew_ops,
    reset_request_payload,
)
from ...transport.journal import (
    JOURNAL_MAGIC,
    OP_DELETE,
    OP_GROUP,
    OP_SET,
    decode_group,
    decode_op,
    encode_group,
    iter_frames,
    pack_frame,
)
from ...transport.store import (
    STEP_APPLY,
    STEP_JOURNAL,
    STEP_KEYS,
    STEP_LOAD,
    STEP_NOTIFY,
    STEP_REPLY,
    batch_steps,
)
from .model import Violation

__all__ = [
    "ProtoExecution", "proto_execution_factory", "proto_unit",
    "demotion_report_payload", "reset_request_payload",
    "V_EPOCH_REGRESSION", "V_MULTI_ADVANCE", "V_ACKED_LOST",
    "V_TORN_GROUP", "V_STALE_ACTED", "V_SMALL_WORLD_DEMOTION",
    "V_LIVE_DROPPED", "V_DEMOTED_HOST_KEPT", "V_RECOVER_MISMATCH",
    "V_RESHARD_EARLY_COMMIT", "V_RESHARD_FALLBACK_MISSED",
    "V_MODEL_ERROR",
]

#: Violation names — the proto checker's vocabulary, referenced by the
#: kill suite (proto_mutations.py), tests, and docs/static_analysis.md.
V_EPOCH_REGRESSION = "epoch-regression"
V_MULTI_ADVANCE = "multi-advance"
V_ACKED_LOST = "acked-op-lost"
V_TORN_GROUP = "torn-group"
V_STALE_ACTED = "stale-report-acted"
V_SMALL_WORLD_DEMOTION = "small-world-demotion"
V_LIVE_DROPPED = "live-lease-dropped"
V_DEMOTED_HOST_KEPT = "demoted-host-kept"
V_RECOVER_MISMATCH = "recover-epoch-mismatch"
V_RESHARD_EARLY_COMMIT = "reshard-early-commit"
V_RESHARD_FALLBACK_MISSED = "reshard-fallback-missed"
V_MODEL_ERROR = "model-error"

RUNNABLE = "runnable"
WAITING = "waiting"
FINISHED = "finished"

_EPOCH_KEY = f"{DRIVER_SCOPE}/epoch"
_RESHARD_COMMIT_KEY = f"{DRIVER_SCOPE}/reshard_commit"

#: Reply sentinels: not-yet-served vs served-with-a-store-error.
_PENDING = object()
_ERROR = object()


class _StoreDown(Exception):
    """Raised INTO a glue generator when its in-flight transaction died
    with the store (the model's URLError/ConnectionError)."""


def proto_unit(action: tuple) -> str:
    """Scheduling unit for preemption accounting: each process is a
    unit, the store (inbox pop + micro-steps) is one unit, and clock
    advancement / crashes are the environment (free — a crash is never
    a scheduler preemption, so crash-at-every-point costs no budget)."""
    kind = action[0]
    if kind == "p":
        return action[1]
    if kind in ("s", "t"):
        return "st"
    return "env"


def _fold_ops(state: Dict[str, bytes], ops) -> Dict[str, bytes]:
    """The post-state one batched transaction commits over ``state`` —
    ground truth straight from the op list, shared with no production
    code path, so a store-side mutant cannot bend both sides at once."""
    out = dict(state)
    for op in ops:
        if op[0] == "check":
            # CAS guard, evaluated against the overlay exactly as
            # batch_steps does: a mismatch aborts the WHOLE batch, so
            # its only legal post-state is the untouched pre-state.
            if out.get(f"{op[1]}/{op[2]}") != op[3]:
                return dict(state)
        elif op[0] == "set":
            out[f"{op[1]}/{op[2]}"] = op[3]
        elif op[0] == "delete":
            out.pop(f"{op[1]}/{op[2]}", None)
    return out


def _journal_records(blob: bytes):
    """Yield every (op, key, value) in the journal's valid prefix, in
    order, expanding group frames — the replay view of the blob."""
    first = True
    for _end, payload in iter_frames(blob):
        if first:
            first = False
            if payload != JOURNAL_MAGIC:
                return
            continue
        if payload and payload[0] == OP_GROUP:
            records = decode_group(payload)
        else:
            records = [decode_op(payload)]
        for rec in records:
            yield rec


def _replay(blob: bytes) -> Dict[str, bytes]:
    """Journal replay with the production longest-valid-prefix rule
    (iter_frames stops at the first torn/corrupt frame)."""
    state: Dict[str, bytes] = {}
    for op, key, value in _journal_records(blob):
        if op == OP_SET:
            state[key] = value
        elif op == OP_DELETE:
            state.pop(key, None)
    return state


class _Req:
    __slots__ = ("client", "ops", "tag", "token")

    def __init__(self, client: str, ops: tuple, tag: str, token: int):
        self.client = client
        self.ops = ops
        self.tag = tag
        self.token = token


class _Proc:
    __slots__ = ("gen", "status", "reply", "token")

    def __init__(self, gen, token: int = 0):
        self.gen = gen
        self.status = RUNNABLE
        self.reply = _PENDING
        self.token = token


# -- glue generators: production kernels wired to the model cluster ------

def _maybe_wrap(ex: "ProtoExecution", role: str, gen, ctx):
    mut = ex.mutation
    if mut is not None and mut.role == role:
        return mut.wrap(gen, ctx)
    return gen


def _drive_kernel(ex: "ProtoExecution", kernel, d: dict):
    """Sub-generator driving a driver kernel whose external steps are
    STEP_TXN (one wire round-trip — a real scheduling point), STEP_CLOCK
    and STEP_GRACE.  A store error is thrown in at the TXN yield as
    :class:`_StoreDown` and propagates to the caller."""
    resp = None
    while True:
        try:
            step = kernel.send(resp)
        except StopIteration as fin:
            return fin.value
        kind = step[0]
        if kind == STEP_TXN:
            resp = yield ("send", tuple(step[1]), step[2])
        elif kind == STEP_CLOCK:
            resp = ex.now
        elif kind == STEP_GRACE:
            d["grace"] = step[1]
            resp = None
        else:
            raise AssertionError(f"unexpected kernel step {step!r}")


def _drive_local(ex: "ProtoExecution", kernel, d: dict) -> None:
    """Drive a kernel with no wire steps (outage re-grace) to completion
    inside the current process step — clock read and grace arm are one
    atomic stamp, exactly as in the production ``_store_recovered``."""
    resp = None
    while True:
        try:
            step = kernel.send(resp)
        except StopIteration:
            return
        resp = None
        if step[0] == STEP_CLOCK:
            resp = ex.now
        elif step[0] == STEP_GRACE:
            d["grace"] = step[1]


def _driver_ticks(ex: "ProtoExecution", d: dict):
    """The driver's tick loop over the production kernels.  Mirrors
    ``ElasticDriver._tick``: fetch (one batched read), outage re-grace
    on the first fetch after a failure, then the judgment generator with
    every step executed against the model cluster."""
    scn = ex.scenario
    while d["tick"] < scn.ticks:
        d["tick"] += 1
        reads = _maybe_wrap(
            ex, "driver_reads",
            tick_read_steps(d["epoch"], None, sorted(ex.slots), (), ()), d)
        try:
            fetched = yield from _drive_kernel(ex, reads, d)
        except _StoreDown:
            d["outage"] = True
            continue
        if d["outage"]:
            d["outage"] = False
            ex.last_recovery_at = ex.now
            _drive_local(
                ex, _maybe_wrap(ex, "driver_recovery",
                                outage_recovery_steps(scn.lease_timeout),
                                d), d)
        # Commit-probe of a pending reshard (production kernel, same tick
        # position as ``_reshard_commit_probe``): reads the survivors'
        # epoch acks over the wire, writes the commit record only when
        # every one has adopted the epoch.
        if scn.reshard and d.get("reshard_pending") is not None:
            pend = d["reshard_pending"]
            probe = _maybe_wrap(ex, "driver_reshard",
                                reshard_commit_steps(pend["epoch"],
                                                     pend["survivors"]), d)
            try:
                res = yield from _drive_kernel(ex, probe, d)
            except _StoreDown:
                d["outage"] = True
                continue
            pend["missing"] = res["missing"]
            if res["committed"]:
                d["reshard_pending"] = None
        # Phase boundary: worker posts may land between the fetch and the
        # judgment of its snapshot — the tick-vs-posts race under test.
        yield ("pause", "judge")
        judgment = _maybe_wrap(
            ex, "driver_judgment",
            tick_judgment_steps(d["epoch"], fetched, ex.rank_to_host,
                                set(d["known"]), set(ex.slots),
                                d["lease_seen"], d["grace"],
                                scn.lease_timeout), d)
        j = ex._drive_judgment(judgment, d)
        if j is None:
            return  # violation recorded mid-judgment
        if j.get("advanced"):
            d["epoch"] += 1
            table = {}
            for ident in sorted(ex.slots):
                rank, host = ex.slots[ident]
                table[ident] = {"rank": rank, "epoch": d["epoch"],
                                "hostname": host}
            plan = None
            if scn.reshard:
                # The REAL plan kernel judges the publish about to go
                # out — marker stamped into the same atomic transaction,
                # fallback (no marker) while a previous reshard is still
                # uncommitted, exactly as ``_rendezvous_epoch`` does.
                plan = reshard_plan(
                    table, set(d["known"]), enabled=True,
                    pending=d.get("reshard_pending"),
                    recent_joiners=d.get("last_joiners") or ())
                if ex.mutation is not None \
                        and ex.mutation.role == "driver_plan":
                    plan = ex.mutation.wrap(plan, d)
                if plan["fallback"]:
                    d["reshard_pending"] = None
                if plan["eligible"]:
                    for slot in table.values():
                        slot["reshard"] = True
                        slot["sync_root"] = plan["sync_root"]
                        slot["joiners"] = plan["joiners"]
                        slot["survivors"] = plan["survivors"]
            ops: List[tuple] = [("set", DRIVER_SCOPE, "epoch",
                                 str(d["epoch"]).encode())]
            ops.extend(("set", RANK_AND_SIZE_SCOPE, ident,
                        json.dumps(table[ident]).encode())
                       for ident in sorted(table))
            if scn.reshard and plan["eligible"]:
                # Armed BEFORE the publish, exactly as production: a
                # store crash mid-service may land the marked table in
                # the journal while losing only the ack, and an armed
                # pending is safe either way — no marker on the wire
                # means no survivor ack, so the commit never fires and
                # the next advance falls back.
                d["reshard_pending"] = {
                    "epoch": d["epoch"],
                    "survivors": plan["survivors"],
                    "missing": list(plan["survivors"]),
                }
                d["last_joiners"] = set(plan["joiners"])
            elif scn.reshard:
                d["last_joiners"] = set()
            try:
                yield ("send", tuple(ops), "advance_publish")
            except _StoreDown:
                d["outage"] = True
            else:
                if scn.reshard:
                    # Mirror the spawn loop: every ranked identity has a
                    # live process after a successful publish.
                    d["known"] = set(ex.slots)


def _driver_proc(ex: "ProtoExecution"):
    yield from _driver_ticks(ex, ex.drv)


def _driver_recovery_proc(ex: "ProtoExecution"):
    """A restarted driver: the production ``recover_steps`` kernel
    against the journal-backed store, then the remaining ticks."""
    d = ex.drv
    d["outage"] = False
    while True:
        while True:
            try:
                rec = yield from _drive_kernel(
                    ex, _maybe_wrap(ex, "driver_recovery",
                                    recover_steps(ex.scenario.lease_timeout),
                                    d), d)
                break
            except _StoreDown:
                continue  # store died mid-recovery: retry, as production
        if rec is None:
            d["epoch"] = ex.scenario.epoch0
            d["known"] = set(ex.slots)
            d["lease_seen"] = {}
            recovered_epoch = None
        else:
            served = ex.recover_epoch_served
            truth = None if served is None else int(bytes(served).decode())
            if truth is None or rec["epoch"] != truth:
                ex._fail(V_RECOVER_MISMATCH,
                         f"restarted driver adopted epoch {rec['epoch']}, "
                         f"but the journal-backed store served {truth}")
                return
            d["epoch"] = rec["epoch"]
            d["known"] = set(rec["adopted"])
            d["lease_seen"] = {ident: (bytes(lease), ex.now)
                               for ident, (_slot, lease)
                               in sorted(rec["adopted"].items())}
            recovered_epoch = rec["epoch"]
        ex.last_recovery_at = ex.now
        if not ex.scenario.reshard:
            break
        # A reshard pending at crash time lived only in driver memory:
        # the restarted driver knows nothing of it, and its initial
        # republish (``start`` → ``_rendezvous_epoch(initial=True)``,
        # never marker-eligible) overwrites the marked table with an
        # unmarked one at the adopted epoch — driver crash mid-reshard
        # degrades to the legacy path by construction.  The republish is
        # CAS-fenced on the adopted epoch: the dead incarnation's
        # in-flight publish may land AFTER our recovery read, and an
        # unfenced republish would regress the durable epoch.  A lost
        # fence means re-adopt and retry — exactly ``start()``'s loop.
        d["reshard_pending"] = None
        d["last_joiners"] = set()
        expected = None if recovered_epoch is None \
            else str(recovered_epoch).encode()
        ops: List[tuple] = [
            ("check", DRIVER_SCOPE, "epoch", expected),
            ("set", DRIVER_SCOPE, "epoch", str(d["epoch"]).encode())]
        ops.extend(("set", RANK_AND_SIZE_SCOPE, ident,
                    json.dumps({"rank": ex.slots[ident][0],
                                "epoch": d["epoch"],
                                "hostname": ex.slots[ident][1]}).encode())
                   for ident in sorted(ex.slots))
        try:
            res = yield ("send", tuple(ops), "recover_publish")
        except _StoreDown:
            d["outage"] = True
            break
        if res and res[0] is False:
            continue  # fence lost: the epoch moved under us; re-adopt
        break
    yield from _driver_ticks(ex, d)


def _worker_proc(ex: "ProtoExecution", spec: dict):
    """One worker: lease renewals and reset requests, built by the SAME
    payload builders production posts through (rendezvous_client.py /
    core/state.py's pusher), sent best-effort like production."""
    renewals = 0
    for item in spec["script"]:
        if item[0] == "renew":
            renewals += 1
            ops = lease_renew_ops(spec["identity"], spec["rank"],
                                  spec["epoch"], renewals, b"{}")
            tag = "lease_renew"
        elif item[0] == "reset":
            ops = [("set", RESET_REQUEST_SCOPE, spec["identity"],
                    reset_request_payload(item[1], item[2]))]
            tag = "reset_request"
        elif item[0] == "ack":
            # Epoch-adoption ack, the exact write a survivor's
            # ``refresh_topology_from_rendezvous`` makes after ADOPTING
            # a published epoch — never before.  The one-shot poll
            # models the refresh's blocking read of the slot table: a
            # survivor only acks an epoch it has OBSERVED published.
            # Acking unconditionally would be a fidelity bug — it lets
            # the model commit a reshard whose marked publish never
            # landed, a schedule no real worker can produce.
            try:
                res = yield ("send",
                             (("get", RANK_AND_SIZE_SCOPE,
                               spec["identity"]),), "epoch_poll")
            except _StoreDown:
                continue
            raw = res[0] if res else None
            if raw is None:
                continue
            try:
                observed = json.loads(bytes(raw).decode()).get("epoch", -1)
            except (ValueError, TypeError):
                continue
            if observed < item[1]:
                continue  # publish not visible yet: no adoption, no ack
            ops = [("set", EPOCH_ACK_SCOPE, spec["identity"],
                    str(observed).encode())]
            tag = "epoch_ack"
        else:
            raise AssertionError(f"unknown worker script item {item!r}")
        try:
            yield ("send", tuple(ops), tag)
        except _StoreDown:
            continue  # best-effort, exactly like the production posters


def _coordinator_proc(ex: "ProtoExecution", spec: dict):
    """The coordinator's straggler plane: the REAL DemotionPolicy judges
    each scripted EWMA snapshot; a verdict posts through the production
    payload builder.  posted_unix is 0.0 — evidence only, and the model
    must stay wall-clock free."""
    policy = DemotionPolicy(spec["demote_secs"], spec["demote_cycles"])
    for obs in spec["observations"]:
        yield ("pause", "observe")
        victim = policy.observe(spec["epoch"], dict(obs),
                                set(spec["active"]))
        if victim is None:
            continue
        payload = demotion_report_payload(
            spec["epoch"], victim, ex.rank_to_host.get(victim),
            dict(obs).get(victim, 0.0), spec["demote_secs"],
            spec["demote_cycles"], 0.0)
        try:
            yield ("send", (("set", DEMOTION_REPORT_SCOPE,
                             spec["identity"], payload),),
                   "demotion_report")
        except _StoreDown:
            continue


# -- the execution ------------------------------------------------------

class ProtoExecution:
    """One schedulable run of the model cluster.  Duck-types the shm
    :class:`~horovod_tpu.tools.mck.model.Execution` interface the
    explorer drives (``enabled_actions`` / ``touches`` / ``step`` /
    ``final_check`` / ``violation`` / ``steps``)."""

    #: Fallback footprint (everything conflicts); real actions report
    #: per-location footprints from :meth:`touches`.
    _TOUCH: FrozenSet[tuple] = frozenset({("w", "cluster")})

    def __init__(self, scenario, mutation=None, max_steps: int = 600):
        self.scenario = scenario
        self.mutation = mutation
        self.max_steps = max_steps
        self.steps = 0
        self.now = 0.0
        self.trace: List[str] = []
        self.violation: Optional[Violation] = None

        # store state.  The inbox is keyed (client, per-client seq):
        # delivery order is the POP's choice, so the key space — not
        # arrival order — is the canonical state, and two enqueues by
        # different clients genuinely commute (the independence the
        # sleep sets rely on).
        self.data: Dict[str, bytes] = {}
        self.journal: bytes = pack_frame(JOURNAL_MAGIC)
        self.inbox: Dict[Tuple[str, int], _Req] = {}
        self._send_seq: Dict[str, int] = {}
        self.store_cur: Optional[dict] = None
        self.acked_sets: List[Tuple[str, bytes, str]] = []
        self._fold_keys: Set[frozenset] = {frozenset()}
        self.true_tick_reply: Optional[Tuple[tuple, tuple]] = None
        self.recover_epoch_served: Optional[bytes] = None
        # Store-side reshard ledger (ground truth for the reshard
        # invariants, rebuilt from replayed durable state on a store
        # crash): marked-published epochs awaiting their commit record,
        # with the survivor set each one published, and epochs whose
        # commit landed.
        self.reshard_pending_store: Dict[int, FrozenSet[str]] = {}
        self.reshard_committed: Set[int] = set()

        # topology ground truth
        self.slots: Dict[str, Tuple[int, str]] = dict(scenario.slots)
        self.rank_to_host: Dict[int, str] = {
            rank: host for rank, host in self.slots.values()}
        self.hosts: FrozenSet[str] = frozenset(
            host for _rank, host in self.slots.values())
        self.blacklisted: Set[str] = set()
        self.drv_last_poll: FrozenSet[str] = self.hosts
        self.tick_poll_served: FrozenSet[str] = frozenset()

        # crash / clock budgets
        self.clock_idx = 0
        self.store_crashes_used = 0
        self.driver_crashes_used = 0
        self.last_recovery_at: Optional[float] = None

        # Durable seed state, committed through the REAL batch kernel so
        # the journal, the data map and the fold set all agree.  The
        # driver's own epoch is always seeded — a restarted driver must
        # find what a prior incarnation persisted.
        self._seed([("set", DRIVER_SCOPE, "epoch",
                     str(scenario.epoch0).encode())])
        for ops in scenario.seeds:
            self._seed(list(ops))

        # driver state (carried across driver restarts)
        self.drv: dict = {
            "epoch": scenario.epoch0, "tick": 0, "outage": False,
            "grace": 0.0, "known": set(self.slots), "lease_seen": {},
            "reshard_pending": None, "last_joiners": set(),
        }

        self.procs: Dict[str, _Proc] = {"drv": _Proc(_driver_proc(self))}
        for spec in scenario.workers:
            self.procs[spec["name"]] = _Proc(_worker_proc(self, spec))
        if scenario.coordinator is not None:
            self.procs["coord"] = _Proc(
                _coordinator_proc(self, scenario.coordinator))
        assert "st" not in self.procs
        for name in list(self.procs):
            self._prime(name)

    # -- seeding -------------------------------------------------------

    def _seed(self, ops: List[tuple]) -> None:
        fold = _fold_ops(self.data, ops)
        self._fold_keys.add(frozenset(fold.items()))
        gen = batch_steps(list(ops))
        resp = None
        while True:
            try:
                step = gen.send(resp)
            except StopIteration:
                return
            resp = None
            kind = step[0]
            if kind == STEP_LOAD:
                resp = self.data.get(step[1])
            elif kind == STEP_KEYS:
                resp = sorted(k for k in self.data
                              if k.startswith(step[1]))
            elif kind == STEP_JOURNAL:
                if step[1]:
                    self.journal += pack_frame(encode_group(list(step[1])))
            elif kind == STEP_APPLY:
                if step[2] is None:
                    self.data.pop(step[1], None)
                else:
                    self.data[step[1]] = step[2]

    # -- scheduling interface (explorer-facing) ------------------------

    def enabled_actions(self) -> List[tuple]:
        if self.violation is not None or self.steps >= self.max_steps:
            return []
        if self.store_cur is not None:
            # Partial-order reduction: mid-transaction, the only action
            # that does not commute with the store's micro-steps is a
            # store crash (intra-transaction state is observable ONLY
            # through the reply, which the micro-steps themselves
            # deliver).  A process step, clock advance, or driver crash
            # scheduled mid-service reaches exactly the states it
            # reaches scheduled before the pop or after the reply, so
            # exploring it here would only duplicate schedules.
            acts = [("t",)]
            if self.store_crashes_used < self.scenario.store_crashes:
                acts.append(("c", "st"))
            return acts
        acts = []
        for name in sorted(self.procs):
            p = self.procs[name]
            if p.status == RUNNABLE or (p.status == WAITING
                                        and p.reply is not _PENDING):
                acts.append(("p", name))
        acts.extend(("s", key) for key in sorted(self.inbox))
        if self.clock_idx < len(self.scenario.clock_steps):
            acts.append(("k", self.clock_idx))
        if self.store_crashes_used < self.scenario.store_crashes:
            acts.append(("c", "st"))
        if self.driver_crashes_used < self.scenario.driver_crashes:
            acts.append(("c", "drv"))
        return acts

    def touches(self, action: tuple) -> FrozenSet[tuple]:
        """Per-action location footprint for sleep-set pruning.

        The locations are the model's real shared state, partitioned so
        that genuinely commuting pairs stay independent:

        - ``proc:<name>`` — a process's generator + reply slot.  Written
          by the process's own steps and by the store action that serves
          ITS request (reply delivery), so post-vs-consume races stay
          dependent while two different workers commute.
        - ``inbox:<name>`` — the client's key range of the keyed inbox.
          Written by the client's sends and by pops of its requests.  A
          store crash writes EVERY inbox range: crash-before-send and
          crash-after-send genuinely differ (the errored ack), even for
          a client with nothing queued yet.
        - ``store`` — data map, journal, acked ledger.  All pops,
          micro-steps and store crashes; never processes (a process sees
          store state only through a served reply, which the ``proc:``
          location already orders).
        - ``clock`` — written by clock advances, read only by driver
          steps (lease scan, expiry, re-grace stamps).  Workers and the
          coordinator never look at the clock, so they commute with it.

        Over-approximation stays sound; the risk is UNDER-approximation,
        which tests/test_mck_proto.py guards by diffing a sleep-set run
        against a ``--no-sleep-sets`` run on a full scenario.
        """
        kind = action[0]
        if kind == "p":
            name = action[1]
            touch = {("w", f"proc:{name}"), ("w", f"inbox:{name}")}
            if name == "drv":
                touch.add(("r", "clock"))
            return frozenset(touch)
        if kind == "s":
            req = self.inbox[action[1]]
            return frozenset({("w", "store"),
                              ("w", f"inbox:{req.client}"),
                              ("w", f"proc:{req.client}")})
        if kind == "t":
            client = self.store_cur["req"].client
            return frozenset({("w", "store"), ("w", f"proc:{client}")})
        if kind == "k":
            return frozenset({("w", "clock")})
        if kind == "c" and action[1] == "st":
            touch = {("w", "store")}
            for name in self.procs:
                touch.add(("w", f"inbox:{name}"))
            doomed = list(self.inbox.values())
            if self.store_cur is not None:
                doomed.append(self.store_cur["req"])
            for req in doomed:
                touch.add(("w", f"proc:{req.client}"))
            return frozenset(touch)
        if kind == "c" and action[1] == "drv":
            return frozenset({("w", "proc:drv"), ("w", "inbox:drv")})
        return self._TOUCH

    def step(self, action: tuple) -> None:
        self.steps += 1
        kind = action[0]
        if kind == "p":
            self.trace.append(f"p:{action[1]}")
            self._proc_step(action[1])
        elif kind == "s":
            key = action[1]
            self.trace.append(
                f"s:{key[0]}#{key[1]}[{self.inbox[key].tag}]")
            self._pop_request(key)
        elif kind == "t":
            self.trace.append("t:store")
            self._store_step()
        elif kind == "k":
            delta = self.scenario.clock_steps[action[1]]
            self.trace.append(f"k:+{delta:g}")
            self.clock_idx += 1
            self.now += delta
        elif kind == "c" and action[1] == "st":
            self.trace.append("c:store-crash")
            self._crash_store()
        elif kind == "c" and action[1] == "drv":
            self.trace.append("c:driver-crash")
            self._crash_driver()
        else:
            self._fail(V_MODEL_ERROR, f"unknown action {action!r}")

    def final_check(self) -> Optional[Violation]:
        if self.violation is not None:
            return self.violation
        v = self._torn_sweep() or self._acked_check()
        if v is not None:
            return v
        for name in sorted(self.procs):
            p = self.procs[name]
            if p.status != FINISHED:
                return Violation(
                    V_MODEL_ERROR,
                    f"process {name} never finished (status {p.status}; "
                    f"steps={self.steps}/{self.max_steps}) — either a "
                    "dropped reply or a too-small --max-steps budget",
                    list(self.trace))
        return None

    # -- processes -----------------------------------------------------

    def _prime(self, name: str) -> None:
        p = self.procs[name]
        try:
            item = next(p.gen)
        except StopIteration:
            p.status = FINISHED
            return
        self._dispatch_yield(name, p, item)

    def _proc_step(self, name: str) -> None:
        p = self.procs[name]
        try:
            if p.status == WAITING:
                reply = p.reply
                p.reply = _PENDING
                p.status = RUNNABLE
                if reply is _ERROR:
                    item = p.gen.throw(_StoreDown())
                else:
                    item = p.gen.send(reply)
            else:
                item = p.gen.send(None)
        except StopIteration:
            p.status = FINISHED
            return
        except _StoreDown:
            p.status = FINISHED
            self._fail(V_MODEL_ERROR,
                       f"process {name}: unhandled store outage")
            return
        self._dispatch_yield(name, p, item)

    def _dispatch_yield(self, name: str, p: _Proc, item: tuple) -> None:
        if item[0] == "send":
            seq = self._send_seq.get(name, 0)
            self._send_seq[name] = seq + 1
            self.inbox[(name, seq)] = _Req(name, tuple(item[1]), item[2],
                                           p.token)
            p.status = WAITING
            p.reply = _PENDING
        elif item[0] == "pause":
            pass  # a pure scheduling point
        else:
            self._fail(V_MODEL_ERROR,
                       f"process {name}: unknown yield {item!r}")

    # -- store ---------------------------------------------------------

    def _pop_request(self, key: Tuple[str, int]) -> None:
        req = self.inbox.pop(key)
        # The expected post-state of THIS transaction, from the ops
        # themselves: the torn sweep's ground truth.  At pop time the
        # store is idle, so self.data is exactly the journal state.
        fold = _fold_ops(self.data, req.ops)
        self._fold_keys.add(frozenset(fold.items()))
        gen = batch_steps(list(req.ops))
        if self.mutation is not None and self.mutation.role == "store":
            gen = self.mutation.wrap(gen, None)
        self.store_cur = {"req": req, "gen": gen, "resp": None}
        if self.store_crashes_used >= self.scenario.store_crashes:
            # No crash can land mid-service anymore, so the micro-step
            # boundaries are indistinguishable to every other unit:
            # serve the whole transaction atomically (same reduction as
            # enabled_actions' mid-transaction restriction).
            while self.store_cur is not None and self.violation is None:
                self._store_step()

    def _store_step(self) -> None:
        cur = self.store_cur
        try:
            step = cur["gen"].send(cur["resp"])
        except StopIteration:
            self.store_cur = None
            return
        cur["resp"] = None
        kind = step[0]
        if kind == STEP_LOAD:
            cur["resp"] = self.data.get(step[1])
        elif kind == STEP_KEYS:
            cur["resp"] = sorted(k for k in self.data
                                 if k.startswith(step[1]))
        elif kind == STEP_JOURNAL:
            if step[1]:
                self.journal += pack_frame(encode_group(list(step[1])))
        elif kind == STEP_APPLY:
            self._store_apply(step[1], step[2], cur["req"])
        elif kind == STEP_NOTIFY:
            pass
        elif kind == STEP_REPLY:
            self._serve_reply(cur["req"], step[1])
        else:
            self._fail(V_MODEL_ERROR, f"unknown store step {step!r}")

    def _store_apply(self, flat: str, value: Optional[bytes],
                     req: _Req) -> None:
        if value is None:
            self.data.pop(flat, None)
            return
        if flat == _EPOCH_KEY and _EPOCH_KEY in self.data:
            old = int(bytes(self.data[_EPOCH_KEY]).decode())
            new = int(bytes(value).decode())
            if new < old:
                self._fail(V_EPOCH_REGRESSION,
                           f"driver epoch regressed {old} -> {new} "
                           f"(txn {req.tag!r} from {req.client})")
        if flat.startswith(f"{DEMOTION_REPORT_SCOPE}/") \
                and self.scenario.active_np <= 2:
            self._fail(V_SMALL_WORLD_DEMOTION,
                       f"demotion report landed at np="
                       f"{self.scenario.active_np} (<= 2): the whole-"
                       "world-slow guard should make this structurally "
                       "impossible")
        if flat.startswith(f"{RANK_AND_SIZE_SCOPE}/"):
            self._apply_slot_doc(flat, value, req)
        if flat == _RESHARD_COMMIT_KEY:
            self._apply_reshard_commit(value, req)
        self.data[flat] = value

    def _apply_slot_doc(self, flat: str, value: bytes, req: _Req) -> None:
        """Reshard ledger + fallback invariant on every published slot
        entry.  A MARKED entry landing at epoch E while an older marked
        epoch never committed is the load-bearing fallback deleted: the
        workers of the failed reshard (some possibly holding blank,
        never-synced state) would be strung along as survivors instead
        of degraded to the legacy full-sync path.  An UNMARKED entry at
        epoch >= E *is* that fallback and retires E."""
        try:
            doc = json.loads(bytes(value).decode())
        except (ValueError, TypeError):
            return
        if not isinstance(doc, dict) or not isinstance(doc.get("epoch"),
                                                       int):
            return
        ep = doc["epoch"]
        if doc.get("reshard"):
            stale = sorted(e for e in self.reshard_pending_store if e < ep)
            if stale:
                self._fail(
                    V_RESHARD_FALLBACK_MISSED,
                    f"reshard-marked slot table published at epoch {ep} "
                    f"(txn {req.tag!r}) while the epoch-{stale[0]} "
                    "reshard never committed: the fallback to the "
                    "legacy full-teardown path was skipped")
            self.reshard_pending_store[ep] = frozenset(
                doc.get("survivors") or ())
        else:
            for e in [e for e in self.reshard_pending_store if e <= ep]:
                del self.reshard_pending_store[e]

    def _apply_reshard_commit(self, value: bytes, req: _Req) -> None:
        """Early-commit invariant, judged on the STORE's own data: when
        the commit record for epoch E lands, every survivor the marked
        publish listed must already have an epoch ack >= E on record —
        the driver-side guard a mutant deletes cannot bend this."""
        try:
            ep = int(bytes(value).decode())
        except ValueError:
            self._fail(V_MODEL_ERROR,
                       f"unparseable reshard commit record {value!r}")
            return
        survivors = self.reshard_pending_store.get(ep)
        if survivors is None:
            if ep not in self.reshard_committed:
                self._fail(
                    V_RESHARD_EARLY_COMMIT,
                    f"reshard commit record for epoch {ep} (txn "
                    f"{req.tag!r}) with no marked publish pending at "
                    "that epoch")
            return
        unacked = []
        for ident in sorted(survivors):
            raw = self.data.get(f"{EPOCH_ACK_SCOPE}/{ident}")
            try:
                acked = int(bytes(raw).decode()) if raw is not None else -1
            except ValueError:
                acked = -1
            if acked < ep:
                unacked.append(ident)
        if unacked:
            self._fail(
                V_RESHARD_EARLY_COMMIT,
                f"reshard commit for epoch {ep} landed with survivor(s) "
                f"{unacked} never having acked it: an in-place "
                "re-rendezvous was declared done over workers that may "
                "still be running the old topology")
            return
        del self.reshard_pending_store[ep]
        self.reshard_committed.add(ep)

    def _serve_reply(self, req: _Req, results: tuple) -> None:
        # A batch aborted by a failed CAS ``check`` journals and applies
        # NOTHING — its sets were never promised, so recording them as
        # acked would manufacture a false V_ACKED_LOST.
        aborted = any(op[0] == "check" and idx < len(results)
                      and results[idx] is False
                      for idx, op in enumerate(req.ops))
        if not aborted:
            for op in req.ops:
                if op[0] == "set":
                    self.acked_sets.append(
                        (f"{op[1]}/{op[2]}", op[3], req.tag))
        p = self.procs.get(req.client)
        current = p is not None and p.token == req.token
        if current and req.client == "drv":
            # The store's ground truth of what the driver was told —
            # captured on the SERVER side, out of reach of driver-side
            # mutants that rewrite what the kernel returns.
            if req.tag == "tick_reads":
                self.true_tick_reply = (tuple(req.ops), tuple(results))
            elif req.tag == "recover_epoch":
                self.recover_epoch_served = results[0]
        if current and p.status == WAITING:
            p.reply = list(results)

    # -- crashes and recovery ------------------------------------------

    def _crash_store(self) -> None:
        self.store_crashes_used += 1
        v = self._torn_sweep() or self._acked_check()
        if v is not None and self.violation is None:
            self.violation = v
        doomed = list(self.inbox.values())
        self.inbox = {}
        if self.store_cur is not None:
            doomed.append(self.store_cur["req"])
            self.store_cur = None
        for req in doomed:
            p = self.procs.get(req.client)
            if p is not None and p.token == req.token \
                    and p.status == WAITING:
                p.reply = _ERROR
        # Restart: state is whatever the journal's valid prefix replays.
        self.data = _replay(self.journal)
        self._rebuild_reshard_ledger()

    def _rebuild_reshard_ledger(self) -> None:
        """Re-derive the reshard ledger from replayed durable state: a
        marked epoch is pending iff its marked entries are still the
        latest for some identity (an unmarked/later publish overwrote
        them — the retirement the incremental path applies) and its
        commit record is absent."""
        pending: Dict[int, FrozenSet[str]] = {}
        for flat, value in self.data.items():
            if not flat.startswith(f"{RANK_AND_SIZE_SCOPE}/"):
                continue
            try:
                doc = json.loads(bytes(value).decode())
            except (ValueError, TypeError):
                continue
            if isinstance(doc, dict) and doc.get("reshard") \
                    and isinstance(doc.get("epoch"), int):
                pending[doc["epoch"]] = frozenset(
                    doc.get("survivors") or ())
        committed = set(self.reshard_committed)
        raw = self.data.get(_RESHARD_COMMIT_KEY)
        if raw is not None:
            try:
                committed.add(int(bytes(raw).decode()))
            except ValueError:
                pass
        for ep in committed:
            pending.pop(ep, None)
        self.reshard_pending_store = pending
        self.reshard_committed = committed

    def _crash_driver(self) -> None:
        self.driver_crashes_used += 1
        old = self.procs["drv"]
        self.procs["drv"] = _Proc(_driver_recovery_proc(self),
                                  token=old.token + 1)
        self._prime("drv")

    # -- judgment side effects (the driver's world) --------------------

    def _drive_judgment(self, kernel, d: dict) -> Optional[dict]:
        """Execute one judgment generator to completion.  Runs inside a
        single process step: the judgment is driver-local compute — its
        store reads already happened in the fetch — so there is no wire
        yield to interleave at (crashing the driver mid-judgment is
        indistinguishable from crashing before it)."""
        advances = 0
        resp = None
        while True:
            try:
                step = kernel.send(resp)
            except StopIteration as fin:
                return fin.value
            resp = None
            kind = step[0]
            if kind == STEP_CLOCK:
                resp = self.now
            elif kind == STEP_BLACKLIST:
                self.blacklisted.add(step[1])
            elif kind == STEP_POLL_HOSTS:
                resp = self._poll_hosts()
            elif kind == STEP_GATE:
                resp = False
            elif kind == STEP_EXPIRE:
                self._apply_expire(step[1], d)
            elif kind == STEP_ADVANCE:
                advances += 1
                if advances > 1:
                    self._fail(V_MULTI_ADVANCE,
                               "two STEP_ADVANCE in one judged tick")
                    return None
                self._check_advance(step[1], d)
            else:
                self._fail(V_MODEL_ERROR,
                           f"unknown judgment step {step!r}")
                return None

    def _poll_hosts(self) -> Tuple[bool, bool]:
        available = self.hosts - frozenset(self.blacklisted)
        changed = available != self.drv_last_poll
        removal = bool(self.drv_last_poll - available)
        self.tick_poll_served = available
        self.drv_last_poll = available
        return changed, removal

    def _apply_expire(self, identity: str, d: dict) -> None:
        d["known"].discard(identity)
        d["lease_seen"].pop(identity, None)
        if self.last_recovery_at is not None and \
                self.now < self.last_recovery_at + \
                self.scenario.lease_timeout:
            self._fail(
                V_LIVE_DROPPED,
                f"identity {identity} expired at t={self.now:g}, inside "
                f"the post-outage re-grace window (recovered at "
                f"t={self.last_recovery_at:g}, timeout "
                f"{self.scenario.lease_timeout:g}): a worker that could "
                "not renew through the outage was shed as dead")

    def _check_advance(self, cause: str, d: dict) -> None:
        """Advance legitimacy against the STORE's ground truth: the ops
        and results it actually served the driver's current-incarnation
        tick fetch.  A driver-side mutant can rewrite what the kernel
        returns, never what the server served."""
        ops, results = self.true_tick_reply or ((), ())

        def current_reports(scope: str) -> List[dict]:
            # d["epoch"] is still the JUDGED epoch here: the driver
            # increments only after the judgment generator returns.
            docs = []
            for op, raw in zip(ops, results):
                if op[0] != "get" or op[1] != scope or raw is None:
                    continue
                try:
                    doc = json.loads(bytes(raw).decode())
                except (ValueError, TypeError):
                    continue
                if isinstance(doc, dict) and doc.get("epoch", -1) \
                        == d["epoch"]:
                    docs.append(doc)
            return docs

        if cause == "reset_request":
            if not current_reports(RESET_REQUEST_SCOPE):
                self._fail(
                    V_STALE_ACTED,
                    "epoch advanced for a reset request, but the store "
                    f"served no epoch-{d['epoch']} reset in this tick's "
                    "fetch — a stale request was acted on")
        elif cause == "demotion":
            reps = current_reports(DEMOTION_REPORT_SCOPE)
            if not reps:
                self._fail(
                    V_STALE_ACTED,
                    "epoch advanced for a demotion, but the store "
                    f"served no epoch-{d['epoch']} report in this "
                    "tick's fetch — a stale report was acted on")
                return
            shed = set()
            for rep in reps:
                host = self.rank_to_host.get(rep.get("rank")) \
                    or rep.get("hostname")
                if isinstance(host, str) and host:
                    shed.add(host)
            kept = shed & self.tick_poll_served
            if kept:
                self._fail(
                    V_DEMOTED_HOST_KEPT,
                    f"demotion advance with host(s) {sorted(kept)} still "
                    "in the discovery poll this tick served — the "
                    "blacklist must land strictly before the poll")

    # -- durability invariants -----------------------------------------

    def _torn_sweep(self) -> Optional[Violation]:
        """Every frame-boundary prefix of the journal must replay to a
        transaction-boundary state.  Byte-level crash points collapse to
        frame boundaries under the longest-valid-prefix rule, so this
        sweep covers a crash at EVERY journal byte."""
        state: Dict[str, bytes] = {}
        first = True
        frame_no = 0
        for _end, payload in iter_frames(self.journal):
            if first:
                first = False
                continue  # the magic frame
            frame_no += 1
            if payload and payload[0] == OP_GROUP:
                records = decode_group(payload)
            else:
                records = [decode_op(payload)]
            for op, key, value in records:
                if op == OP_SET:
                    state[key] = value
                elif op == OP_DELETE:
                    state.pop(key, None)
            if frozenset(state.items()) not in self._fold_keys:
                return Violation(
                    V_TORN_GROUP,
                    f"journal prefix ending at frame {frame_no} replays "
                    "to a state that is no transaction boundary: a crash "
                    "there recovers half a batched transaction",
                    list(self.trace))
        return None

    def _acked_check(self) -> Optional[Violation]:
        """Every SET the store ACKED must be in the journal: the reply
        is the durability promise (WAL ordering — group record strictly
        before the first apply, reply strictly after)."""
        present = {(key, bytes(value))
                   for op, key, value in _journal_records(self.journal)
                   if op == OP_SET}
        for flat, value, tag in self.acked_sets:
            if (flat, bytes(value)) not in present:
                return Violation(
                    V_ACKED_LOST,
                    f"acked set of {flat!r} (txn {tag!r}) is not in the "
                    "journal: a crash after the ack loses an "
                    "acknowledged write",
                    list(self.trace))
        return None

    # -- plumbing ------------------------------------------------------

    def _fail(self, name: str, detail: str) -> None:
        if self.violation is None:
            self.violation = Violation(name, detail, list(self.trace))


def proto_execution_factory(scenario, model, mutation=None,
                            max_steps: int = 600):
    """``execution_factory`` for :func:`explore.check`; ``model`` is the
    mode label ("proto") and carries no semantics here.  Scenarios with
    ``kind == "fanin"`` route to the negotiation fan-in degrade model
    (fanin_model.py), which shares this mode's action vocabulary and
    therefore its ``proto_unit`` pricing."""
    if getattr(scenario, "kind", "proto") == "fanin":
        from .fanin_model import FaninExecution

        return FaninExecution(scenario, mutation=mutation,
                              max_steps=max_steps)
    return ProtoExecution(scenario, mutation=mutation, max_steps=max_steps)
