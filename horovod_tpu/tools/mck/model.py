"""Deterministic execution model for the shm ring + futex-doorbell protocol.

One :class:`Execution` is one run of the two protocol threads (sender
"S", receiver "R") under an explicit schedule.  The threads are the REAL
:func:`~horovod_tpu.transport.shm.sender_steps` /
:func:`~horovod_tpu.transport.shm.receiver_steps` generators — the same
objects the production drivers execute against live segments — driven
here against a model memory with an explicit store-buffer semantics:

- Every store a thread issues lands in its private per-thread store
  buffer first and becomes globally visible only when a FLUSH action is
  scheduled.  The ``tso`` memory model flushes strictly in FIFO order
  (the x86-64 guarantee the production comment relies on); the ``weak``
  model may flush ANY buffered entry next, i.e. it permits store-store
  reordering.
- A thread's own loads read through its buffer (newest matching entry
  wins) — a core always sees its own stores.
- The futex syscalls (OP_WAIT / OP_WAKE) first drain the CALLING
  thread's buffer to global memory, modeling the locked kernel
  operations inside the syscall that act as a full barrier on the
  caller's core, then operate on global state: WAIT re-reads the bell
  and sleeps only if it still equals the expected value; WAKE wakes
  every current sleeper (FUTEX_WAKE with INT_MAX, as production does).
  This is deliberately the REALISTIC syscall semantics: under ``weak``
  the protocol must break via flush-agent reordering alone, which is
  exactly the store-store fence the production protocol leans on.
- Timeouts exist only as the abort-propagation path: once the scenario's
  abort has fired, a sleeping thread may be woken by a TIMEOUT action
  (the bounded ``_BELL_WAIT_SECS`` wait expiring).  Spurious timeouts
  are not modeled — a "missed wakeup" here means the production thread
  would burn a full bounded wait with progress already published, the
  exact latency bug the doorbell exists to prevent.

Scheduling granularity: one action executes one VISIBLE op — a load, a
receiver ring copy, a wait, a wake, or (in abort scenarios) a poll.
Thread-local ops (stores and sender copies, which only enter the private
buffer, plus polls when no abort can ever fire) auto-execute attached to
the preceding visible op; they commute with every other agent's actions,
so no interleaving is lost.  Payload bytes are modeled as their global
sequence number, so FIFO/lost-byte/overwrite violations are detected the
moment the receiver lands a wrong byte.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ...transport.shm import (
    ABORTED,
    DONE,
    LOC_BELL_OWN,
    LOC_BELL_PEER,
    LOC_HEAD,
    LOC_TAIL,
    OP_COPY,
    OP_LOAD,
    OP_POLL,
    OP_STORE,
    OP_WAIT,
    OP_WAKE,
    SIG_ABORT,
    SIG_OK,
    receiver_steps,
    sender_steps,
)

SENDER = "S"
RECEIVER = "R"

#: Concrete (direction-level) names for the two single-writer doorbells
#: the role-relative LOC_BELL_OWN / LOC_BELL_PEER resolve to: the sender
#: writes DATA_BELL and waits on SPACE_BELL; the receiver mirrors.
DATA_BELL = "data_bell"
SPACE_BELL = "space_bell"

RUNNABLE = "runnable"
SLEEPING = "sleeping"
FINISHED = "finished"

#: Violation names — the checker's vocabulary, referenced by tests, the
#: mutation kill suite, and docs/static_analysis.md.
V_MISSED_WAKEUP = "missed-wakeup"
V_DEADLOCK = "deadlock"
V_STARVATION = "starvation"
V_LOST_BYTES = "lost-bytes"
V_UNPUBLISHED_READ = "unpublished-read"
V_LIVELOCK = "livelock"
V_FUTEX_PAIRING = "futex-pairing"
V_STALE_BELL = "stale-bell"
V_MODEL_ERROR = "model-error"


class Violation:
    """One invariant breach plus the schedule that reproduces it."""

    __slots__ = ("name", "detail", "schedule")

    def __init__(self, name: str, detail: str, schedule: List[str]):
        self.name = name
        self.detail = detail
        self.schedule = schedule

    def to_dict(self) -> dict:
        return {"name": self.name, "detail": self.detail,
                "schedule": list(self.schedule)}


class Scenario:
    """One bounded workload: per-call segment lengths for each side (one
    generator instance per call — the per-CALL bell discipline is part of
    the protocol under test) and whether a mesh abort may fire."""

    __slots__ = ("name", "cap", "send_calls", "recv_calls", "abort",
                 "description", "preemptions")

    def __init__(self, name: str, cap: int, send_calls: List[List[int]],
                 recv_calls: List[List[int]], abort: bool,
                 description: str, preemptions: int):
        if sum(map(sum, send_calls)) != sum(map(sum, recv_calls)):
            raise ValueError(f"scenario {name}: send/recv byte mismatch")
        self.name = name
        self.cap = cap
        self.send_calls = send_calls
        self.recv_calls = recv_calls
        self.abort = abort
        self.description = description
        self.preemptions = preemptions

    @property
    def total_bytes(self) -> int:
        return sum(map(sum, self.send_calls))


class _Thread:
    __slots__ = ("tid", "factories", "call", "gen", "pending", "status",
                 "result", "fresh_bell", "last_bell", "bell_store_pending")

    def __init__(self, tid: str, factories: List[Callable]):
        self.tid = tid
        self.factories = factories
        self.call = 0
        self.gen = None
        self.pending: Optional[tuple] = None
        self.status = RUNNABLE
        self.result: Optional[str] = None
        # Structural-invariant state: the bell value of the freshest
        # precheck load (and whether one happened since the last wait),
        # and whether a bell store still awaits its FUTEX_WAKE.
        self.fresh_bell = False
        self.last_bell: Optional[int] = None
        self.bell_store_pending = False


def unit(action: tuple) -> str:
    """Scheduling unit for preemption accounting: a thread and its flush
    agent are one unit (a store buffer drains on the thread's own core —
    its progress is not a scheduler preemption); the abort injector is
    the environment."""
    return "env" if action[0] == "a" else action[1]


class Execution:
    """One schedulable run.  ``step(action)`` executes one action; the
    caller replays prefixes to explore (generators cannot be forked)."""

    def __init__(self, scenario: Scenario, memory_model: str,
                 mutation=None, max_steps: int = 600,
                 structural: bool = True):
        if memory_model not in ("tso", "weak"):
            raise ValueError(f"unknown memory model {memory_model!r}")
        self.scenario = scenario
        self.model = memory_model
        self.max_steps = max_steps
        self.structural = structural
        self.words: Dict[str, int] = {LOC_HEAD: 0, LOC_TAIL: 0,
                                      DATA_BELL: 0, SPACE_BELL: 0}
        self.ring: List[Optional[int]] = [None] * scenario.cap
        self.buffers: Dict[str, List[tuple]] = {SENDER: [], RECEIVER: []}
        # tid -> concrete bell word the sleeper is parked on (a futex
        # wake on one word never disturbs waiters on the other).
        self.sleepers: Dict[str, str] = {}
        self.abort = False
        self.abort_armed = scenario.abort
        self.received: List[int] = []
        self.trace: List[str] = []
        self.steps = 0
        self.violation: Optional[Violation] = None
        # Sender bytes are their global sequence number: segment idx/off
        # within a call maps through these per-call prefixes.
        self._send_base: List[List[int]] = []
        base = 0
        for lens in scenario.send_calls:
            prefixes = []
            for n in lens:
                prefixes.append(base)
                base += n
            self._send_base.append(prefixes)

        def factories(role: str, calls: List[List[int]]) -> List[Callable]:
            step_fn = sender_steps if role == SENDER else receiver_steps
            out = []
            for lens in calls:
                def make(lens=lens, step_fn=step_fn):
                    gen = step_fn(scenario.cap, list(lens))
                    if mutation is not None and mutation.role == role:
                        gen = mutation.wrap(gen)
                    return gen
                out.append(make)
            return out

        self.threads: Dict[str, _Thread] = {
            SENDER: _Thread(SENDER, factories(SENDER, scenario.send_calls)),
            RECEIVER: _Thread(RECEIVER,
                              factories(RECEIVER, scenario.recv_calls)),
        }
        for t in self.threads.values():
            self._fetch(t, None, first=True)

    # -- memory ------------------------------------------------------------

    @staticmethod
    def _word(tid: str, loc: str) -> str:
        """Resolve a role-relative generator loc to a concrete shared
        word: the sender's own bell is the data bell, the receiver's the
        space bell, and each waits on the other's."""
        if loc == LOC_BELL_OWN:
            return DATA_BELL if tid == SENDER else SPACE_BELL
        if loc == LOC_BELL_PEER:
            return SPACE_BELL if tid == SENDER else DATA_BELL
        return loc

    def _visible(self, tid: str, loc: str) -> int:
        for entry in reversed(self.buffers[tid]):
            if entry[0] == "word" and entry[1] == loc:
                return entry[2]
        return self.words[loc]

    def _apply(self, entry: tuple) -> str:
        if entry[0] == "word":
            self.words[entry[1]] = entry[2]
            return f"{entry[1]}={entry[2]}"
        for pos, seq in entry[1]:
            self.ring[pos] = seq
        span = entry[1]
        return f"ring[{span[0][0]}..{span[-1][0]}]"

    def _drain(self, tid: str) -> None:
        """Syscall barrier: publish the caller's buffered stores, in
        buffer order, before the kernel reads global state."""
        buf = self.buffers[tid]
        while buf:
            self._apply(buf.pop(0))

    # -- violations --------------------------------------------------------

    def _violate(self, name: str, detail: str) -> None:
        if self.violation is None:
            self.violation = Violation(name, detail, list(self.trace))

    # -- generator advancement --------------------------------------------

    def _fetch(self, t: _Thread, resp, first: bool = False) -> None:
        """Advance ``t`` past its just-completed op (answering it with
        ``resp``) to its next VISIBLE op, auto-executing thread-local
        ones on the way."""
        if t.gen is None:
            if first:
                t.gen = t.factories[t.call]()
                resp = None
            else:  # pragma: no cover - defensive
                raise RuntimeError("fetch on a finished thread")
        while True:
            try:
                op = t.gen.send(resp)
            except StopIteration as fin:
                if t.bell_store_pending and self.structural:
                    self._violate(
                        V_FUTEX_PAIRING,
                        f"{t.tid} finished a call with a bell store never "
                        "followed by FUTEX_WAKE: any peer that went to "
                        "sleep before the store burns a full bounded wait")
                result = fin.value if fin.value is not None else DONE
                t.call += 1
                if result == ABORTED or t.call >= len(t.factories):
                    t.gen = None
                    t.pending = None
                    t.status = FINISHED
                    t.result = result
                    return
                t.gen = t.factories[t.call]()
                resp = None
                continue
            kind = op[0]
            if kind == OP_STORE:
                self._exec_store(t, op)
                resp = None
                continue
            if kind == OP_COPY and t.tid == SENDER:
                base = self._send_base[t.call][op[1]]
                _, _idx, off, pos, run = op
                self.buffers[t.tid].append(
                    ("ring", [(pos + k, base + off + k)
                              for k in range(run)]))
                resp = None
                continue
            if kind == OP_POLL and not self.abort_armed:
                resp = SIG_OK
                continue
            t.pending = op
            return

    def _exec_store(self, t: _Thread, op: tuple) -> None:
        loc, value = op[1], op[2]
        if loc == LOC_BELL_OWN:
            t.bell_store_pending = True
        self.buffers[t.tid].append(("word", self._word(t.tid, loc), value))

    # -- scheduling --------------------------------------------------------

    def enabled_actions(self) -> List[tuple]:
        if self.violation is not None:
            return []
        acts: List[tuple] = []
        for t in self.threads.values():
            if t.status == RUNNABLE and t.pending is not None:
                acts.append(("t", t.tid))
            elif t.status == SLEEPING and self.abort:
                acts.append(("w", t.tid))
        for tid, buf in self.buffers.items():
            if buf:
                if self.model == "tso":
                    acts.append(("f", tid, 0))
                else:
                    # Store-store reordering across ADDRESSES only:
                    # same-location stores stay in program order (cache
                    # coherence holds even on weak machines), so a flush
                    # may pick any entry that is the oldest for its
                    # location.  This also keeps the thread's own
                    # forwarded view consistent: the newest buffered
                    # entry per location is always still buffered.
                    seen: Set[object] = set()
                    for i, entry in enumerate(buf):
                        key = entry[1] if entry[0] == "word" else "ring"
                        if key not in seen:
                            acts.append(("f", tid, i))
                            seen.add(key)
        if self.abort_armed and not self.abort \
                and any(t.status != FINISHED for t in self.threads.values()):
            acts.append(("a",))
        return acts

    def touches(self, action: tuple) -> frozenset:
        """Read/write footprint of an enabled action, for the explorer's
        independence relation."""
        kind = action[0]
        if kind == "t":
            tid = action[1]
            op = self.threads[tid].pending
            if op[0] == OP_LOAD:
                return frozenset({("r", self._word(tid, op[1]))})
            if op[0] == OP_COPY:
                return frozenset({("r", "ring")})
            if op[0] == OP_POLL:
                return frozenset({("r", "abort")})
            # OP_WAIT / OP_WAKE: the touched futex word plus the
            # syscall's buffer drain.
            if op[0] == OP_WAIT:
                word = self._word(tid, LOC_BELL_PEER)
                s = {("w", ("futex", word)), ("r", word)}
            else:
                s = {("w", ("futex", self._word(tid, LOC_BELL_OWN)))}
            for entry in self.buffers[tid]:
                s.add(("w", entry[1] if entry[0] == "word" else "ring"))
            return frozenset(s)
        if kind == "f":
            entry = self.buffers[action[1]][action[2]]
            return frozenset(
                {("w", entry[1] if entry[0] == "word" else "ring")})
        if kind == "w":
            word = self._word(action[1], LOC_BELL_PEER)
            return frozenset({("w", ("futex", word))})
        return frozenset({("w", "abort")})

    def step(self, action: tuple) -> None:
        self.steps += 1
        if self.steps > self.max_steps:
            self._violate(
                V_LIVELOCK,
                f"no quiescence within {self.max_steps} scheduled actions: "
                "the protocol is spinning without making progress")
            return
        kind = action[0]
        if kind == "a":
            self.abort = True
            self.trace.append("env: mesh abort flag set")
            return
        if kind == "f":
            tid = action[1]
            entry = self.buffers[tid].pop(action[2])
            desc = self._apply(entry)
            self.trace.append(f"flush({tid}): {desc} -> shared")
            return
        if kind == "w":
            tid = action[1]
            t = self.threads[tid]
            self.sleepers.pop(tid, None)
            t.status = RUNNABLE
            self.trace.append(f"{tid}: bounded wait timed out (abort set)")
            self._fetch(t, None)
            return
        t = self.threads[action[1]]
        op = t.pending
        if self.structural and t.bell_store_pending and op[0] != OP_WAKE:
            self._violate(
                V_FUTEX_PAIRING,
                f"{t.tid} moved the bell but ran {op[0]} before the "
                "FUTEX_WAKE that publishes it to sleepers")
            return
        if op[0] == OP_LOAD:
            word = self._word(t.tid, op[1])
            value = self._visible(t.tid, word)
            tag = op[2] if len(op) > 2 else None
            if op[1] == LOC_BELL_PEER and tag == "precheck":
                t.fresh_bell = True
                t.last_bell = value
            self.trace.append(f"{t.tid}: load {word} -> {value}")
            self._fetch(t, value)
        elif op[0] == OP_POLL:
            resp = SIG_ABORT if self.abort else SIG_OK
            self.trace.append(f"{t.tid}: poll abort -> {resp}")
            self._fetch(t, resp)
        elif op[0] == OP_COPY:
            # Receiver-side ring read (the sender's copies are buffered
            # stores, auto-executed in _fetch).
            _, _idx, _got, pos, run = op
            for k in range(run):
                value = self.ring[pos + k]
                want = len(self.received)
                if value is None:
                    self._violate(
                        V_UNPUBLISHED_READ,
                        f"receiver read ring[{pos + k}] before the "
                        "sender's data bytes became visible: head was "
                        "published ahead of the bytes it covers")
                    return
                if value != want:
                    self._violate(
                        V_LOST_BYTES,
                        f"receiver landed byte seq {value} where seq "
                        f"{want} was due (ring[{pos + k}]): bytes were "
                        "overwritten or delivered out of order")
                    return
                self.received.append(value)
            self.trace.append(
                f"{t.tid}: copy ring[{pos}..{pos + run - 1}] out")
            t.fresh_bell = False
            self._fetch(t, None)
        elif op[0] == OP_WAIT:
            expected = op[1]
            if self.structural and not t.fresh_bell:
                self._violate(
                    V_STALE_BELL,
                    f"{t.tid} armed FUTEX_WAIT with a bell value not "
                    "re-read since its last wait/copy: a bump between "
                    "the stale read and this wait is invisible and the "
                    "wait can no longer be cut short")
                return
            if self.structural and expected != t.last_bell:
                self._violate(
                    V_STALE_BELL,
                    f"{t.tid} waits on bell=={expected} but last loaded "
                    f"{t.last_bell}")
                return
            t.fresh_bell = False
            self._drain(t.tid)
            word = self._word(t.tid, LOC_BELL_PEER)
            current = self.words[word]
            if current != expected:
                self.trace.append(
                    f"{t.tid}: FUTEX_WAIT({word}=={expected}) -> EAGAIN "
                    f"({word}={current})")
                self._fetch(t, None)
            else:
                t.status = SLEEPING
                self.sleepers[t.tid] = word
                self.trace.append(
                    f"{t.tid}: FUTEX_WAIT({word}=={expected}) -> sleep")
        else:  # OP_WAKE
            t.bell_store_pending = False
            self._drain(t.tid)
            word = self._word(t.tid, LOC_BELL_OWN)
            woken = sorted(tid for tid, on in self.sleepers.items()
                           if on == word)
            for tid in woken:
                other = self.threads[tid]
                other.status = RUNNABLE
                del self.sleepers[tid]
                self._fetch(other, None)
            self.trace.append(
                f"{t.tid}: FUTEX_WAKE({word}) -> woke {woken}")
            self._fetch(t, None)

    # -- terminal checks ---------------------------------------------------

    def final_check(self) -> Optional[Violation]:
        """Invariants judged at quiescence (no enabled actions): every
        buffered store has flushed, so global memory is the final state."""
        if self.violation is not None:
            return self.violation
        sleeping = [t for t in self.threads.values()
                    if t.status == SLEEPING]
        if sleeping:
            head, tail = self.words[LOC_HEAD], self.words[LOC_TAIL]
            for t in sleeping:
                waits_for = (self.scenario.cap - (head - tail)) \
                    if t.tid == SENDER else (head - tail)
                if waits_for > 0:
                    self._violate(
                        V_MISSED_WAKEUP,
                        f"{t.tid} is asleep on the bell with "
                        f"{waits_for} byte(s) of "
                        f"{'space' if t.tid == SENDER else 'data'} "
                        "already published and no wake left in flight: "
                        "production burns a full bounded wait "
                        "(_BELL_WAIT_SECS) per occurrence")
                    return self.violation
            if len(sleeping) == 2:
                self._violate(V_DEADLOCK,
                              "both sides asleep on the bell with "
                              "nothing published either way")
            else:
                self._violate(
                    V_STARVATION,
                    f"{sleeping[0].tid} asleep with its condition "
                    "unsatisfiable (peer finished): bytes went missing")
            return self.violation
        if not self.abort:
            total = self.scenario.total_bytes
            if self.received != list(range(total)):
                self._violate(
                    V_LOST_BYTES,
                    f"delivered {len(self.received)}/{total} bytes "
                    "(out-of-order or missing) at termination")
                return self.violation
            for t in self.threads.values():
                if t.result != DONE:
                    self._violate(
                        V_MODEL_ERROR,
                        f"{t.tid} ended {t.result!r} with no abort fired")
                    return self.violation
        return None
