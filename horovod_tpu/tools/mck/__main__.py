"""``python -m horovod_tpu.tools.mck`` — see the package docstring."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
