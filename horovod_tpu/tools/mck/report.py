"""Human and machine rendering of model-checker results.

The text report is what a developer reads when CI goes red: the
violation class, why it matters in production terms, and the minimal
reproducing schedule — every scheduled action in order, small enough to
walk through by hand.  The JSON report is the CI artifact
(``ci/mck.last.report.json``): schedule counts and completeness per
scenario, so "proved" is auditable and a truncated run cannot
impersonate an exhaustive one.
"""

from __future__ import annotations

import json
from typing import List

from .explore import ExploreResult


def render_result(res: ExploreResult) -> str:
    head = f"{res.scenario.name} [{res.model}"
    if res.mutation_name:
        head += f", mutant {res.mutation_name}"
    head += "]"
    status = "OK" if res.ok else "VIOLATION"
    if res.truncated:
        status += " (TRUNCATED: schedule cap hit, space NOT exhausted)"
    lines = [
        f"{head}: {status}",
        f"  schedules explored: {res.schedules}  "
        f"max depth: {res.max_depth}  "
        f"preemption bound: {res.bound}  "
        f"elapsed: {res.elapsed:.2f}s",
    ]
    for viol in res.violations.values():
        lines.append(f"  {viol.name}: {viol.detail}")
        if res.min_bound is not None:
            lines.append(
                f"  minimal counterexample ({res.min_bound} "
                f"preemption(s), {len(viol.schedule)} actions):")
        else:
            lines.append(
                f"  counterexample ({len(viol.schedule)} actions):")
        lines.extend(f"    {step}" for step in viol.schedule)
    return "\n".join(lines)


def render_text(results: List[ExploreResult]) -> str:
    return "\n".join(render_result(r) for r in results)


def summary_line(results: List[ExploreResult]) -> str:
    scheds = sum(r.schedules for r in results)
    bad = sorted({name for r in results for name in r.violations})
    trunc = sum(1 for r in results if r.truncated)
    verdict = f"violations: {', '.join(bad)}" if bad else "no violations"
    tail = f"; {trunc} run(s) truncated" if trunc else ""
    return (f"hvd-mck: {len(results)} run(s), {scheds} schedules — "
            f"{verdict}{tail}")


def to_report_dict(results: List[ExploreResult], mode: str) -> dict:
    return {
        "tool": "hvd-mck",
        "mode": mode,
        "runs": [r.to_dict() for r in results],
        "ok": all(r.ok for r in results),
        "complete": all(r.complete for r in results),
    }


def write_json(results: List[ExploreResult], mode: str,
               path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_report_dict(results, mode), fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
