"""``hvd-mck proto`` — the elastic-epoch-protocol checking mode.

Same exit-code contract as the shm mode: 0 clean, 1 violation (or a
surviving mutant), 2 for a truncated ``--smoke`` run or an unknown
scenario/mutation name.  The JSON report shares the shm schema with
``"mode": "proto"`` (report.py), so CI tooling reads both artifacts the
same way.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from .explore import ExploreResult, check
from .proto_model import proto_execution_factory, proto_unit
from .proto_mutations import PROTO_MUTATIONS
from .proto_scenarios import PROTO_SCENARIOS
from .report import render_result, summary_line, write_json


def _parser() -> argparse.ArgumentParser:
    par = argparse.ArgumentParser(
        prog="hvd-mck proto",
        description="crash/reorder model checker for the elastic epoch "
                    "protocol (driver judgment, batched-transaction WAL, "
                    "worker posts) — the production kernels driven "
                    "against a model cluster")
    par.add_argument("--scenario", action="append", default=None,
                     metavar="NAME",
                     help="scenario to check (repeatable; default: all)")
    par.add_argument("--preemptions", type=int, default=None,
                     help="override the per-scenario preemption bound "
                          "(crashes and clock advances are free)")
    par.add_argument("--max-schedules", type=int, default=50000,
                     help="schedule cap per run; hitting it reports the "
                          "run as TRUNCATED, never as proved")
    par.add_argument("--max-steps", type=int, default=600,
                     help="per-schedule action budget")
    par.add_argument("--mutation", metavar="NAME",
                     help="run one seeded mutation from the kill suite")
    par.add_argument("--inject", metavar="NAME",
                     help="checker-has-teeth guard: run one seeded "
                          "mutation as a PLAIN check — exit 1 iff the "
                          "violation is found (the shm lane's weak-mode "
                          "counterfactual, for this protocol); an exit "
                          "of 0 means the checker went blind")
    par.add_argument("--mutants", action="store_true",
                     help="run the full mutation-kill suite: exit 0 iff "
                          "every seeded protocol bug is caught")
    par.add_argument("--smoke", action="store_true",
                     help="CI gate: all scenarios clean AND complete; "
                          "exit 2 if any run truncated")
    par.add_argument("--json", metavar="PATH",
                     help="write the machine-readable report here")
    par.add_argument("--list", action="store_true",
                     help="list scenarios and mutations, then exit")
    par.add_argument("-q", "--quiet", action="store_true",
                     help="print only the summary line and violations")
    return par


def _print_listing() -> None:
    print("proto scenarios:")
    for scn in PROTO_SCENARIOS.values():
        print(f"  {scn.name:22s} ticks={scn.ticks} "
              f"slots={len(scn.slots)} "
              f"crashes=st:{scn.store_crashes}/drv:{scn.driver_crashes} "
              f"preemptions<={scn.preemptions}")
        print(f"           {scn.description}")
    print("proto mutations (kill suite):")
    for mut in PROTO_MUTATIONS.values():
        print(f"  {mut.name:26s} [{mut.role} @ {mut.scenario}] "
              f"-> {', '.join(sorted(mut.expected))}")
        print(f"           {mut.description}")


def _check(scenario, args, mutation=None) -> ExploreResult:
    return check(scenario, "proto", mutation=mutation,
                 bound=args.preemptions,
                 max_schedules=args.max_schedules,
                 max_steps=args.max_steps,
                 execution_factory=proto_execution_factory,
                 unit_fn=proto_unit)


def _run_mutants(args, names: List[str]) -> int:
    results: List[ExploreResult] = []
    unkilled: List[str] = []
    for name in names:
        mut = PROTO_MUTATIONS[name]
        res = _check(PROTO_SCENARIOS[mut.scenario], args, mutation=mut)
        results.append(res)
        caught = set(res.violations) & mut.expected
        if caught:
            if not args.quiet:
                print(render_result(res))
                print(f"  KILLED by {', '.join(sorted(caught))}")
        else:
            unkilled.append(name)
            print(render_result(res))
            found = ", ".join(sorted(res.violations)) or "nothing"
            print(f"  NOT KILLED: expected one of "
                  f"{', '.join(sorted(mut.expected))}, found {found}")
    if args.json:
        write_json(results, "proto", args.json)
    print(summary_line(results))
    if unkilled:
        print(f"hvd-mck proto: mutation suite FAILED — surviving "
              f"mutants: {', '.join(unkilled)} (the checker's bounds no "
              f"longer catch seeded protocol bugs)")
        return 1
    print(f"hvd-mck proto: mutation suite passed — "
          f"{len(names)}/{len(names)} mutants killed")
    return 0


def proto_main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.list:
        _print_listing()
        return 0

    if args.inject:
        if args.inject not in PROTO_MUTATIONS:
            print(f"hvd-mck proto: unknown mutation {args.inject!r} "
                  f"(have: {', '.join(PROTO_MUTATIONS)})", file=sys.stderr)
            return 2
        mut = PROTO_MUTATIONS[args.inject]
        res = _check(PROTO_SCENARIOS[mut.scenario], args, mutation=mut)
        print(render_result(res))
        if args.json:
            write_json([res], "proto", args.json)
        print(summary_line([res]))
        return 1 if res.violations else 0

    if args.mutation or args.mutants:
        if args.mutation:
            if args.mutation not in PROTO_MUTATIONS:
                print(f"hvd-mck proto: unknown mutation "
                      f"{args.mutation!r} "
                      f"(have: {', '.join(PROTO_MUTATIONS)})",
                      file=sys.stderr)
                return 2
            names = [args.mutation]
        else:
            names = list(PROTO_MUTATIONS)
        return _run_mutants(args, names)

    names = args.scenario or list(PROTO_SCENARIOS)
    for name in names:
        if name not in PROTO_SCENARIOS:
            print(f"hvd-mck proto: unknown scenario {name!r} "
                  f"(have: {', '.join(PROTO_SCENARIOS)})",
                  file=sys.stderr)
            return 2
    results = []
    for name in names:
        res = _check(PROTO_SCENARIOS[name], args)
        results.append(res)
        if not args.quiet or not res.ok:
            print(render_result(res))
    if args.json:
        write_json(results, "proto", args.json)
    print(summary_line(results))
    if any(not r.ok for r in results):
        return 1
    if args.smoke and any(r.truncated for r in results):
        print("hvd-mck proto: smoke run truncated — raise "
              "--max-schedules or shrink the scenario; an incomplete "
              "exploration is not a proof", file=sys.stderr)
        return 2
    return 0
