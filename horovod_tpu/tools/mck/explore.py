"""Bounded-exhaustive schedule exploration with sleep-set pruning.

The explorer enumerates every interleaving of an :class:`Execution`'s
enabled actions up to a CHESS-style preemption bound, by depth-first
search with replay (the protocol generators cannot be forked, so
backtracking re-runs the action prefix — executions are tiny and fully
deterministic, which keeps this honest and cheap).

Two classic reductions, both documented in docs/static_analysis.md:

- **Preemption bounding** (Musuvathi/Qadeer): context switches away
  from a unit that could still run are budgeted.  A thread and its
  store-buffer flush agent count as ONE unit (the buffer drains on the
  thread's own core), and environment actions (the abort injector) are
  free.  Empirically almost every concurrency bug in this protocol
  class reproduces within 2-3 preemptions; the per-scenario bounds live
  with the scenarios.
- **Sleep sets** (Godefroid): after exploring action ``a`` at a state,
  sibling branches need not re-explore actions independent of ``a``
  first — the interleavings commute.  Dependence is conservative: same
  scheduling unit, or overlapping location footprints with at least one
  write (``Execution.touches``).

The combination is a bug-finding bound, not an unbounded proof: a trace
pruned by the sleep set is Mazurkiewicz-equivalent to an explored one,
but its equivalent representative could in principle sit just outside
the preemption budget.  The mutation-kill suite (tests/test_mck.py)
exists precisely to demonstrate the configured bounds still catch every
seeded protocol bug, and ``truncated`` reporting keeps schedule caps
from silently passing as exhaustive.

After a violating run, :func:`check` re-explores at ascending preemption
bounds so the reported counterexample is one of MINIMAL preemption count
— the shortest story a human has to read.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from .model import Execution, Scenario, Violation, unit


def _conflict(a: tuple, ta: frozenset, b: tuple, tb: frozenset,
              unit_fn=unit) -> bool:
    """Dependence relation for sleep sets: a shared location with at
    least one writer, or the same scheduling unit — EXCEPT a thread op
    against the thread's OWN flush agent, which commutes: store-buffer
    forwarding means draining an entry never changes what the owning
    thread's loads/copies/polls observe, only what other agents do (and
    the location rule covers those pairs).  The thread's own futex
    syscalls DO conflict with its flushes — the syscall drain disables
    them — and are caught below via the drained-entry footprint."""
    if unit_fn(a) == unit_fn(b):
        if {a[0], b[0]} == {"t", "f"}:
            thread_touch = ta if a[0] == "t" else tb
            return ("w", "futex") in thread_touch
        return True
    for mode_a, loc_a in ta:
        for mode_b, loc_b in tb:
            if loc_a == loc_b and "w" in (mode_a, mode_b):
                return True
    return False


class _Frame:
    __slots__ = ("candidates", "idx", "explored", "sleep", "last_unit",
                 "preemptions", "enabled_units")

    def __init__(self, candidates, sleep, last_unit, preemptions,
                 enabled_units):
        self.candidates = candidates
        self.idx = 0
        self.explored: List[Tuple[tuple, frozenset]] = []
        self.sleep = sleep
        self.last_unit = last_unit
        self.preemptions = preemptions
        self.enabled_units = enabled_units


class ExploreResult:
    """Outcome of one bounded exploration of one scenario."""

    def __init__(self, scenario: Scenario, model: str,
                 mutation_name: Optional[str], bound: int):
        self.scenario = scenario
        self.model = model
        self.mutation_name = mutation_name
        self.bound = bound
        self.min_bound: Optional[int] = None
        self.schedules = 0
        self.max_depth = 0
        self.truncated = False
        self.violations: Dict[str, Violation] = {}
        self.elapsed = 0.0

    @property
    def complete(self) -> bool:
        return not self.truncated

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario.name,
            "model": self.model,
            "mutation": self.mutation_name,
            "preemption_bound": self.bound,
            "minimal_bound": self.min_bound,
            "schedules": self.schedules,
            "max_depth": self.max_depth,
            "complete": self.complete,
            "elapsed_secs": round(self.elapsed, 3),
            "violations": [v.to_dict() for v in self.violations.values()],
        }


def explore(scenario: Scenario, model: str, mutation=None,
            bound: Optional[int] = None, max_schedules: int = 50000,
            max_steps: int = 600, collect: bool = False,
            sleep_sets: bool = True, structural: bool = True,
            execution_factory=None, unit_fn=None) -> ExploreResult:
    """Explore every schedule of ``scenario`` under ``model`` up to
    ``bound`` preemptions.  ``collect`` keeps going after the first
    violation to gather one counterexample per violation class.

    ``execution_factory`` / ``unit_fn`` generalize the engine to other
    execution models (the ``proto`` mode's message-passing cluster): the
    factory builds a fresh execution duck-typing :class:`Execution`
    (``enabled_actions`` / ``touches`` / ``step`` / ``final_check`` /
    ``violation`` / ``steps``), and ``unit_fn`` maps an action to its
    scheduling unit ("env" actions are preemption-free).  Defaults are
    the shared-memory model this module was born with."""
    if bound is None:
        bound = scenario.preemptions
    if unit_fn is None:
        unit_fn = unit
    res = ExploreResult(scenario, model,
                        getattr(mutation, "name", None), bound)
    started = time.monotonic()

    def fresh() -> Execution:
        if execution_factory is not None:
            return execution_factory(scenario, model, mutation=mutation,
                                     max_steps=max_steps)
        return Execution(scenario, model, mutation=mutation,
                         max_steps=max_steps, structural=structural)

    def replay(prefix: List[tuple]) -> Execution:
        ex = fresh()
        for act in prefix:
            ex.step(act)
        return ex

    def make_frame(ex: Execution, sleep: dict, last_unit,
                   preemptions: int) -> Optional[_Frame]:
        enabled = ex.enabled_actions()
        if not enabled:
            return None
        # Continuation first: finishing the running unit's block keeps
        # preemption-free schedules at the front of the search.
        cont = [a for a in enabled if unit_fn(a) == last_unit]
        rest = sorted((a for a in enabled if unit_fn(a) != last_unit),
                      key=repr)
        return _Frame(cont + rest, sleep, last_unit, preemptions,
                      frozenset(unit_fn(a) for a in enabled))

    def leaf(ex: Execution) -> None:
        res.schedules += 1
        res.max_depth = max(res.max_depth, ex.steps)
        if res.schedules >= max_schedules:
            res.truncated = True
        viol = ex.violation if ex.violation is not None else ex.final_check()
        if viol is not None and viol.name not in res.violations:
            res.violations[viol.name] = viol

    live = fresh()
    root = make_frame(live, {}, None, 0)
    if root is None:
        leaf(live)
        res.elapsed = time.monotonic() - started
        return res

    prefix: List[tuple] = []
    stack = [root]
    while stack:
        if res.truncated or (res.violations and not collect):
            break
        frame = stack[-1]
        action = None
        cost = 0
        while frame.idx < len(frame.candidates):
            cand = frame.candidates[frame.idx]
            frame.idx += 1
            if sleep_sets and cand in frame.sleep:
                continue
            u = unit_fn(cand)
            cost = 1 if (u != "env" and frame.last_unit is not None
                         and u != frame.last_unit
                         and frame.last_unit in frame.enabled_units) else 0
            if frame.preemptions + cost > bound:
                continue
            action = cand
            break
        if action is None:
            stack.pop()
            if stack and prefix:
                prefix.pop()
                live = replay(prefix)
            continue
        touch = live.touches(action)
        child_sleep = {
            b: tb
            for b, tb in list(frame.sleep.items()) + frame.explored
            if not _conflict(action, touch, b, tb, unit_fn)
        } if sleep_sets else {}
        frame.explored.append((action, touch))
        live.step(action)
        prefix.append(action)
        next_unit = frame.last_unit if unit_fn(action) == "env" \
            else unit_fn(action)
        child = make_frame(live, child_sleep, next_unit,
                           frame.preemptions + cost)
        if child is None:
            leaf(live)
            prefix.pop()
            live = replay(prefix)
        else:
            stack.append(child)

    res.elapsed = time.monotonic() - started
    return res


def check(scenario: Scenario, model: str, mutation=None,
          bound: Optional[int] = None, max_schedules: int = 50000,
          max_steps: int = 600, collect: bool = True,
          sleep_sets: bool = True, structural: bool = True,
          execution_factory=None, unit_fn=None) -> ExploreResult:
    """Explore at the scenario's full preemption bound; on violation,
    re-run at ascending bounds so the reported counterexamples carry the
    minimal number of preemptions that exhibits each class."""
    if bound is None:
        bound = scenario.preemptions
    res = explore(scenario, model, mutation=mutation, bound=bound,
                  max_schedules=max_schedules, max_steps=max_steps,
                  collect=collect, sleep_sets=sleep_sets,
                  structural=structural,
                  execution_factory=execution_factory, unit_fn=unit_fn)
    if res.violations:
        for smaller in range(bound):
            narrow = explore(scenario, model, mutation=mutation,
                             bound=smaller, max_schedules=max_schedules,
                             max_steps=max_steps, collect=collect,
                             sleep_sets=sleep_sets, structural=structural,
                             execution_factory=execution_factory,
                             unit_fn=unit_fn)
            if narrow.violations:
                for name, viol in narrow.violations.items():
                    res.violations[name] = viol
                res.min_bound = smaller
                break
        else:
            res.min_bound = bound
    return res
