"""The hvd-lint rule set — each rule encodes one invariant this codebase
actually depends on (see module docstrings it references for the why).

Rules are deliberately syntactic and local: they run on a single file's
AST plus a small amount of cross-file state (the fault-site registry, the
fault-injection doc).  False positives are handled by suppression comments
with mandatory justification, not by weakening the rule.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import FileContext, Project, Violation

HOROVOD_KNOB_RE = re.compile(r"^HOROVOD_[A-Z0-9_]+$")

#: Terminal attribute/variable names that denote a lock-ish object.  ``cv``
#: and ``cond`` are included so a Condition's no-timeout ``wait`` inside its
#: own ``with cv:`` block is caught too.
LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex|cv|cond|condition)$",
                          re.IGNORECASE)

ENV_GETTERS = {"get_int", "get_float", "get_bool", "get_str"}


def _terminal_name(node: ast.AST) -> Optional[str]:
    """Last dotted segment of a Name/Attribute chain (``p.send_lock`` ->
    ``send_lock``); None for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted repr for diagnostics and identity ('self._lock')."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    return "<expr>"


def _is_lockish(node: ast.AST) -> bool:
    name = _terminal_name(node)
    return name is not None and LOCK_NAME_RE.search(name) is not None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class Rule:
    code = "HVD???"
    title = ""

    def check(self, ctx: FileContext,
              project: Project) -> Iterator[Violation]:  # pragma: no cover
        raise NotImplementedError

    def _v(self, ctx: FileContext, node: ast.AST, msg: str) -> Violation:
        return Violation(self.code, ctx.path,
                         getattr(node, "lineno", 1),
                         getattr(node, "col_offset", 0), msg)


# ---------------------------------------------------------------------------
# HVD001 — blocking call while holding a lock
# ---------------------------------------------------------------------------

class BlockingUnderLock(Rule):
    """The PR 2 hang-class contract: nothing may block unboundedly while a
    lock is held.  A blocked holder wedges every other thread that needs
    the lock — including the abort path that would have un-wedged it.

    Detected blocking shapes (inside a ``with <lock>:`` body, or between a
    lock's ``.acquire()`` and ``.release()`` in the same function):

    - ``time.sleep(...)``
    - raw socket ops (``recv``/``recv_into``/``accept``/``send``/
      ``sendall`` on a receiver whose name mentions sock/listener/conn)
    - ``.join()`` / ``.wait()`` / ``.wait_for(pred)`` / ``.result()`` /
      ``.communicate()`` without a timeout
    - ``subprocess.run/call/check_call/check_output`` without ``timeout=``
    - ``.get()`` with no args on a queue-named receiver
    """

    code = "HVD001"
    title = "blocking call while holding a lock"

    _SOCK_RECEIVER_RE = re.compile(r"(sock|listener|conn)", re.IGNORECASE)
    _SOCK_METHODS = {"recv", "recv_into", "recvfrom", "accept",
                     "send", "sendall", "sendto"}
    _SUBPROCESS_FUNCS = {"run", "call", "check_call", "check_output"}

    def check(self, ctx, project):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, node)

    def _check_function(self, ctx, fn) -> Iterator[Violation]:
        held: List[str] = []
        yield from self._visit_stmts(ctx, fn.body, held)

    def _visit_stmts(self, ctx, stmts, held) -> Iterator[Violation]:
        for stmt in stmts:
            yield from self._visit_stmt(ctx, stmt, held)

    def _visit_stmt(self, ctx, stmt, held) -> Iterator[Violation]:
        # Track acquire()/release() pairs in source order.  This is a lint
        # approximation (no path sensitivity), which is exactly what we
        # want: code whose lock extent is hard to see statically is code
        # that should be rewritten as a ``with`` block.
        for call in self._calls_in(stmt):
            name = _terminal_name(call.func)
            if name == "acquire" and isinstance(call.func, ast.Attribute) \
                    and _is_lockish(call.func.value):
                lock = _dotted(call.func.value)
                if lock not in held:
                    held.append(lock)
            elif name == "release" and isinstance(call.func, ast.Attribute) \
                    and _is_lockish(call.func.value):
                lock = _dotted(call.func.value)
                if lock in held:
                    held.remove(lock)

        if isinstance(stmt, ast.With):
            pushed = []
            for item in stmt.items:
                cm = item.context_expr
                if _is_lockish(cm):
                    pushed.append(_dotted(cm))
            held.extend(pushed)
            yield from self._visit_stmts(ctx, stmt.body, held)
            for name in pushed:
                if name in held:
                    held.remove(name)
            return

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # A nested def runs later, on some other call stack: the
            # enclosing lock scope does not apply; its own body is visited
            # by the module-level walk.
            return

        if held:
            yield from self._flag_blocking(ctx, stmt, held)

        for attr in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, attr, []):
                yield from self._visit_stmt(ctx, sub, held)
        for handler in getattr(stmt, "handlers", []):
            yield from self._visit_stmts(ctx, handler.body, held)

    def _calls_in(self, stmt) -> Iterator[ast.Call]:
        """Calls in the statement's own expressions (not sub-statements,
        not nested defs)."""
        for field_ in ast.iter_fields(stmt):
            _, value = field_
            values = value if isinstance(value, list) else [value]
            for v in values:
                if isinstance(v, ast.stmt) or not isinstance(v, ast.AST):
                    continue
                for sub in ast.walk(v):
                    if isinstance(sub, ast.Call):
                        yield sub

    def _flag_blocking(self, ctx, stmt, held) -> Iterator[Violation]:
        lock_desc = ", ".join(held)
        for call in self._calls_in(stmt):
            msg = self._blocking_reason(call)
            if msg:
                yield self._v(
                    ctx, call,
                    f"{msg} while holding {lock_desc}; a blocked holder "
                    "wedges every thread that needs the lock (move the "
                    "blocking call outside the lock scope or bound it)")

    def _blocking_reason(self, call: ast.Call) -> Optional[str]:
        func = call.func
        name = _terminal_name(func)
        if name is None:
            return None
        has_timeout_kw = _kw(call, "timeout") is not None

        if name == "sleep":
            recv = func.value if isinstance(func, ast.Attribute) else None
            if recv is None or _terminal_name(recv) == "time":
                return "time.sleep"
        if isinstance(func, ast.Attribute):
            recv_name = _dotted(func.value)
            if name in self._SOCK_METHODS \
                    and self._SOCK_RECEIVER_RE.search(recv_name):
                return f"raw socket .{name}()"
            if name == "join" and not call.args and not has_timeout_kw:
                # str.join always passes an iterable positionally, so a
                # zero-positional-arg join is a thread/process join.
                return "unbounded .join()"
            if name in ("wait", "communicate", "result") \
                    and not call.args and not has_timeout_kw:
                return f"unbounded .{name}()"
            if name == "wait_for" and len(call.args) <= 1 \
                    and not has_timeout_kw:
                return "unbounded .wait_for()"
            if name == "get" and not call.args and not has_timeout_kw \
                    and _kw(call, "block") is None \
                    and re.search(r"(queue|_q)$", recv_name, re.IGNORECASE):
                return "unbounded queue .get()"
            if name in self._SUBPROCESS_FUNCS \
                    and _terminal_name(func.value) == "subprocess" \
                    and not has_timeout_kw:
                return f"subprocess.{name} without timeout"
        return None


# ---------------------------------------------------------------------------
# HVD002 — raw HOROVOD_* env literal outside common/env.py
# ---------------------------------------------------------------------------

class EnvLiteralOutsideRegistry(Rule):
    """``common/env.py``'s module docstring promises it is the single
    source of config truth.  A ``HOROVOD_*`` knob read (or written)
    through a string literal anywhere else forks that truth: the knob is
    invisible to the registry, its default gets duplicated, and a typo'd
    name silently reads nothing."""

    code = "HVD002"
    title = "raw HOROVOD_* env literal outside common/env.py"

    def check(self, ctx, project):
        if ctx.rel_path.endswith("common/env.py"):
            return
        for node in ast.walk(ctx.tree):
            lit = self._env_literal(node)
            if lit is not None:
                yield self._v(
                    ctx, node,
                    f"raw env access of {lit!r}; declare a named constant "
                    "in horovod_tpu/common/env.py and reference it "
                    "(single config-truth contract)")

    def _env_literal(self, node: ast.AST) -> Optional[str]:
        # os.environ["HOROVOD_X"] loads/stores/deletes
        if isinstance(node, ast.Subscript) and self._is_environ(node.value):
            return self._knob(node.slice)
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        name = _terminal_name(func)
        if name in ("get", "setdefault", "pop") \
                and isinstance(func, ast.Attribute) \
                and self._is_environ(func.value) and node.args:
            return self._knob(node.args[0])
        if name == "getenv" and isinstance(func, ast.Attribute) \
                and _terminal_name(func.value) == "os" and node.args:
            return self._knob(node.args[0])
        if name in ENV_GETTERS and node.args:
            return self._knob(node.args[0])
        return None

    @staticmethod
    def _is_environ(node: ast.AST) -> bool:
        return _terminal_name(node) == "environ"

    @staticmethod
    def _knob(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and HOROVOD_KNOB_RE.match(node.value):
            return node.value
        return None


# ---------------------------------------------------------------------------
# HVD003 — fault sites must come from (and be documented in) the registry
# ---------------------------------------------------------------------------

class FaultSiteRegistry(Rule):
    """``faults.inject("tcp.rcv")`` with a typo'd site matches no clause,
    injects nothing, and passes every chaos test vacuously — the exact
    silent failure the fault plane exists to prevent.  Every injected site
    must be a literal found in ``faults.SITES``, and every registry entry
    must appear in ``docs/fault_injection.md`` so operators can discover
    it."""

    code = "HVD003"
    title = "fault site not in faults.SITES / undocumented site"

    def check(self, ctx, project):
        is_registry = ctx.rel_path.endswith("common/faults.py")
        sites = project.fault_sites
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if _terminal_name(func) != "inject":
                continue
            if isinstance(func, ast.Attribute) \
                    and _terminal_name(func.value) != "faults":
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if sites and arg.value not in sites:
                    yield self._v(
                        ctx, node,
                        f"fault site {arg.value!r} is not registered in "
                        f"faults.SITES (known: {', '.join(sites)}); a "
                        "typo'd site injects nothing and passes chaos "
                        "tests vacuously")
            elif not is_registry:
                yield self._v(
                    ctx, node,
                    "fault site must be a string literal from faults.SITES "
                    "(a computed site defeats static verification)")
        if is_registry:
            doc = project.fault_doc
            seen: Set[str] = set()
            for site in sites:
                if site in seen:
                    yield Violation(self.code, ctx.path, 1, 0,
                                    f"duplicate faults.SITES entry {site!r}")
                seen.add(site)
                if doc and f"`{site}`" not in doc:
                    yield Violation(
                        self.code, ctx.path, 1, 0,
                        f"registered fault site {site!r} is missing from "
                        "docs/fault_injection.md (the site table is the "
                        "operator-facing registry mirror)")


# ---------------------------------------------------------------------------
# HVD004 — swallowed exception in a thread-target/daemon-loop body
# ---------------------------------------------------------------------------

class SwallowedThreadException(Rule):
    """The PR 2 loop-death contract: a background thread that dies (or
    eats an error) silently converts a loud failure into a distributed
    hang.  Every ``except:``/``except Exception`` in a thread-run body
    must log, re-raise, or abort-broadcast."""

    code = "HVD004"
    title = "swallowed exception in thread-target/daemon-loop body"

    _LOG_METHODS = {"error", "warning", "exception", "critical",
                    "info", "debug", "log"}

    def check(self, ctx, project):
        targets = self._thread_target_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (node.name in targets or node.name.endswith("_loop")
                    or self._is_thread_run(ctx.tree, node)):
                continue
            for handler in self._handlers_in(node):
                if self._is_broad(handler) \
                        and not self._handled_loudly(handler):
                    yield self._v(
                        ctx, handler,
                        f"broad exception swallowed in thread body "
                        f"{node.name!r}: log it, re-raise, or "
                        "abort-broadcast (silent loop death = "
                        "distributed hang)")

    def _thread_target_names(self, tree) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) == "Thread":
                tgt = _kw(node, "target")
                if tgt is not None:
                    name = _terminal_name(tgt)
                    if name:
                        names.add(name)
        return names

    def _is_thread_run(self, tree, fn) -> bool:
        """``run`` methods of classes deriving from Thread."""
        if fn.name != "run":
            return False
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and fn in node.body:
                return any(_terminal_name(b) == "Thread" for b in node.bases)
        return False

    def _handlers_in(self, fn) -> Iterator[ast.ExceptHandler]:
        # Manual walk that does NOT descend into nested defs: a nested
        # function gets its own assessment iff it is itself a thread body.
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Try):
                yield from node.handlers
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        names = []
        types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
            else [handler.type]
        for t in types:
            names.append(_terminal_name(t))
        return "Exception" in names or "BaseException" in names

    def _handled_loudly(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name and "abort" in name.lower():
                    return True
                if isinstance(node.func, ast.Attribute) \
                        and name in self._LOG_METHODS:
                    recv = _dotted(node.func.value)
                    if "log" in recv.lower():
                        return True
            # Stash-and-surface: the bound exception object is READ in the
            # handler body (appended to an error list, assigned to an
            # attribute the waiting parent re-raises, ...).  Capturing the
            # exception for propagation is not a silent swallow.
            if handler.name and isinstance(node, ast.Name) \
                    and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return True
        return False


# ---------------------------------------------------------------------------
# HVD005 — control-frame wire-tag invariants (core/messages.py)
# ---------------------------------------------------------------------------

class WireTagInvariants(Rule):
    """Frames are distinguished on the wire ONLY by their leading magic,
    and the transport's frame header is ``<Q len|flags><I crc32>`` — the
    length word's top bit reserved for control frames (AbortFrame), the
    CRC field owned by the transport layer alone.  Two classes sharing a
    magic, a frame class without one, messages.py reaching for the
    control bit or computing its own wire CRC, or the header registry's
    structs drifting from the documented layout all produce
    positional-framing desyncs (or silently unverified bytes) that
    surface as 'survivors read negotiation bytes as tensor data'.

    The header VALUES are checked in ``transport/frame_bits.py``, the
    registry every transport imports from (HVD008 enforces that nothing
    re-derives them elsewhere)."""

    code = "HVD005"
    title = "wire framing invariant (core/messages.py, " \
            "transport/frame_bits.py)"

    #: The frame-header layout contract (docs/integrity.md): the length
    #: word and the CRC field each live in exactly one module-level
    #: struct, with these formats.  Changing either silently desyncs
    #: every peer built from a different revision.
    _HEADER_STRUCTS = {"_LEN": "<Q", "_CRC": "<I"}

    #: The flag-bit reservations (docs/data_plane.md): each must be
    #: declared as ``1 << bit`` so mixed-version skew analysis and the
    #: model checker's wire assumptions stay true by inspection.
    _FLAG_BITS = {"_CTRL_FLAG": 63, "_DEFER_FLAG": 62, "_DIGEST_FLAG": 61}

    def check(self, ctx, project):
        if ctx.rel_path.endswith("transport/frame_bits.py"):
            yield from self._check_transport_header(ctx)
            return
        if not ctx.rel_path.endswith("core/messages.py"):
            return
        magics: Dict[str, Tuple[int, ast.AST]] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id.endswith("_MAGIC"):
                        try:
                            val = ast.literal_eval(node.value)
                        except ValueError:
                            continue
                        magics[tgt.id] = (val, node)
        by_value: Dict[int, str] = {}
        for name, (val, node) in magics.items():
            if val in by_value:
                yield self._v(
                    ctx, node,
                    f"wire tag {name} duplicates {by_value[val]} "
                    f"(0x{val:08X}); frames become indistinguishable")
            else:
                by_value[val] = name
            if not (0 <= val < 2 ** 32):
                yield self._v(ctx, node,
                              f"wire tag {name} does not fit in the u32 "
                              "magic field")

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node, magics)
            lit = self._ctrl_bit_literal(node)
            if lit is not None:
                yield self._v(
                    ctx, lit,
                    "core/messages.py must not touch the length-header top "
                    "bit (1 << 63): it is the transport's control-frame "
                    "flag, reserved as _CTRL_FLAG in "
                    "transport/frame_bits.py")
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) == "crc32":
                yield self._v(
                    ctx, node,
                    "core/messages.py must not compute wire CRCs: the "
                    "integrity envelope is the transport's _CRC header "
                    "field (one layer, one owner — a second checksum "
                    "here would drift from it)")

    def _check_transport_header(self, ctx) -> Iterator[Violation]:
        """transport/frame_bits.py owns the frame header: ``_LEN``/
        ``_CRC`` structs with the documented formats, and the flag-bit
        reservations (``_CTRL_FLAG = 1 << 63`` and friends), must all
        exist exactly as declared — the wire contract every peer and
        every doc (docs/integrity.md) assumes."""
        structs: Dict[str, object] = {}
        flags: Dict[str, bool] = {name: False for name in self._FLAG_BITS}
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                v = node.value
                if isinstance(v, ast.Call) \
                        and _terminal_name(v.func) == "Struct" \
                        and v.args and isinstance(v.args[0], ast.Constant):
                    structs[tgt.id] = (v.args[0].value, node)
                bit = self._FLAG_BITS.get(tgt.id)
                if bit is not None \
                        and self._bit_literal(v, bit) is not None:
                    flags[tgt.id] = True
        for name, fmt in self._HEADER_STRUCTS.items():
            got = structs.get(name)
            if got is None:
                yield Violation(
                    self.code, ctx.path, 1, 0,
                    f"transport/frame_bits.py must declare {name} = "
                    f"struct.Struct({fmt!r}) (frame-header layout "
                    "contract: <Q len|flags><I crc32>)")
            elif got[0] != fmt:
                yield self._v(
                    ctx, got[1],
                    f"frame-header struct {name} must use format {fmt!r} "
                    f"(found {got[0]!r}); peers built from a different "
                    "layout desync on every frame")
        for name, bit in self._FLAG_BITS.items():
            if not flags[name]:
                yield Violation(
                    self.code, ctx.path, 1, 0,
                    f"transport/frame_bits.py must reserve length-header "
                    f"bit {bit} as {name} = 1 << {bit} (the flag-lane "
                    "contract mixed-version skew detection depends on)")

    #: every Writer method that appends bytes — the magic must precede
    #: ALL of them, not just the first u32 (a u8 written before the u32
    #: magic still shifts the leading 4 bytes off the tag).
    _WRITER_METHODS = frozenset({
        "u8", "u32", "i32", "i64", "f64",
        "string", "i64_list", "i32_list", "str_list",
    })

    def _check_class(self, ctx, cls, magics) -> Iterator[Violation]:
        to_bytes = None
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) and node.name == "to_bytes":
                to_bytes = node
        if to_bytes is None:
            return
        writes = sorted(
            (node for node in ast.walk(to_bytes)
             if isinstance(node, ast.Call)
             and _terminal_name(node.func) in self._WRITER_METHODS),
            key=lambda n: (n.lineno, n.col_offset))
        if writes:
            first_call = writes[0]
            if _terminal_name(first_call.func) == "u32" and first_call.args:
                first = first_call.args[0]
                if isinstance(first, ast.Name) \
                        and first.id.endswith("_MAGIC"):
                    if first.id not in magics:
                        yield self._v(
                            ctx, first,
                            f"{cls.name}.to_bytes writes undeclared wire "
                            f"tag {first.id}")
                    return
        yield self._v(
            ctx, to_bytes,
            f"{cls.name}.to_bytes must write a module-level *_MAGIC wire "
            "tag as its first field (frames are distinguished only by "
            "their leading magic)")

    @staticmethod
    def _ctrl_bit_literal(node: ast.AST) -> Optional[ast.AST]:
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift) \
                and isinstance(node.right, ast.Constant) \
                and node.right.value == 63:
            return node
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and node.value >= 2 ** 63:
            return node
        return None

    @staticmethod
    def _bit_literal(node: ast.AST, bit: int) -> Optional[ast.AST]:
        """``1 << bit`` (or the equivalent integer constant), exactly."""
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift) \
                and isinstance(node.left, ast.Constant) \
                and node.left.value == 1 \
                and isinstance(node.right, ast.Constant) \
                and node.right.value == bit:
            return node
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and node.value == 2 ** bit:
            return node
        return None


# ---------------------------------------------------------------------------
# HVD006 — anonymous threads
# ---------------------------------------------------------------------------

class AnonymousThread(Rule):
    """Lockdep reports, the stall inspector, and py-spy dumps attribute
    work by thread name; an anonymous ``Thread-12`` is undebuggable in a
    process that runs a dozen daemons.  Every thread must be named (and
    every ThreadPoolExecutor must set ``thread_name_prefix``)."""

    code = "HVD006"
    title = "anonymous thread (threading.Thread without name=)"

    def check(self, ctx, project):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) \
                    and any(_terminal_name(b) == "Thread"
                            for b in node.bases):
                yield from self._check_subclass(ctx, node)
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if name == "Thread" and _kw(node, "target") is not None \
                    and _kw(node, "name") is None:
                yield self._v(
                    ctx, node,
                    "thread has no name=; lockdep and the stall inspector "
                    "cannot attribute an anonymous Thread-N")
            if name == "ThreadPoolExecutor" \
                    and _kw(node, "thread_name_prefix") is None:
                yield self._v(
                    ctx, node,
                    "ThreadPoolExecutor without thread_name_prefix=; "
                    "worker threads become anonymous")

    def _check_subclass(self, ctx, cls) -> Iterator[Violation]:
        """A Thread subclass escapes the Thread(target=...) check, so its
        __init__ must name the thread itself: either pass name= through
        super().__init__/Thread.__init__ or assign self.name."""
        init = None
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) and node.name == "__init__":
                init = node
        if init is None:
            yield self._v(
                ctx, cls,
                f"Thread subclass {cls.name} has no __init__ passing "
                "name=; its instances are anonymous Thread-N")
            return
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and tgt.attr == "name" \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        return
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "__init__" \
                    and _kw(node, "name") is not None:
                return
        yield self._v(
            ctx, init,
            f"{cls.name}.__init__ neither passes name= to the Thread "
            "base nor assigns self.name; instances are anonymous "
            "Thread-N")


# ---------------------------------------------------------------------------
# HVD007 — metric names must come from (and be documented in) the catalog
# ---------------------------------------------------------------------------

class MetricCatalogRule(Rule):
    """``metrics.inc("collectve_latency...")`` with a typo'd name records
    into a series nobody reads — dashboards and the overhead guard pass
    vacuously, the exact silent failure HVD003 closes for fault sites.
    Every name fed to ``metrics.inc``/``set_gauge``/``observe`` (and to
    the ``phase_stats``/``wire_stats`` ``add`` accumulators the registry
    absorbs as views) must be a literal found in ``core/metrics.py``'s
    ``CATALOG``, and every catalog entry must appear in
    ``docs/observability.md`` so operators can discover it."""

    code = "HVD007"
    title = "metric name not in metrics CATALOG / undocumented metric"

    _REG_FUNCS = frozenset({"inc", "set_gauge", "observe"})
    _REG_RECEIVERS = frozenset({"metrics", "registry"})
    _STATS_RECEIVERS = frozenset({"wire_stats", "phase_stats"})

    def check(self, ctx, project):
        is_registry = ctx.rel_path.endswith("core/metrics.py")
        names = project.metric_catalog
        if is_registry:
            yield from self._check_registry(ctx, names, project)
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            fname = _terminal_name(func)
            if not isinstance(func, ast.Attribute):
                continue
            recv = _terminal_name(func.value)
            if fname in self._REG_FUNCS and recv in self._REG_RECEIVERS:
                pass
            elif fname == "add" and recv in self._STATS_RECEIVERS:
                pass
            else:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if names and arg.value not in names:
                    yield self._v(
                        ctx, node,
                        f"metric name {arg.value!r} is not declared in "
                        "core/metrics.py CATALOG; a typo'd name records "
                        "into a series nobody reads")
            else:
                yield self._v(
                    ctx, node,
                    "metric name must be a string literal from the "
                    "core/metrics.py CATALOG (a computed name defeats "
                    "static verification)")

    def _check_registry(self, ctx, names, project) -> Iterator[Violation]:
        doc = project.metrics_doc
        seen: Set[str] = set()
        for name in names:
            if name in seen:
                yield Violation(self.code, ctx.path, 1, 0,
                                f"duplicate CATALOG entry {name!r}")
            seen.add(name)
            if doc and f"`{name}`" not in doc:
                yield Violation(
                    self.code, ctx.path, 1, 0,
                    f"cataloged metric {name!r} is missing from "
                    "docs/observability.md (the catalog table is the "
                    "operator-facing registry mirror)")


# ---------------------------------------------------------------------------
# HVD008 — frame-header bit literals live only in transport/frame_bits.py
# ---------------------------------------------------------------------------

class FrameBitRegistry(Rule):
    """The length word's top byte (bits 56-63) is the wire flag/dtype
    lane: control, digest-deferred, digest-check, and the cast-on-the-
    wire dtype code.  Those positions are the cross-transport,
    cross-VERSION contract — tcp and shm must agree with each other and
    with every older peer — so they are defined exactly once, in
    ``transport/frame_bits.py``, and imported everywhere else.  A ``<<
    56``..``<< 63`` literal (or a re-binding of a registry name) in any
    other module is a second derivation of the same bit position: the
    pre-extraction tree had tcp.py owning the bits while shm.py
    re-derived some and imported the rest, which is exactly how framing
    contracts drift apart."""

    code = "HVD008"
    title = "frame-header bit literal outside transport/frame_bits.py"

    #: Names frame_bits.py exports; re-binding one elsewhere forks the
    #: registry even without a raw bit literal.
    _REGISTRY_NAMES = frozenset({
        "_LEN", "_CRC", "_CTRL_FLAG", "_DEFER_FLAG", "_DIGEST_FLAG",
        "_WIRE_DTYPE_SHIFT", "_WIRE_DTYPE_MASK", "_FLAGS_MASK",
        "_DIGEST_PAYLOAD", "_FrameHeader", "_MAX_FRAME_BYTES",
        # wire dtype codes (the 3-bit lane's values): re-binding one
        # outside the registry forks the compression skew contract
        "_WIRE_DTYPE_RAW", "_WIRE_DTYPE_FP16", "_WIRE_DTYPE_BF16",
        "_WIRE_DTYPE_INT8", "_WIRE_DTYPE_ONEBIT", "_WIRE_DTYPE_TOPK",
    })
    _FLAG_BIT_RANGE = range(56, 64)

    def check(self, ctx, project):
        if ctx.rel_path.endswith("transport/frame_bits.py"):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.LShift) \
                    and isinstance(node.right, ast.Constant) \
                    and isinstance(node.right.value, int) \
                    and node.right.value in self._FLAG_BIT_RANGE:
                yield self._v(
                    ctx, node,
                    f"frame-header bit literal (<< {node.right.value}): "
                    "bits 56-63 of the length word are the wire "
                    "flag/dtype lane, defined once in "
                    "transport/frame_bits.py — import the named constant "
                    "instead of re-deriving the position")
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id in self._REGISTRY_NAMES:
                        yield self._v(
                            ctx, node,
                            f"re-binding of frame-bit registry name "
                            f"{tgt.id}: transport/frame_bits.py is the "
                            "single source of the frame-header contract; "
                            "import it, don't shadow it")


# ---------------------------------------------------------------------------
# HVD009 — shm control words move only through the accessor helpers
# ---------------------------------------------------------------------------

class ShmAccessorDiscipline(Rule):
    """The shm ring's correctness argument is machine-checked (hvd-mck)
    over the step generators, and the proof only covers accesses the
    model can see.  ``transport/shm.py`` therefore funnels EVERY raw
    struct move against a header offset through four accessors
    (``_load_u64``/``_store_u64``/``_load_u32``/``_store_u32``) so the
    set of shared-memory control-word accesses is closed by
    construction.  A raw ``unpack_from``/``pack_into`` against an
    ``_OFF_*`` constant (or a ``*_head_off``/``*_tail_off``/
    ``*_bell_off``/``*_pid_off`` attribute) anywhere else is a
    shared-memory access the checker never explored — an unverified hole
    in a verified protocol."""

    code = "HVD009"
    title = "raw struct access against shm control-word offsets"

    _ACCESSORS = frozenset({"_load_u64", "_store_u64",
                            "_load_u32", "_store_u32"})
    _STRUCT_METHODS = frozenset({"unpack_from", "pack_into"})
    _OFF_CONST_RE = re.compile(r"^_OFF_[A-Z0-9_]+$")
    _OFF_ATTR_RE = re.compile(r"(^|_)(head|tail|bell|pid)_off$")

    def check(self, ctx, project):
        in_shm = ctx.rel_path.endswith("transport/shm.py")
        yield from self._scan(ctx, ctx.tree, None, in_shm)

    def _scan(self, ctx, node, fn_name, in_shm) -> Iterator[Violation]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan(ctx, child, child.name, in_shm)
                continue
            if isinstance(child, ast.Call) \
                    and _terminal_name(child.func) in self._STRUCT_METHODS \
                    and not (in_shm and fn_name in self._ACCESSORS):
                yield from self._check_call(ctx, child, in_shm)
            yield from self._scan(ctx, child, fn_name, in_shm)

    def _check_call(self, ctx, call, in_shm) -> Iterator[Violation]:
        offending = None
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                name = _terminal_name(sub)
                if name is not None and self._is_offset_name(name):
                    offending = name
                    break
            if offending:
                break
        method = _terminal_name(call.func)
        if offending:
            yield self._v(
                ctx, call,
                f"raw {method} against shm header offset {offending}: "
                "control words move only through the "
                "_load_u64/_store_u64/_load_u32/_store_u32 accessors "
                "(the model-checked access set is closed by "
                "construction)")
        elif in_shm:
            yield self._v(
                ctx, call,
                f"raw struct {method} in transport/shm.py outside the "
                "control-word accessors: every shared-memory struct move "
                "must go through _load_u64/_store_u64/_load_u32/"
                "_store_u32 so hvd-mck's access model stays exhaustive")

    def _is_offset_name(self, name: str) -> bool:
        return self._OFF_CONST_RE.match(name) is not None \
            or self._OFF_ATTR_RE.search(name) is not None


# ---------------------------------------------------------------------------
# HVD010 — rendezvous scope names come from transport/scopes.py
# ---------------------------------------------------------------------------

class ScopeNameRegistry(Rule):
    """A rendezvous scope name is a wire contract between the driver, the
    workers, and the store server — three parties that never share code
    at runtime, so a typo reads an empty scope and times out instead of
    failing loudly.  ``transport/scopes.py`` is the single source of
    those names; everything else imports the constant.  A registered
    scope name appearing as a STRING LITERAL in a scope position
    elsewhere (first argument of a store ``set``/``get``/``delete``/
    ``keys``/``wait`` call, or the scope slot of a batch op tuple) is a
    second spelling of the same contract — exactly how ``"epoch_ack"``
    drifted into three modules before the registry existed.  Re-binding
    a ``*_SCOPE`` name to a registered value forks it the same way."""

    code = "HVD010"
    title = "rendezvous scope literal outside transport/scopes.py"

    #: Store-API methods whose FIRST positional argument is a scope,
    #: mapped to the minimum positional arity of the STORE signature —
    #: ``set(scope, key, value)`` has 3, ``get(scope, key)`` has 2,
    #: ``keys(scope)`` has 1.  The arity gate is what keeps a plain dict
    #: lookup like ``fetched.get("epoch_ack")`` (one arg: a local dict
    #: key, not a wire scope) out of the rule's blast radius.
    _SCOPE_CALLS = {
        "set": 3, "store_set": 3,
        "get": 2, "delete": 2, "wait": 2,
        "store_get": 2, "store_delete": 2,
        "keys": 1, "store_keys": 1,
    }
    #: Batch op verbs: ``(verb, scope, key[, value])`` tuples.
    _BATCH_VERBS = frozenset({"set", "get", "delete", "keys"})

    def check(self, ctx, project):
        if ctx.rel_path.endswith("transport/scopes.py"):
            return
        scopes = frozenset(project.scope_registry)
        if not scopes:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in self._SCOPE_CALLS \
                        and len(node.args) >= self._SCOPE_CALLS[name] \
                        and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value in scopes:
                    yield self._v(
                        ctx, node,
                        f"scope literal {node.args[0].value!r} in "
                        f"{name}() call: scope names are a wire contract "
                        "defined once in transport/scopes.py — import "
                        "the constant instead of re-spelling it")
            elif isinstance(node, (ast.Tuple, ast.List)) \
                    and len(node.elts) >= 2 \
                    and isinstance(node.elts[0], ast.Constant) \
                    and node.elts[0].value in self._BATCH_VERBS \
                    and isinstance(node.elts[1], ast.Constant) \
                    and node.elts[1].value in scopes:
                yield self._v(
                    ctx, node,
                    f"scope literal {node.elts[1].value!r} in batch op "
                    f"tuple ({node.elts[0].value!r}, ...): import the "
                    "constant from transport/scopes.py instead of "
                    "re-spelling the wire contract")
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value in scopes:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id.endswith("_SCOPE"):
                        yield self._v(
                            ctx, node,
                            f"re-binding of scope name {tgt.id} = "
                            f"{node.value.value!r}: transport/scopes.py "
                            "is the single source of scope names; "
                            "import it, don't shadow it")


ALL_RULES: Tuple[Rule, ...] = (
    BlockingUnderLock(),
    EnvLiteralOutsideRegistry(),
    FaultSiteRegistry(),
    SwallowedThreadException(),
    WireTagInvariants(),
    AnonymousThread(),
    MetricCatalogRule(),
    FrameBitRegistry(),
    ShmAccessorDiscipline(),
    ScopeNameRegistry(),
)

RULE_CODES = frozenset(r.code for r in ALL_RULES) | {"HVD000"}
