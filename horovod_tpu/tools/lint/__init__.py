"""hvd-lint — codebase-invariant static analysis for horovod_tpu.

The failure plane (PR 2) closed a class of distributed hangs, but its
invariants were enforced only by convention: one new ``recv()`` under a
held ``send_lock``, one typo'd ``faults.inject("tcp.rcv")`` site, or one
silently-swallowed background-thread exception quietly reopens the hang
class.  Horovod proper leans on C++ sanitizers/TSan for this; our control
plane is pure Python, so the equivalent is built in-repo: a small AST
checker framework with rules tuned to THIS codebase's contracts.

Usage::

    python -m horovod_tpu.tools.lint horovod_tpu/
    hvd-lint horovod_tpu/ tests/some_file.py

Rules (see ``rules.py`` and ``docs/static_analysis.md``):

==========  ===========================================================
HVD000      malformed/unjustified ``# hvdlint: disable=...`` comment
HVD001      blocking call while holding a lock
HVD002      raw ``HOROVOD_*`` env literal outside ``common/env.py``
HVD003      fault site not in ``faults.SITES`` / undocumented site
HVD004      swallowed exception in a thread-target/daemon-loop body
HVD005      control-frame wire-tag invariants in ``core/messages.py``
HVD006      anonymous thread (``threading.Thread`` without ``name=``)
HVD007      metric name not in ``core/metrics.py`` ``CATALOG`` /
            undocumented metric
==========  ===========================================================

Suppressions: a violation is silenced by a comment on its line (or on a
comment-only line directly above it)::

    sock.sendall(buf)  # hvdlint: disable=HVD001 -- bounded by settimeout(5)

The justification after ``--`` is REQUIRED — a suppression that doesn't
say *why* the invariant is safe to break here is itself a violation
(HVD000).  Unknown rule codes in a suppression are HVD000 too, so a typo
can't silently disable nothing.
"""

from __future__ import annotations

import ast
import io
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Violation", "FileContext", "Project", "lint_paths", "lint_source",
    "format_violation", "main",
]


@dataclass(frozen=True)
class Violation:
    code: str
    path: str
    line: int
    col: int
    message: str


# Suppression-comment grammar (one or more codes, then a mandatory
# justification; see the module docstring for the written form — spelling
# the literal syntax here would make this very comment parse as one).
_SUPPRESS_RE = re.compile(
    r"#\s*hvdlint:\s*disable=\s*([A-Za-z0-9_,\s]+?)\s*"
    r"(?:--\s*(?P<why>.*?))?\s*$")


@dataclass
class _Suppression:
    codes: Tuple[str, ...]
    justification: str
    comment_line: int


@dataclass
class FileContext:
    """Everything a rule needs about one source file."""

    path: str            # path as given on the command line
    rel_path: str        # posix-style path relative to the package root
    source: str
    tree: ast.AST
    suppressions: Dict[int, List[_Suppression]] = field(default_factory=dict)
    pre_errors: List[Violation] = field(default_factory=list)


class Project:
    """Cross-file state shared by all rules in one lint run (the fault-site
    registry, the fault-injection doc) — resolved lazily so linting an
    arbitrary file list doesn't require the whole tree."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or _find_package_root()
        self._sites: Optional[Tuple[str, ...]] = None
        self._fault_doc: Optional[str] = None
        self._metric_catalog: Optional[Tuple[str, ...]] = None
        self._metrics_doc: Optional[str] = None
        self._scope_registry: Optional[Tuple[str, ...]] = None

    @property
    def fault_sites(self) -> Tuple[str, ...]:
        """``faults.SITES`` parsed from the AST of common/faults.py —
        parsed, not imported, so linting never executes package code (an
        import would run ``configure()`` against the ambient env)."""
        if self._sites is None:
            self._sites = self._parse_sites()
        return self._sites

    def _parse_sites(self) -> Tuple[str, ...]:
        path = os.path.join(self.root, "horovod_tpu", "common", "faults.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return ()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "SITES":
                        vals = getattr(node.value, "elts", [])
                        return tuple(
                            v.value for v in vals
                            if isinstance(v, ast.Constant)
                            and isinstance(v.value, str))
        return ()

    @property
    def fault_doc(self) -> str:
        if self._fault_doc is None:
            self._fault_doc = self._read_doc("fault_injection.md")
        return self._fault_doc

    @property
    def metric_catalog(self) -> Tuple[str, ...]:
        """``CATALOG`` keys parsed from the AST of core/metrics.py —
        parsed, not imported, like :attr:`fault_sites` (duplicate dict
        keys survive the parse, so HVD007 can flag them)."""
        if self._metric_catalog is None:
            self._metric_catalog = self._parse_metric_catalog()
        return self._metric_catalog

    def _parse_metric_catalog(self) -> Tuple[str, ...]:
        path = os.path.join(self.root, "horovod_tpu", "core", "metrics.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return ()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "CATALOG" \
                            and isinstance(node.value, ast.Dict):
                        return tuple(
                            k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str))
        return ()

    @property
    def scope_registry(self) -> Tuple[str, ...]:
        """Scope-name string values parsed from the AST of
        transport/scopes.py (every ``*_SCOPE = "..."`` assignment) —
        parsed, not imported, like :attr:`fault_sites`.  HVD010 uses the
        VALUES: a registered scope name appearing as a string literal in
        a scope position anywhere else is a forked wire contract."""
        if self._scope_registry is None:
            self._scope_registry = self._parse_scope_registry()
        return self._scope_registry

    def _parse_scope_registry(self) -> Tuple[str, ...]:
        path = os.path.join(self.root, "horovod_tpu", "transport",
                            "scopes.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return ()
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id.endswith("_SCOPE"):
                        out.append(node.value.value)
        return tuple(out)

    @property
    def metrics_doc(self) -> str:
        if self._metrics_doc is None:
            self._metrics_doc = self._read_doc("observability.md")
        return self._metrics_doc

    def _read_doc(self, name: str) -> str:
        path = os.path.join(self.root, "docs", name)
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""


def _find_package_root() -> str:
    """Repo root = the directory holding the ``horovod_tpu`` package."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def _collect_suppressions(source: str, path: str):
    """Map line -> suppressions; malformed comments become HVD000."""
    sup: Dict[int, List[_Suppression]] = {}
    errors: List[Violation] = []
    from .rules import RULE_CODES  # late: rules imports this module

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return sup, errors
    # Comment-only lines: a suppression there applies to the next
    # non-blank source line (the statement it precedes).
    code_lines = set()
    comment_tokens = []
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            comment_tokens.append(tok)
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENCODING, tokenize.ENDMARKER):
            code_lines.add(tok.start[0])
    src_lines = source.splitlines()
    for tok in comment_tokens:
        text = tok.string
        if "hvdlint" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        line = tok.start[0]
        if m is None:
            errors.append(Violation(
                "HVD000", path, line, tok.start[1],
                "malformed hvdlint comment; expected "
                "'# hvdlint: disable=HVD00x -- justification'"))
            continue
        codes = tuple(c.strip().upper() for c in m.group(1).split(",")
                      if c.strip())
        why = (m.group("why") or "").strip()
        bad = [c for c in codes if c not in RULE_CODES]
        if bad:
            errors.append(Violation(
                "HVD000", path, line, tok.start[1],
                f"suppression names unknown rule(s) {', '.join(bad)}"))
            continue
        if not why:
            errors.append(Violation(
                "HVD000", path, line, tok.start[1],
                f"suppression of {', '.join(codes)} lacks a justification "
                "('-- <why this is safe here>' is required)"))
            continue
        target = line
        if line not in code_lines:
            # Comment-only line: applies to the next code line.
            nxt = line + 1
            while nxt <= len(src_lines) and nxt not in code_lines:
                nxt += 1
            target = nxt
        sup.setdefault(target, []).append(
            _Suppression(codes, why, line))
    return sup, errors


def _lint_file_context(ctx: FileContext, project: Project) -> List[Violation]:
    from .rules import ALL_RULES

    raw: List[Violation] = list(ctx.pre_errors)
    for rule in ALL_RULES:
        raw.extend(rule.check(ctx, project))
    out = []
    for v in raw:
        if v.code != "HVD000":
            sups = ctx.suppressions.get(v.line, [])
            if any(v.code in s.codes for s in sups):
                continue
        out.append(v)
    return out


def _make_context(path: str, source: str, root: str) -> FileContext:
    rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        ctx = FileContext(path=path, rel_path=rel, source=source,
                          tree=ast.Module(body=[], type_ignores=[]))
        ctx.pre_errors.append(Violation(
            "HVD000", path, e.lineno or 1, e.offset or 0,
            f"file does not parse: {e.msg}"))
        return ctx
    sup, errors = _collect_suppressions(source, path)
    return FileContext(path=path, rel_path=rel, source=source, tree=tree,
                       suppressions=sup, pre_errors=errors)


def lint_source(source: str, path: str = "<string>",
                project: Optional[Project] = None) -> List[Violation]:
    """Lint one in-memory source blob (the test-fixture entry point)."""
    project = project or Project()
    ctx = _make_context(path, source, project.root)
    return _lint_file_context(ctx, project)


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            yield p


def lint_paths(paths: Sequence[str],
               project: Optional[Project] = None) -> List[Violation]:
    project = project or Project()
    violations: List[Violation] = []
    for path in _iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            violations.append(Violation("HVD000", path, 1, 0,
                                        f"cannot read file: {e}"))
            continue
        ctx = _make_context(path, source, project.root)
        violations.extend(_lint_file_context(ctx, project))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return violations


def format_violation(v: Violation) -> str:
    return f"{v.path}:{v.line}:{v.col}: {v.code} {v.message}"


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    from .rules import ALL_RULES

    ap = argparse.ArgumentParser(
        prog="hvd-lint",
        description="codebase-invariant static analysis for horovod_tpu")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint "
                         "(default: the horovod_tpu package)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--root", default=None,
                    help="repo root override (registry/doc lookups)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.code}  {rule.title}")
        return 0

    project = Project(root=args.root)
    paths = args.paths or [os.path.join(project.root, "horovod_tpu")]
    files = list(_iter_py_files(paths))
    violations = lint_paths(files, project)
    for v in violations:
        print(format_violation(v))
    n_files = len(files)
    if violations:
        print(f"hvd-lint: {len(violations)} violation(s) in "
              f"{n_files} file(s)", file=sys.stderr)
        return 1
    print(f"hvd-lint: {n_files} file(s) clean", file=sys.stderr)
    return 0
