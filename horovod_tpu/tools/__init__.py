"""Developer tooling that ships with the package (hvd-lint lives here).

Nothing under ``tools`` is imported by the runtime — keeping the checkers
inside the package (instead of a detached scripts/ dir) means the lint
rules version together with the invariants they enforce.
"""
