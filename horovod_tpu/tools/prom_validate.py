"""Strict validator for the ``GET /metrics`` Prometheus text exposition.

``ci/metrics_smoke.sh`` scrapes a live np=2 job and feeds the text here;
the unit tests feed synthetic renders.  Checks, in the spirit of
promtool's lint but stdlib-only:

- every non-blank line parses as ``# HELP``/``# TYPE`` metadata or as a
  sample (``name{labels} value``), labels well-formed, value a float;
- a family's HELP and TYPE precede its first sample, TYPE is a known
  kind, and neither repeats;
- histograms are shape-complete per label set: ``le`` bucket bounds
  strictly ascending, cumulative counts non-decreasing, a ``+Inf``
  bucket present and equal to the matching ``_count`` sample;
- catalog coverage, both ways: every scraped family must be a
  ``CATALOG`` entry of the matching kind (a typo'd or unregistered
  series fails the scrape), and every family in ``--required`` must be
  present.  Full reverse coverage (every CATALOG entry scraped) is not a
  property any single run can have — fault counters only exist in chaos
  runs, driver gauges only on the elastic driver — so the smoke lane
  pins the subset a clean np=2 job must always serve.

Usage::

    python -m horovod_tpu.tools.prom_validate scrape.txt \\
        --required controller_cycles_total collective_latency_seconds
    ... | python -m horovod_tpu.tools.prom_validate -
"""

from __future__ import annotations

import argparse
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.metrics import CATALOG, PROM_PREFIX

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+(\S+)$")
_META_RE = re.compile(
    r"^# (HELP|TYPE) ([a-zA-Z_:][a-zA-Z0-9_:]*)(?: (.*))?$")
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="([^"\n\\]*)"$')
_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_labels(block: Optional[str],
                  errs: List[str], where: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    if not block:
        return labels
    for item in block[1:-1].split(","):
        if not item:
            continue
        m = _LABEL_RE.match(item)
        if not m:
            errs.append(f"{where}: malformed label {item!r}")
            continue
        labels[m.group(1)] = m.group(2)
    return labels


def _family_of(name: str, types: Dict[str, str]) -> str:
    """Resolve a sample name to its metric family: histogram samples
    ``X_bucket``/``X_sum``/``X_count`` belong to family ``X``."""
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            if types.get(base) == "histogram":
                return base
    return name


def validate(text: str, required: Sequence[str] = (),
             prefix: str = PROM_PREFIX) -> List[str]:
    """Return the list of violations (empty == valid)."""
    errs: List[str] = []
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    sampled: List[str] = []  # families in first-sample order
    # (family, labels-minus-le) -> [(le_float, cum_count)]
    buckets: Dict[Tuple, List[Tuple[float, float]]] = {}
    counts: Dict[Tuple, float] = {}

    for ln_no, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        where = f"line {ln_no}"
        m = _META_RE.match(line)
        if m:
            what, name, rest = m.groups()
            table = helps if what == "HELP" else types
            if name in table:
                errs.append(f"{where}: duplicate # {what} for {name}")
            table[name] = rest or ""
            if what == "TYPE" and rest not in _KINDS:
                errs.append(f"{where}: unknown TYPE {rest!r} for {name}")
            continue
        if line.startswith("#"):
            errs.append(f"{where}: unparseable comment {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errs.append(f"{where}: unparseable sample {line!r}")
            continue
        name, label_block, value_s = m.groups()
        labels = _parse_labels(label_block, errs, where)
        try:
            value = float(value_s)
        except ValueError:
            errs.append(f"{where}: non-numeric value {value_s!r}")
            continue
        family = _family_of(name, types)
        if family not in types:
            errs.append(f"{where}: sample {name} before its # TYPE")
        if family not in helps:
            errs.append(f"{where}: sample {name} before its # HELP")
        if family not in sampled:
            sampled.append(family)
        if types.get(family) == "histogram":
            key = (family, tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le")))
            if name.endswith("_bucket"):
                le_s = labels.get("le")
                if le_s is None:
                    errs.append(f"{where}: histogram bucket without le=")
                    continue
                le = float("inf") if le_s == "+Inf" else float(le_s)
                buckets.setdefault(key, []).append((le, value))
            elif name.endswith("_count"):
                counts[key] = value

    for (family, lbls), series in buckets.items():
        where = f"{family}{dict(lbls) if lbls else ''}"
        les = [le for le, _ in series]
        if les != sorted(les) or len(set(les)) != len(les):
            errs.append(f"{where}: le bounds not strictly ascending")
        vals = [v for _, v in series]
        if any(b < a for a, b in zip(vals, vals[1:])):
            errs.append(f"{where}: bucket counts not cumulative")
        if not les or les[-1] != float("inf"):
            errs.append(f"{where}: missing +Inf bucket")
        elif (family, lbls) in counts and vals[-1] != counts[(family, lbls)]:
            errs.append(f"{where}: +Inf bucket {vals[-1]} != _count "
                        f"{counts[(family, lbls)]}")
        if (family, lbls) not in counts:
            errs.append(f"{where}: histogram without a _count sample")

    for family in sampled:
        if not family.startswith(prefix):
            errs.append(f"family {family} lacks the {prefix} prefix")
            continue
        base = family[len(prefix):]
        entry = CATALOG.get(base)
        if entry is None:
            errs.append(f"family {family}: {base!r} not in CATALOG "
                        "(HVD007: every scraped series must be declared)")
        elif types.get(family) != entry[0]:
            errs.append(f"family {family}: TYPE {types.get(family)!r} != "
                        f"catalog kind {entry[0]!r}")

    present = {f[len(prefix):] for f in sampled if f.startswith(prefix)}
    for base in required:
        if base not in present:
            errs.append(f"required family {base} missing from the scrape")
    return errs


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="prom-validate",
        description="strictly validate a /metrics Prometheus text scrape "
                    "against the metric catalog")
    ap.add_argument("input", help="scrape file, or - for stdin")
    ap.add_argument("--required", nargs="*", default=[],
                    help="catalog families that must be present")
    args = ap.parse_args(argv)
    text = sys.stdin.read() if args.input == "-" \
        else open(args.input).read()
    errs = validate(text, required=args.required)
    for e in errs:
        print(f"prom-validate: {e}", file=sys.stderr)
    n_fam = len({ln.split("{")[0].split()[0] for ln in text.splitlines()
                 if ln and not ln.startswith("#")})
    if errs:
        print(f"prom-validate: FAILED ({len(errs)} violation(s) across "
              f"{n_fam} series name(s))", file=sys.stderr)
        return 1
    print(f"prom-validate: OK ({n_fam} series name(s), "
          f"{len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
