"""Ring attention: blockwise sequence/context parallelism.

Not present in the reference (SURVEY §5.7 — it scales batch, never
sequence); required here because long-context is first-class for the TPU
build.  Design: Q/K/V are sharded along the sequence axis over the ``seq``
mesh axis.  Each device keeps its Q shard resident and streams K/V shards
around the ring with ``ppermute`` (ICI-neighbor CollectivePermute — the
cheapest TPU collective), accumulating attention with the numerically-stable
online-softmax (flash) recurrence.  Communication overlaps compute: XLA
schedules the ppermute of block t+1 concurrently with the matmuls of block
t because there is no data dependence between them.

Memory per device is O(seq/n) for activations — full-sequence attention
never materializes.  Causal masking is applied per block from global
positions; blocks entirely in the future contribute nothing (their masked
exp() terms are zero) but are still computed — a pallas kernel that skips
them is the profile-guided next step (`/opt/skills/guides/pallas_guide.md`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .collectives import axis_size
from .mesh import AXIS_SEQ


def _online_block(carry, kv_block, q, q_pos, kv_pos_fn, scale, causal):
    """One flash-accumulation step against the K/V block currently held.

    carry: (o, m, l, step) with o [b,h,sq,d], m/l [b,h,sq,1].
    kv_block: (k, v) each [b, skv, h, d].
    """
    o, m, l, step = carry
    k, v = kv_block
    # [b, h, sq, skv]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        kv_pos = kv_pos_fn(step)                       # [skv]
        mask = q_pos[:, None] >= kv_pos[None, :]       # [sq, skv]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # Guard -inf - -inf = nan for fully-masked rows / first block.
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_new))
    p = jnp.exp(s - m_new)
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    o = o * alpha + pv
    return (o, m_new, l, step + 1)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = AXIS_SEQ, causal: bool = False,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Ring self-attention over sequence shards.

    Must run inside ``shard_map`` with ``axis_name`` bound; q/k/v are the
    local shards shaped ``[batch, seq_shard, heads, head_dim]`` (sequence
    split contiguously across the axis, shard i owning positions
    ``[i*seq_shard, (i+1)*seq_shard)``).  Returns the local output shard in
    q's dtype.
    """
    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    skv = k.shape[1]
    scale = sm_scale if sm_scale is not None else d ** -0.5

    q32 = q.astype(jnp.float32)
    q_pos = my_idx * sq + jnp.arange(sq)

    def kv_pos_fn(step):
        # After `step` +1-shifts, this device holds the block that
        # originated on rank (my_idx - step) mod n.
        owner = (my_idx - step) % n
        return owner * skv + jnp.arange(skv)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def scan_body(carry, _):
        o_m_l_step, (k_cur, v_cur) = carry
        new_acc = _online_block(o_m_l_step, (k_cur, v_cur), q32, q_pos,
                                kv_pos_fn, scale, causal)
        k_nxt = lax.ppermute(k_cur, axis_name, perm=perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm=perm)
        return (new_acc, (k_nxt, v_nxt)), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    init = ((o0, m0, l0, jnp.zeros((), jnp.int32)), (k, v))
    (final_acc, _), _ = lax.scan(scan_body, init, None, length=n)
    o, _, l, _ = final_acc
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
    out = (o / l).astype(q.dtype)
    return jnp.einsum("bhqd->bqhd", out)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = AXIS_SEQ,
                           causal: bool = False,
                           sm_scale: Optional[float] = None):
    """Convenience wrapper: shard_map ``ring_attention`` over ``mesh`` with
    batch on 'data' and sequence on ``axis_name``."""
    from jax.sharding import PartitionSpec as P

    from .sharding import shard_map_fn

    spec = P("data", axis_name, None, None)
    fn = shard_map_fn(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, sm_scale=sm_scale),
        mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
