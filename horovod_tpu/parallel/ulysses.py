"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The reference ships the raw uneven alltoall primitive
(`operations.cc:1081-1142`) that SURVEY §5.7 identifies as "the
communication pattern Ulysses-style SP would need" — this module is that
pattern realized on TPU.  Two all_to_alls per attention call:

1. before attention: reshard from sequence-split/head-full to
   sequence-full/head-split (each device then holds ``heads/n`` full-length
   heads and runs ordinary attention on them);
2. after attention: reshard back.

Compared with ring attention: Ulysses moves activations twice via
all-to-all (bandwidth ~2·B·S·H·D/n per device, latency-friendly on ICI's
all-to-all-capable torus) but runs plain unmodified attention in between,
so it composes with any attention kernel (flash, pallas) untouched.  Ring
keeps K/V streaming with n ppermutes and never materializes the full
sequence — better above ~128k tokens or when heads < devices.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .collectives import axis_size
from .mesh import AXIS_SEQ


def _default_attention(q, k, v, causal: bool, sm_scale: Optional[float]):
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(q.dtype)


def seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[b, s/n, h, d] sequence-sharded → [b, s, h/n, d] head-sharded."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[b, s, h/n, d] head-sharded → [b, s/n, h, d] sequence-sharded."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = AXIS_SEQ, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      attention_fn: Optional[Callable] = None) -> jax.Array:
    """All-to-all sequence-parallel attention.

    Inside ``shard_map``; local shards ``[batch, seq_shard, heads,
    head_dim]`` with ``heads % axis_size == 0``.  ``attention_fn(q, k, v)``
    may be any full-sequence attention (e.g. a pallas flash kernel); the
    default is plain softmax attention.
    """
    n = axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the seq axis "
            f"size ({n}); use ring_attention otherwise")
    qh, kh, vh = (seq_to_heads(t, axis_name) for t in (q, k, v))
    if attention_fn is None:
        out = _default_attention(qh, kh, vh, causal, sm_scale)
    else:
        out = attention_fn(qh, kh, vh)
    return heads_to_seq(out, axis_name)
