"""Pipeline parallelism over a ``pipe`` mesh axis.

Not in the reference (SURVEY §2.9: PP absent) — included because on TPU
pipelining is mesh machinery, not a separate runtime: stages are shards of
a stacked parameter tree over the ``pipe`` axis, activations move to the
next stage with ``ppermute`` (neighbor CollectivePermute on ICI), and the
schedule is a ``lax.scan`` — compiler-friendly, no host control flow.

Schedule: GPipe-style fill-drain.  With M microbatches and N stages the
scan runs M+N-1 ticks; stage s computes microbatch t-s at tick t.  Bubble
fraction (N-1)/(M+N-1) — callers pick M >= 4N to amortize.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from .collectives import axis_size
from .mesh import AXIS_PIPE


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, microbatches: jax.Array,
                   axis_name: str = AXIS_PIPE) -> jax.Array:
    """Run microbatches through the pipeline; inside ``shard_map``.

    - ``stage_fn(params, x) -> y``: one stage's computation; every stage
      must map the same activation shape to itself (classic equal-width
      pipeline).
    - ``stage_params``: this stage's parameter pytree (callers shard a
      stacked tree over ``pipe`` and squeeze the leading axis).
    - ``microbatches``: ``[M, micro_batch, ...]`` — the real inputs on
      stage 0 (other stages' values are ignored).

    Returns ``[M, micro_batch, ...]`` outputs, identical on every stage
    (the last stage's results are broadcast back so downstream loss code
    is stage-agnostic).
    """
    n = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (clamped during drain); others take
        # the activation handed to them last tick.
        mb = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb, incoming)
        y = stage_fn(stage_params, x)
        # Last stage banks microbatch t-(n-1) once the pipe is full.
        out_t = t - (n - 1)
        outputs = lax.cond(
            out_t >= 0,
            lambda o: lax.dynamic_update_index_in_dim(o, y, jnp.clip(out_t, 0, m - 1), 0),
            lambda o: o, outputs)
        nxt = lax.ppermute(y, axis_name, perm=perm)
        return (nxt, outputs), None

    incoming0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (incoming0, outputs0),
                               jnp.arange(m + n - 1))
    # outputs is only real on the last stage; broadcast it to all stages
    # (masked psum — lowers to an efficient one-to-all on ICI).
    masked = jnp.where(stage == n - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def stack_stage_params(params_per_stage) -> Any:
    """Stack a list of per-stage pytrees into one tree with a leading
    ``pipe`` axis, ready to shard with ``P('pipe', ...)``."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_per_stage)
