"""jit-path collectives: XLA equivalents of the reference op chain.

The reference dispatches each fused Response through an ordered chain of
backend ops (`operation_manager.cc:41-49`; NCCL/MPI/Gloo implementations in
SURVEY §2.3).  Inside ``jit``/``shard_map`` those backends are replaced by a
single "backend": XLA emits the collective HLO and the TPU runtime executes
it over ICI/DCN.  These wrappers exist so framework code names *operations*
(allreduce/allgather/...) rather than lax primitives, mirroring the
reference API surface (`hvd.allreduce` etc.) on the compiled path.

All functions must be called inside ``shard_map`` (or a jit with manual
axes) where ``axis_name`` is bound.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisNames = Union[str, Sequence[str]]


def allreduce(x: jax.Array, axis_name: AxisNames, op: str = "sum",
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None) -> jax.Array:
    """Sum/average/min/max allreduce.

    Average is postscale-by-1/size exactly like the reference
    (`operations.cc:953-956`); pre/postscale mirror the wire fields
    (`message.h:48-113`).
    """
    if prescale_factor is not None:
        x = x * prescale_factor
    if op in ("sum", "average", "mean"):
        out = lax.psum(x, axis_name)
        if op in ("average", "mean"):
            out = out / axis_size(axis_name)
    elif op == "min":
        out = lax.pmin(x, axis_name)
    elif op == "max":
        out = lax.pmax(x, axis_name)
    else:
        raise ValueError(f"unsupported reduce op {op!r}")
    if postscale_factor is not None:
        out = out * postscale_factor
    return out


def allgather(x: jax.Array, axis_name: AxisNames, axis: int = 0,
              tiled: bool = True) -> jax.Array:
    """Concatenate shards along ``axis`` (reference `MPIAllgather`,
    `mpi_operations.cc:97`; variable first-dim gathers are the eager path's
    job — compiled shapes are static)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: AxisNames, axis: int = 0) -> jax.Array:
    """psum then keep this rank's shard — the building block of the
    reference's hierarchical allreduce (`nccl_operations.cc:194-405`,
    ncclReduceScatter leg)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x: jax.Array, axis_name: AxisNames, root: int = 0) -> jax.Array:
    """Every member gets root's value (reference `MPIBroadcast`,
    `mpi_operations.cc:358`).  Implemented as masked psum — a one-hot
    select then sum, which XLA lowers to an efficient broadcast."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def alltoall(x: jax.Array, axis_name: AxisNames,
             split_axis: int = 0, concat_axis: int = 0) -> jax.Array:
    """Even alltoall (reference `MPIAlltoall`, `mpi_operations.cc:393`).
    Uneven splits belong to the eager path; XLA shapes are static."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute_ring(x: jax.Array, axis_name: AxisNames, shift: int = 1) -> jax.Array:
    """Rotate values around the axis ring — the primitive under ring
    attention and pipeline transfers.  Maps to ICI-neighbor
    CollectivePermute, the cheapest possible TPU collective."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def barrier_value(axis_name: AxisNames) -> jax.Array:
    """A data-dependent barrier: psum of 1 — any rank arriving late delays
    everyone (eager-path barrier lives in `frameworks.jax.ops.barrier`)."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name)


def _one_axis_size(a: str) -> int:
    # lax.axis_size only exists in newer jax; psum of the literal 1 folds
    # to the static axis size at trace time on every version.
    if hasattr(lax, "axis_size"):
        return lax.axis_size(a)
    return lax.psum(1, a)


def axis_size(axis_name: AxisNames) -> int:
    if isinstance(axis_name, (tuple, list)):
        size = 1
        for a in axis_name:
            size *= _one_axis_size(a)
        return size
    return _one_axis_size(axis_name)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def hierarchical_allreduce(x: jax.Array, local_axis: str,
                           cross_axis: str) -> jax.Array:
    """Explicit 2-level allreduce: reduce-scatter on the fast axis, allreduce
    on the slow axis, allgather back on the fast axis — the
    `NCCLHierarchicalAllreduce` schedule (`nccl_operations.cc:194-405`)
    written in XLA collectives.  On TPU XLA usually derives this on its own
    for a (dcn, ici) mesh; this exists for explicit control and for parity
    with `HOROVOD_HIERARCHICAL_ALLREDUCE` (`operations.cc:486-495`).
    """
    shard = lax.psum_scatter(x.reshape(-1), local_axis, scatter_dimension=0,
                             tiled=True)
    shard = lax.psum(shard, cross_axis)
    full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
    return full.reshape(x.shape)
