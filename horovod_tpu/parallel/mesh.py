"""Device-mesh construction.

The reference keeps three communicators — GLOBAL, LOCAL (intra-node), CROSS
(one rank per node) — split at ``mpi_context.cc:147-156`` and uses LOCAL for
the fast fabric and CROSS for the slow one (`nccl_operations.cc:194-405`,
the hierarchical allreduce).  On TPU the same idea is expressed as mesh
*axes*: inner axes are laid out over ICI (fast), the outermost axis over DCN
(slow, across pod slices).  XLA then picks hierarchical collective
algorithms automatically — the NCCLHierarchical pattern is what the XLA
runtime already does for multi-slice meshes (SURVEY §5.8).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Canonical axis names, outermost (slowest fabric) first.
AXIS_DATA = "data"      # data parallelism (the reference's one strategy)
AXIS_PIPE = "pipe"      # pipeline stages
AXIS_EXPERT = "expert"  # expert parallelism (MoE)
AXIS_SEQ = "seq"        # sequence/context parallelism (ring / Ulysses)
AXIS_MODEL = "model"    # tensor (operator) parallelism

# Mesh-axis order: data outermost so DP rides DCN across slices while
# model/seq/pipe axes stay inside a slice on ICI.
_AXIS_ORDER = (AXIS_DATA, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL)


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism degrees. ``-1`` on ``data`` means "use whatever
    devices remain" (like the reference sizing DP to world size)."""

    data: int = -1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1
    # Axes that should be laid out contiguously on the fastest fabric first.
    # Default: rightmost axes innermost (model closest on ICI).
    axis_order: Tuple[str, ...] = field(default=_AXIS_ORDER)

    def degrees(self) -> Dict[str, int]:
        return {
            AXIS_DATA: self.data,
            AXIS_PIPE: self.pipe,
            AXIS_EXPERT: self.expert,
            AXIS_SEQ: self.seq,
            AXIS_MODEL: self.model,
        }


def mesh_shape_for(spec: MeshSpec, n_devices: int) -> Tuple[Tuple[str, int], ...]:
    """Resolve a MeshSpec against a device count: fills in ``data=-1`` and
    validates divisibility (the analog of the launcher's slot math,
    reference ``common/util/hosts.py:get_host_assignments``)."""
    degrees = spec.degrees()
    fixed = 1
    for name, d in degrees.items():
        if d != -1:
            if d < 1:
                raise ValueError(f"axis {name!r} must be >=1 or -1, got {d}")
            fixed *= d
    free = [name for name, d in degrees.items() if d == -1]
    if len(free) > 1:
        raise ValueError(f"at most one axis may be -1, got {free}")
    if free:
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {fixed}")
        degrees[free[0]] = n_devices // fixed
    elif fixed != n_devices:
        raise ValueError(
            f"mesh spec wants {fixed} devices but {n_devices} are available")
    return tuple((name, degrees[name]) for name in spec.axis_order)


def build_mesh(spec: Optional[MeshSpec] = None,
               devices: Optional[Sequence] = None,
               contiguous_submeshes: bool = True):
    """Build a :class:`jax.sharding.Mesh` from a spec.

    Device order: ``jax.devices()`` enumerates chips so that nearby indices
    are nearby on ICI (same host first).  Reshaping that flat order into the
    axis grid with the *innermost* axes varying fastest puts model/seq
    collectives on neighboring chips — the LOCAL-communicator role — while
    the outermost (data) axis spans hosts/slices — the CROSS role.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    shape = mesh_shape_for(spec or MeshSpec(), len(devices))
    names = tuple(name for name, _ in shape)
    dims = tuple(d for _, d in shape)
    grid = np.asarray(devices, dtype=object).reshape(dims)
    return jax.sharding.Mesh(grid, names)


def data_parallel_mesh(devices: Optional[Sequence] = None):
    """Pure-DP mesh over all devices — the reference's world communicator."""
    return build_mesh(MeshSpec(data=-1), devices=devices)


def local_mesh_axes(mesh) -> List[str]:
    """Axes of size > 1 (useful for building full psum axis tuples)."""
    return [name for name, size in zip(mesh.axis_names, mesh.devices.shape)
            if size > 1]


def validate_power_of_two(n: int, what: str = "ranks") -> None:
    """Adasum VHDD requires power-of-two participant counts
    (reference `adasum.h:194-450`)."""
    if n & (n - 1):
        raise ValueError(
            f"{what} must be a power of two for Adasum VHDD, got {n} "
            f"(nearest: {2 ** int(math.log2(n))})")
