"""SPMD gradient synchronization — DistributedOptimizer's compiled-path core.

The reference's `DistributedOptimizer` (torch `optimizer.py:32`, TF
`tensorflow/__init__.py:465`) allreduces every gradient tensor through the
background runtime.  Inside jit the same contract is one line per leaf:
``lax.pmean`` over the data axes.  Fusion, bucketing and overlap — the
things `FuseResponses` (`controller.cc:859-998`) and WFBP hooks buy on GPU —
are XLA's job here (its allreduce combiner merges small collectives and
schedules them over ICI concurrently with the backward pass).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
from jax import lax

AxisNames = Union[str, Sequence[str]]


def allreduce_gradients(grads: Any, axis_name: AxisNames = "data",
                        op: str = "average",
                        prescale_factor: Optional[float] = None,
                        postscale_factor: Optional[float] = None) -> Any:
    """Allreduce a gradient pytree across data-parallel replicas.

    ``op='average'`` matches the reference default (`Average`,
    postscale-by-1/size, `operations.cc:953-956`).
    """
    from .collectives import allreduce

    def _sync(g):
        return allreduce(g, axis_name, op=op,
                         prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor)

    return jax.tree_util.tree_map(_sync, grads)


def cross_replica_mean(tree: Any, axis_name: AxisNames = "data") -> Any:
    """pmean over a pytree (metrics averaging — the role of Keras
    `MetricAverageCallback`, reference `_keras/callbacks.py:48`)."""
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)
