"""Sharding helpers: NamedSharding rules + shard_map plumbing.

The reference has no sharding notion — its unit is "a named tensor,
replicated everywhere, allreduced on demand".  On TPU the idiomatic
equivalent is: put arrays in the right :class:`NamedSharding` and let
XLA insert collectives.  These helpers centralize that.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Replicate a pytree across the whole mesh — the SPMD analog of
    `broadcast_parameters` (reference `torch/functions.py:30`): afterwards
    every device holds identical values."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def batch_sharding(mesh: Mesh, batch_axes: Union[str, Sequence[str]] = "data",
                   ndim: int = 2) -> NamedSharding:
    """Shard dim 0 (batch) over the data axis, replicate the rest."""
    spec = [batch_axes] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def shard_batch(mesh: Mesh, batch: Any,
                batch_axes: Union[str, Sequence[str]] = "data") -> Any:
    """Place host batch arrays so dim 0 is split across the data axis —
    what the per-rank data loader achieves in the reference by each rank
    reading its own shard."""
    def _put(x):
        spec = [batch_axes] + [None] * (x.ndim - 1)
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(_put, batch)


def shard_map_fn(fn, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Uniform wrapper over jax's shard_map (API moved across jax versions)."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    except TypeError:  # older kwarg name
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
