"""SPMD mesh parallelism — the TPU fast path.

The reference (huyutuo/horovod 0.20.3) is a data-parallel allreduce engine
whose data plane is NCCL/MPI (`horovod/common/ops/`, SURVEY §2.3).  On TPU
the XLA runtime plays NCCL's role: collectives are compiled into the program
and ride ICI within a slice / DCN across slices.  This package is therefore
*the* performance path of horovod_tpu:

- :mod:`.mesh` — device-mesh construction mirroring the reference's
  GLOBAL/LOCAL/CROSS communicator split (`mpi_context.cc:147-156`) as mesh
  axes;
- :mod:`.collectives` — jit-path wrappers over ``lax.psum`` /
  ``all_gather`` / ``psum_scatter`` / ``all_to_all`` / ``ppermute``, the
  XLA equivalents of the reference's MPI/NCCL op chain;
- :mod:`.grad_sync` — the SPMD analog of ``DistributedOptimizer``'s
  allreduce-on-gradients;
- :mod:`.ring_attention` — ring (blockwise) attention sequence parallelism;
- :mod:`.ulysses` — all-to-all (DeepSpeed-Ulysses-style) sequence
  parallelism built on the alltoall primitive the reference exposes raw
  (`operations.cc:1081-1142`);
- :mod:`.pipeline` — pipeline parallelism over a ``pipe`` mesh axis;
- :mod:`.moe` — expert parallelism (gating + all_to_all dispatch/combine).

Beyond-parity scope (TP/PP/SP/EP) is deliberate: on TPU these fall out of
the same mesh machinery that gives data parallelism, and the build target
treats long-context + distributed as first-class.
"""

from .mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQ,
    MeshSpec,
    build_mesh,
    data_parallel_mesh,
    local_mesh_axes,
    mesh_shape_for,
)
from .collectives import (  # noqa: F401
    allgather,
    allreduce,
    alltoall,
    barrier_value,
    broadcast,
    ppermute_ring,
    reduce_scatter,
)
from .grad_sync import allreduce_gradients, cross_replica_mean  # noqa: F401
from .sharding import (  # noqa: F401
    batch_sharding,
    named_sharding,
    replicate,
    shard_batch,
    shard_map_fn,
)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
from .moe import moe_dispatch_combine  # noqa: F401
