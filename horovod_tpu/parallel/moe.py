"""Expert parallelism: gating + all_to_all dispatch/combine.

The reference exposes only the raw alltoall primitive
(`operations.cc:1081-1142`; SURVEY §2.9 notes it as the building block
"users could use for MoE-style exchange, but no EP strategy ships").  Here
the strategy ships: Switch-style top-1 routing with capacity, tokens
exchanged over the ``expert`` mesh axis with two tiled ``all_to_all``s
(dispatch and return), one expert per axis member.

Capacity drops are the standard trade: tokens over an expert's capacity
pass through unchanged (residual connection keeps them sane), keeping all
shapes static for XLA.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .collectives import axis_size
from .mesh import AXIS_EXPERT


def moe_dispatch_combine(x: jax.Array, gate_logits: jax.Array,
                         expert_fn: Callable[[jax.Array], jax.Array],
                         axis_name: str = AXIS_EXPERT,
                         capacity_factor: float = 1.25,
                         capacity: Optional[int] = None) -> jax.Array:
    """Top-1 MoE layer body; inside ``shard_map`` over ``axis_name``.

    - ``x``: local tokens ``[t, d]``;
    - ``gate_logits``: ``[t, n_experts]`` with ``n_experts == axis_size``;
    - ``expert_fn``: this device's expert, ``[c, d] -> [c, d]``.

    Returns ``[t, d]``: gate-weighted expert outputs (dropped tokens get 0,
    callers add the residual).
    """
    n = axis_size(axis_name)
    t, d = x.shape
    if capacity is None:
        capacity = max(1, int(capacity_factor * t / n))
    c = capacity

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)  # [t, n]
    expert_idx = jnp.argmax(probs, axis=-1)                           # [t]
    gate = jnp.max(probs, axis=-1)                                    # [t]
    onehot = jax.nn.one_hot(expert_idx, n, dtype=jnp.float32)         # [t, n]
    # Position of each token within its expert's queue; >=c means dropped.
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot                # [t, n]
    keep = (pos < c) * onehot
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), c, dtype=jnp.float32)
    dispatch = keep[..., None] * pos_oh                               # [t, n, c]

    # [n, c, d]: slot (e, j) holds the j-th local token routed to expert e.
    send = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # Exchange: device e receives every peer's slice for expert e.
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                                 # [n, c, d]
    out = expert_fn(recv.reshape(n * c, d).astype(x.dtype))
    out = out.reshape(n, c, d).astype(jnp.float32)
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                                 # [n, c, d]
    combine = dispatch * gate[:, None, None]
    return jnp.einsum("tec,ecd->td", combine, back).astype(x.dtype)


def load_balancing_loss(gate_logits: jax.Array, axis_name: str = AXIS_EXPERT) -> jax.Array:
    """Switch-Transformer auxiliary loss: n * sum(fraction_tokens * mean_prob)."""
    n = gate_logits.shape[-1]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), n), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return n * jnp.sum(frac * mean_prob)
