"""The elastic driver: discovery loop, membership epochs, worker lifecycle.

Reference: ``runner/elastic/driver.py:1-309`` — a background thread polls
discovery every second (``DISCOVER_HOSTS_FREQUENCY_SECS``), host-set diffs
trigger worker notification + a new rendezvous epoch, failed workers
blacklist their host after repeated failures, and rank assignments stay
stable for surviving hosts (``_update_host_assignments``).

Membership protocol (epoch-based, coordinator-authoritative like the rest
of this framework):

1. every epoch the driver publishes a slot table (rank/local/cross + epoch)
   under ``rank_and_size/{hostname}:{local_rank}``;
2. workers (re)initialize from their identity's entry; removed identities
   see ``rank: -1`` and exit;
3. on change: epoch += 1, publish, notify live workers (they raise
   ``HostsUpdatedInterrupt`` at the next commit), spawn processes for new
   identities;
4. worker process death ⇒ failure recorded; a host whose workers keep
   dying is blacklisted; remaining workers hit ``HorovodInternalError``
   (broken TCP mesh) and re-rendezvous into the next epoch.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

from ..common.logging_util import get_logger
from ..runner.hosts import SlotInfo, get_host_assignments
from ..runner.rendezvous import RendezvousServer
from .discovery import HostManager
from .registration import WorkerStateRegistry
from .worker import WORKERS_SCOPE, WorkerNotificationClient

log = get_logger("horovod_tpu.elastic.driver")

DISCOVER_HOSTS_FREQUENCY_SECS = 1.0
ELASTIC_TIMEOUT_SECS = 600.0


class ElasticDriver:
    def __init__(self, rendezvous: RendezvousServer, host_manager: HostManager,
                 min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 timeout: float = ELASTIC_TIMEOUT_SECS):
        self.rendezvous = rendezvous
        self.hosts = host_manager
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.timeout = timeout
        self.epoch = 0
        self.resets = 0
        self._slots: List[SlotInfo] = []
        self._known_identities: Dict[str, SlotInfo] = {}
        self._create_worker: Optional[Callable[[SlotInfo, int], None]] = None
        self._registry = WorkerStateRegistry(0)
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._wakeup = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None
        self._await_ack: Optional[bool] = None  # added_only flavor, or None
        self._removed_identities: set = set()

    # ------------------------------------------------------------------

    def wait_for_available_slots(self, min_np: Optional[int] = None) -> None:
        """Block until discovery provides enough slots
        (reference ``driver.py:145``)."""
        need = min_np or self.min_np
        deadline = time.monotonic() + self.timeout
        while True:
            self.hosts.update_available_hosts()
            if self.hosts.total_slots() >= need:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {need} slots "
                    f"(have {self.hosts.total_slots()})")
            time.sleep(DISCOVER_HOSTS_FREQUENCY_SECS)

    def start(self, create_worker: Callable[[SlotInfo, int], None]) -> None:
        """Publish epoch 0 assignments, spawn workers, start discovery."""
        self._create_worker = create_worker
        self.wait_for_available_slots()
        self._rendezvous_epoch(initial=True)
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, name="hvd-elastic-discovery",
            daemon=True)
        self._discovery_thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        self._wakeup.set()

    # ------------------------------------------------------------------

    def _assignments(self) -> List[SlotInfo]:
        hosts = self.hosts.current_hosts
        total = sum(h.slots for h in hosts)
        np_ = min(total, self.max_np) if self.max_np else total
        return get_host_assignments(hosts, min(self.min_np, np_), np_)

    def _rendezvous_epoch(self, initial: bool = False) -> None:
        with self._lock:
            if not initial:
                self.epoch += 1
                self.resets += 1
            new_slots = self._assignments()
            self._slots = new_slots
            self._registry.reset(len(new_slots))

            # Publish the new table; removed identities get rank -1 so a
            # surviving process on a removed host exits cleanly.
            table = {}
            for s in new_slots:
                table[f"{s.hostname}:{s.local_rank}"] = {
                    "hostname": s.hostname, "rank": s.rank,
                    "local_rank": s.local_rank, "cross_rank": s.cross_rank,
                    "size": s.size, "local_size": s.local_size,
                    "cross_size": s.cross_size, "epoch": self.epoch,
                }
            for identity in self._known_identities:
                if identity not in table:
                    host, lr = identity.rsplit(":", 1)
                    table[identity] = {
                        "hostname": host, "rank": -1, "local_rank": int(lr),
                        "cross_rank": -1, "size": 0, "local_size": 0,
                        "cross_size": 0, "epoch": self.epoch,
                    }
            for identity, slot in table.items():
                self.rendezvous.set("rank_and_size", identity,
                                    json.dumps(slot).encode())

            # Spawn processes for identities that have none yet.
            for s in new_slots:
                identity = f"{s.hostname}:{s.local_rank}"
                if identity not in self._known_identities:
                    log.info("spawning worker %s (epoch %d, rank %d)",
                             identity, self.epoch, s.rank)
                    self._create_worker(s, self.epoch)
                self._known_identities[identity] = s
            current = {f"{s.hostname}:{s.local_rank}" for s in new_slots}
            self._removed_identities = {
                i for i in self._known_identities if i not in current}
            for identity in self._removed_identities:
                self._known_identities.pop(identity)

    def _notify_workers(self, added_only: bool) -> None:
        addresses = []
        missing = []
        # Removed identities are notified too: their table entry says
        # rank −1, and the ping is what makes them exit promptly instead
        # of waiting to hit a dead socket.
        identities = {f"{s.hostname}:{s.local_rank}" for s in self._slots}
        identities.update(self._removed_identities)
        for identity in sorted(identities):
            raw = self.rendezvous.get(WORKERS_SCOPE, identity)
            if raw:
                addresses.append(raw.decode())
            else:
                missing.append(identity)
        log.info("notifying %d workers of host change (unregistered: %s)",
                 len(addresses), missing or "none")
        WorkerNotificationClient(addresses).notify_hosts_updated(added_only)

    def _discovery_loop(self) -> None:
        while not self._shutdown.is_set():
            self._wakeup.wait(DISCOVER_HOSTS_FREQUENCY_SECS)
            self._wakeup.clear()
            if self._shutdown.is_set():
                return
            self._renotify_unacked()
            try:
                changed, removal = self.hosts.update_available_hosts()
            except Exception as e:  # noqa: BLE001 — discovery script hiccups
                log.warning("host discovery failed: %s", e)
                continue
            if not changed:
                continue
            if self.reset_limit is not None and \
                    self.resets >= self.reset_limit:
                log.error("reset limit %d reached; ignoring host change",
                          self.reset_limit)
                continue
            if self.hosts.total_slots() < self.min_np:
                log.warning("host change leaves fewer than min_np slots; "
                            "waiting for capacity")
                continue
            log.info("host set changed (removal=%s); advancing epoch",
                     removal)
            self._rendezvous_epoch()
            self._await_ack = not removal  # remember flavor for re-notify
            self._notify_workers(added_only=not removal)

    # ------------------------------------------------------------------

    def _renotify_unacked(self) -> None:
        """Notification is racy against worker startup (a worker may
        register its endpoint just after a change fired).  Until every
        current identity acks the epoch, keep pinging each tick."""
        if self._await_ack is None or self.epoch == 0:
            return
        unacked = []
        for s in self._slots:
            identity = f"{s.hostname}:{s.local_rank}"
            raw = self.rendezvous.get("epoch_ack", identity)
            if raw is None or int(raw.decode()) < self.epoch:
                unacked.append(identity)
        if not unacked:
            self._await_ack = None
            return
        self._notify_workers(added_only=self._await_ack)

    def record_worker_exit(self, slot: SlotInfo, exit_code: int) -> None:
        """Called by the launcher's process monitor (reference
        ``_handle_worker_exit``, ``driver.py:292-308``)."""
        if exit_code == 0:
            self._registry.record_success(slot.rank)
            return
        self._registry.record_failure(slot.rank)
        self.hosts.blacklist(slot.hostname)
        self._known_identities.pop(f"{slot.hostname}:{slot.local_rank}", None)
        self._wakeup.set()

    @property
    def current_slots(self) -> List[SlotInfo]:
        with self._lock:
            return list(self._slots)
