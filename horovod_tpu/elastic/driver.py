"""The elastic driver: discovery loop, membership epochs, worker lifecycle.

Reference: ``runner/elastic/driver.py:1-309`` — a background thread polls
discovery every second (``DISCOVER_HOSTS_FREQUENCY_SECS``), host-set diffs
trigger worker notification + a new rendezvous epoch, failed workers
blacklist their host after repeated failures, and rank assignments stay
stable for surviving hosts (``_update_host_assignments``).

Membership protocol (epoch-based, coordinator-authoritative like the rest
of this framework):

1. every epoch the driver publishes a slot table (rank/local/cross + epoch)
   under ``rank_and_size/{hostname}:{local_rank}``;
2. workers (re)initialize from their identity's entry; removed identities
   see ``rank: -1`` and exit;
3. on change: epoch += 1, publish, notify live workers with the NEW epoch
   number (they raise ``HostsUpdatedInterrupt`` at the next commit; pings
   carrying an epoch ≤ the worker's own are ignored as stale — the race
   that livelocked round 1); spawn processes for new identities, which the
   driver marks as implicitly acked (they are born at the new epoch);
4. worker process death ⇒ failure recorded; crash exits blacklist the host
   after ``crash_failure_limit`` strikes, transient exits (the worker gave
   up re-initializing, exit code ``TRANSIENT_EXIT_CODE``) after
   ``transient_failure_limit``; identities whose process died but whose
   host is still healthy are respawned at the next epoch (reference
   ``registration.py:75-135`` resume semantics).
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..common import env as env_mod
from ..common import faults
from ..common.logging_util import get_logger
from ..core import flight_recorder
from ..core import metrics
from ..core import timeline as timeline_mod
from ..runner.hosts import SlotInfo, get_host_assignments
from ..runner.rendezvous import ExternalRendezvous, RendezvousServer
from ..transport.store import LEASE_SCOPE
from .constants import (
    DEFAULT_CRASH_FAILURE_LIMIT,
    DEFAULT_TRANSIENT_FAILURE_LIMIT,
    DISCOVER_HOSTS_FREQUENCY_SECS,
    ELASTIC_TIMEOUT_SECS,
    TRANSIENT_EXIT_CODE,
)
from . import rendezvous_client
from .discovery import HostManager
from .registration import WorkerStateRegistry
from .worker import WORKERS_SCOPE, WorkerNotificationClient

log = get_logger("horovod_tpu.elastic.driver")

#: Scope the driver persists its own durable state in (currently just the
#: epoch) so a restarted driver can re-adopt instead of resetting to 0.
DRIVER_SCOPE = "driver"


class ElasticDriver:
    #: Store-outage shapes: a dead/restarting rendezvous server surfaces
    #: from the HTTP client as URLError/ConnectionError — both OSError.
    #: The in-process server never raises, so partitioned mode only ever
    #: engages against an external (HOROVOD_RENDEZVOUS_EXTERNAL) store.
    _STORE_ERRORS = OSError

    def __init__(self, rendezvous: RendezvousServer, host_manager: HostManager,
                 min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 timeout: float = ELASTIC_TIMEOUT_SECS,
                 crash_failure_limit: Optional[int] = None,
                 transient_failure_limit: Optional[int] = None,
                 lease_timeout: Optional[float] = None):
        self.rendezvous = rendezvous
        self.hosts = host_manager
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.timeout = timeout
        self.epoch = 0
        self.resets = 0
        self.stopped_error: Optional[str] = None
        self.crash_failure_limit = crash_failure_limit if crash_failure_limit \
            is not None else env_mod.get_int(
                env_mod.HOROVOD_ELASTIC_CRASH_FAILURE_LIMIT,
                DEFAULT_CRASH_FAILURE_LIMIT)
        self.transient_failure_limit = transient_failure_limit \
            if transient_failure_limit is not None else env_mod.get_int(
                env_mod.HOROVOD_ELASTIC_TRANSIENT_FAILURE_LIMIT,
                DEFAULT_TRANSIENT_FAILURE_LIMIT)
        self._crash_failures: Dict[str, int] = defaultdict(int)
        self._transient_failures: Dict[str, int] = defaultdict(int)
        self._slots: List[SlotInfo] = []
        self._known_identities: Dict[str, SlotInfo] = {}
        self._create_worker: Optional[Callable[[SlotInfo, int], None]] = None
        self._registry = WorkerStateRegistry(0)
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._wakeup = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None
        self._await_ack: Optional[bool] = None  # added_only flavor, or None
        self._removed_identities: set = set()
        self._exited_identities: set = set()
        # (reporter identity, epoch, rank) demotions already counted: a
        # current-epoch report stays readable in the store until the
        # epoch advances (e.g. across waiting-for-capacity ticks), and
        # re-reading it must not re-count metrics or re-log the shed.
        self._demotion_seen: Set[Tuple[str, int, int]] = set()
        # Once any worker succeeds the job is winding down: membership no
        # longer changes, so a finished (dead-but-successful) identity can
        # never be handed a rank in a fresh epoch (reference
        # registration.py:139-143 stops the driver on first SUCCESS).
        self._success = False
        # -- lease-based liveness (docs/control_plane.md) --------------
        self.lease_timeout = lease_timeout if lease_timeout is not None \
            else env_mod.get_float(env_mod.HOROVOD_LEASE_TIMEOUT_SECS,
                                   env_mod.DEFAULT_LEASE_TIMEOUT_SECS)
        # identity -> (last lease value seen, monotonic time it CHANGED).
        # Freshness is time-since-last-value-change on OUR clock — worker
        # clocks never enter the judgment (renewals bump a counter, so a
        # live worker's value always changes).
        self._lease_seen: Dict[str, Tuple[bytes, float]] = {}
        # Monotonic deadline before which no lease may expire: armed
        # after a store outage ends (workers couldn't renew through it)
        # and after driver recovery (replayed values are pre-crash), so
        # every worker gets one full timeout to show life first.
        self._lease_grace_until = 0.0
        self._store_outage_since: Optional[float] = None

    # ------------------------------------------------------------------

    def wait_for_available_slots(self, min_np: Optional[int] = None) -> None:
        """Block until discovery provides enough slots
        (reference ``driver.py:145``)."""
        need = min_np or self.min_np
        deadline = time.monotonic() + self.timeout
        while True:
            self.hosts.update_available_hosts()
            if self.hosts.total_slots() >= need:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {need} slots "
                    f"(have {self.hosts.total_slots()})")
            time.sleep(DISCOVER_HOSTS_FREQUENCY_SECS)

    def start(self, create_worker: Callable[[SlotInfo, int], None]) -> None:
        """Publish epoch 0 assignments, spawn workers, start discovery."""
        self._create_worker = create_worker
        self.wait_for_available_slots()
        self._rendezvous_epoch(initial=True)
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, name="hvd-elastic-discovery",
            daemon=True)
        self._discovery_thread.start()

    def stop(self, error_message: Optional[str] = None) -> None:
        if error_message and not self.stopped_error:
            self.stopped_error = error_message
        self._shutdown.set()
        self._wakeup.set()

    def finished(self) -> bool:
        return self._shutdown.is_set()

    # ------------------------------------------------------------------

    def _assignments(self) -> List[SlotInfo]:
        hosts = self.hosts.current_hosts
        total = sum(h.slots for h in hosts)
        np_ = min(total, self.max_np) if self.max_np else total
        return get_host_assignments(hosts, min(self.min_np, np_), np_)

    def _rendezvous_epoch(self, initial: bool = False) -> None:
        with self._lock:
            if not initial:
                self.epoch += 1
                self.resets += 1
            new_slots = self._assignments()
            self._slots = new_slots
            self._registry.reset(len(new_slots))

            # Publish the new table; removed identities get rank -1 so a
            # surviving process on a removed host exits cleanly.
            table = {}
            for s in new_slots:
                table[f"{s.hostname}:{s.local_rank}"] = {
                    "hostname": s.hostname, "rank": s.rank,
                    "local_rank": s.local_rank, "cross_rank": s.cross_rank,
                    "size": s.size, "local_size": s.local_size,
                    "cross_size": s.cross_size, "epoch": self.epoch,
                }
            for identity in self._known_identities:
                if identity not in table:
                    host, lr = identity.rsplit(":", 1)
                    table[identity] = {
                        "hostname": host, "rank": -1, "local_rank": int(lr),
                        "cross_rank": -1, "size": 0, "local_size": 0,
                        "cross_size": 0, "epoch": self.epoch,
                    }
            # One batched transaction: the whole slot table plus the
            # durable epoch land atomically (a driver crash mid-publish
            # can no longer leave a half-written table for
            # recover_from_store to adopt).  The epoch record rides the
            # same group so a restarted driver re-adopts this epoch
            # instead of resetting to 0 and respawning the world.
            publish_ops = [
                ("set", "rank_and_size", identity,
                 json.dumps(slot).encode())
                for identity, slot in table.items()]
            publish_ops.append(("set", DRIVER_SCOPE, "epoch",
                                str(self.epoch).encode()))
            self.rendezvous.batch(publish_ops)

            # Spawn processes for identities that have none yet.  A
            # driver-spawned worker is born at this epoch, so it is
            # implicitly acked — without this, `_renotify_unacked` pings
            # every worker forever after a scale-up (workers spawned fresh
            # never pass through `refresh_topology_from_rendezvous`, the
            # only other place the ack is written).  The ack writes are
            # collected and batched after the spawn loop: the first read
            # of them is a LATER tick's renotify scan.
            ack_ops = []
            for s in new_slots:
                identity = f"{s.hostname}:{s.local_rank}"
                if identity not in self._known_identities:
                    log.info("spawning worker %s (epoch %d, rank %d)",
                             identity, self.epoch, s.rank)
                    t_spawn = time.monotonic_ns() \
                        if timeline_mod.control_active() else None
                    self._create_worker(s, self.epoch)
                    if t_spawn is not None:
                        timeline_mod.control_span_since(
                            "driver", "DRV_SPAWN", t_spawn,
                            identity=identity, epoch=self.epoch)
                    self._exited_identities.discard(identity)
                    ack_ops.append(("set", "epoch_ack", identity,
                                    str(self.epoch).encode()))
                self._known_identities[identity] = s
            if ack_ops:
                self.rendezvous.batch(ack_ops)
            current = {f"{s.hostname}:{s.local_rank}" for s in new_slots}
            self._removed_identities = {
                i for i in self._known_identities if i not in current}
            for identity in self._removed_identities:
                self._known_identities.pop(identity)

    def _notify_workers(self, added_only: bool,
                        identities: Optional[set] = None) -> None:
        if identities is None:
            # Removed identities are notified too: their table entry says
            # rank −1, and the ping is what makes them exit promptly
            # instead of waiting to hit a dead socket.
            identities = {f"{s.hostname}:{s.local_rank}" for s in self._slots}
            identities.update(self._removed_identities)
        ordered = sorted(identities)
        raws = self.rendezvous.batch(
            [("get", WORKERS_SCOPE, identity) for identity in ordered])
        addresses = []
        missing = []
        for identity, raw in zip(ordered, raws):
            if raw:
                addresses.append(raw.decode())
            else:
                missing.append(identity)
        log.info("notifying %d workers of host change at epoch %d "
                 "(unregistered: %s)", len(addresses), self.epoch,
                 missing or "none")
        WorkerNotificationClient(addresses).notify_hosts_updated(
            added_only, epoch=self.epoch)

    def _discovery_loop(self) -> None:
        while not self._shutdown.is_set():
            t_wait = time.monotonic_ns() \
                if timeline_mod.control_active() else None
            self._wakeup.wait(DISCOVER_HOSTS_FREQUENCY_SECS)
            if t_wait is not None:
                timeline_mod.control_span_since("driver", "DRV_WAIT", t_wait)
            self._wakeup.clear()
            if self._shutdown.is_set():
                return
            # Chaos site for driver-death scenarios: action=raise kills
            # this thread (a wedged driver), exit kills the launcher.
            # Deliberately OUTSIDE the tick timing — an injected raise
            # must not land a latency sample.
            if faults.ACTIVE:
                faults.inject("driver.tick")
            t0 = time.monotonic_ns()
            try:
                self._tick(t0)
            finally:
                if metrics.ENABLED:
                    metrics.observe("driver_tick_seconds",
                                    (time.monotonic_ns() - t0) / 1e9)

    def _tick(self, t0_ns: int) -> None:
        """One discovery tick (the former loop body; early returns are the
        old ``continue``s).  ``t0_ns`` anchors the CHURN_EVENT span when
        this tick advances the epoch, so the span covers the detection
        work (lease scan, reset-request reads) that led to it."""
        # Every per-tick store op rides one try: a failure means the
        # store is down/partitioned, NOT that workers died — freeze
        # membership judgment (no lease expiry, no epoch advance)
        # until it answers again, then re-grace the lease clocks.
        try:
            fetched = self._tick_store_reads()
            self._renotify_unacked(fetched.get("epoch_ack"))
            reset_reasons = self._pending_reset_requests(fetched["reset"])
            demotion_reports = self._parse_demotion_reports(
                fetched["demotion"], self.epoch)
            expired = self._scan_leases(fetched["lease"])
            self._store_recovered()
            self._push_driver_metrics()
        except self._STORE_ERRORS as e:
            self._store_outage(e)
            return
        # Demotions blacklist BEFORE the discovery poll so the shed host
        # drops out of this very tick's host set (changed + removal).
        demoted = self._apply_demotions(demotion_reports)
        try:
            changed, removal = self.hosts.update_available_hosts()
        except Exception as e:  # noqa: BLE001 — discovery script hiccups
            log.warning("host discovery failed: %s", e)
            return
        # Identities that should have a process but whose worker died
        # (without the host being blacklisted) need a respawn epoch.
        with self._lock:
            if self._success:
                # Winding down: never rendezvous a new epoch once a
                # worker finished — a fresh slot table would assign a
                # rank to the dead-but-successful identity and hang the
                # survivors' mesh build.
                return
            if expired:
                # A lease expired with the store REACHABLE: the worker
                # is genuinely dead (or wedged past saving) — drop it
                # from the known set so the missing-workers path below
                # advances the epoch THIS tick.
                metrics.inc("lease_expirations_total", len(expired))
                for identity in sorted(expired):
                    log.warning(
                        "worker %s lease expired (no renewal in %.0fs "
                        "with the store reachable); declaring dead",
                        identity, self.lease_timeout)
                    self._known_identities.pop(identity, None)
                    self._lease_seen.pop(identity, None)
            missing_workers = {
                f"{s.hostname}:{s.local_rank}" for s in self._slots
            } - set(self._known_identities)
        if not changed and not missing_workers and not reset_reasons \
                and not demoted:
            return
        if self.reset_limit is not None and \
                self.resets >= self.reset_limit:
            msg = (f"elastic reset limit {self.reset_limit} reached; "
                   "stopping job (reference RESET_LIMIT_EXCEEDED)")
            log.error(msg)
            self.stop(error_message=msg)
            return
        if self.hosts.total_slots() < self.min_np:
            log.warning("host change leaves fewer than min_np slots; "
                        "waiting for capacity")
            return
        # A worker-initiated reset (e.g. corruption abort with every
        # process still alive) is removal-LIKE for sync purposes: the
        # workers rolled back and must state.sync() after the reset.
        removalish = removal or bool(missing_workers) \
            or bool(reset_reasons) or bool(demoted)
        # Cause precedence mirrors the judgment order above: an expired
        # lease explains the missing worker it produced, a demotion is a
        # deliberate shed of a live-but-slow host, a reset request means
        # everyone is alive, worker_exit is a death the exit monitor saw
        # first, host_change is pure discovery movement.
        cause = ("lease_expiry" if expired else
                 "demotion" if demoted else
                 "reset_request" if reset_reasons else
                 "worker_exit" if missing_workers else "host_change")
        log.info("host set changed (removal=%s, dead_workers=%s, "
                 "reset_requests=%s, demotions=%s, cause=%s); "
                 "advancing epoch",
                 removal, sorted(missing_workers), reset_reasons, demoted,
                 cause)
        self._rendezvous_epoch()
        self._await_ack = not removalish  # remember flavor for re-notify
        self._notify_workers(added_only=not removalish)
        metrics.inc("driver_epoch_transitions_total", cause=cause)
        flight_recorder.record(
            "epoch_transition", epoch=self.epoch, cause=cause,
            removal=removal, dead_workers=sorted(missing_workers),
            reset_requests=reset_reasons, demotions=demoted)
        if timeline_mod.control_active():
            timeline_mod.control_span_since(
                "driver", "CHURN_EVENT", t0_ns,
                epoch=self.epoch, cause=cause)
            timeline_mod.control_instant(
                "driver", "EPOCH_TRANSITION", epoch=self.epoch, cause=cause)

    def _tick_store_reads(self) -> Dict[str, Optional[Dict[str, object]]]:
        """Coalesce this tick's store reads into ONE batched round-trip.

        The pre-batching tick issued ``keys + 2–3 ops per identity``
        sequentially — at np=64 that is ~81% of a churn event's latency
        (``benchmarks/results/controller_churn_np64.json``, r14).  A
        single ``/batch`` carries the renotify ack reads, the
        reset-request reads, and the lease reads; a get of an absent key
        returns None, which each consumer already treats as "not
        present", so the old keys-then-intersect dance is unnecessary.
        Raises the store error on outage, like every other tick op."""
        with self._lock:
            slot_ids = sorted({f"{s.hostname}:{s.local_rank}"
                               for s in self._slots})
            ack_ids = None
            if self._await_ack is not None and self.epoch != 0:
                ids = set(slot_ids) | self._removed_identities
                ids -= self._exited_identities
                ack_ids = sorted(ids)
        ops: List[tuple] = []
        if ack_ids is not None:
            ops.extend(("get", "epoch_ack", i) for i in ack_ids)
        ops.extend(("get", rendezvous_client.RESET_REQUEST_SCOPE, i)
                   for i in slot_ids)
        ops.extend(("get", rendezvous_client.DEMOTION_REPORT_SCOPE, i)
                   for i in slot_ids)
        ops.extend(("get", LEASE_SCOPE, i) for i in slot_ids)
        results = self.rendezvous.batch(ops)
        idx = 0
        out: Dict[str, Optional[Dict[str, object]]] = {"epoch_ack": None}
        if ack_ids is not None:
            out["epoch_ack"] = dict(
                zip(ack_ids, results[idx:idx + len(ack_ids)]))
            idx += len(ack_ids)
        out["reset"] = dict(zip(slot_ids, results[idx:idx + len(slot_ids)]))
        idx += len(slot_ids)
        out["demotion"] = dict(
            zip(slot_ids, results[idx:idx + len(slot_ids)]))
        idx += len(slot_ids)
        out["lease"] = dict(zip(slot_ids, results[idx:]))
        return out

    def _push_driver_metrics(self) -> None:
        """External-server deployments only: the driver's gauges and
        counters live in the launcher process, which the (remote) server's
        ``GET /metrics`` cannot see — push an epoch-stamped snapshot under
        the reserved ``driver`` key, like a worker does.  The in-process
        server snapshots this same registry directly; pushing there too
        would double-count every series."""
        if not metrics.ENABLED or \
                not isinstance(self.rendezvous, ExternalRendezvous):
            return
        snap = metrics.registry.snapshot()
        snap["rank"] = "driver"
        snap["epoch"] = self.epoch
        self.rendezvous.set(metrics.METRICS_SCOPE, "driver",
                            json.dumps(snap).encode())

    def _pending_reset_requests(
            self, raws: Optional[Dict[str, object]] = None) -> List[str]:
        """Worker-posted epoch-reset requests for the CURRENT epoch.

        The integrity plane's recovery trigger: a corruption abort leaves
        every worker alive-but-rolled-back, waiting for an epoch that no
        exit or host change would ever produce.  A request stamped with an
        OLDER epoch was already answered by a later bump and is ignored —
        the same staleness rule the abort frames use.  ``raws`` is the
        tick's batched prefetch (identity -> value); None falls back to
        per-identity reads."""
        reasons = []
        if raws is None:
            with self._lock:
                identities = {f"{s.hostname}:{s.local_rank}"
                              for s in self._slots}
            raws = {identity: self.rendezvous.get(
                        rendezvous_client.RESET_REQUEST_SCOPE, identity)
                    for identity in identities}
        for identity in sorted(raws):
            raw = raws[identity]
            if raw is None:
                continue
            try:
                req = json.loads(raw.decode())
            except ValueError:
                continue
            if req.get("epoch", -1) == self.epoch:
                reasons.append(
                    f"{identity}: {req.get('reason', 'unspecified')}")
        return reasons

    @staticmethod
    def _parse_demotion_reports(
            raws: Optional[Dict[str, object]],
            epoch: int) -> List[Dict[str, object]]:
        """Coordinator-posted demotion reports for the CURRENT epoch.

        Mirrors the reset-request staleness rule: a report stamped with
        an older epoch was answered by a later bump already (the epoch
        advance it caused re-evaluated the whole world) and is ignored —
        stale reports auto-expire, no deletion round-trip needed.
        Malformed payloads are skipped; this channel is advisory."""
        reports: List[Dict[str, object]] = []
        for identity in sorted(raws or {}):
            raw = raws[identity]
            if raw is None:
                continue
            try:
                rep = json.loads(bytes(raw).decode())
            except (ValueError, TypeError):
                continue
            if isinstance(rep, dict) and rep.get("epoch", -1) == epoch \
                    and isinstance(rep.get("rank"), int):
                rep["reporter"] = identity
                reports.append(rep)
        return reports

    def _apply_demotions(
            self, reports: List[Dict[str, object]]) -> List[str]:
        """Blacklist the hosts named by current-epoch demotion reports.

        The victim's hostname is resolved authoritatively from the
        driver's own slot table by rank (the report's hostname field is
        best-effort evidence).  Returns ``rank@host`` strings for the
        demotions applied this tick — they drive the epoch advance and
        its ``cause="demotion"`` trail.  Repeated reports for a host
        already blacklisted still count as a demotion in flight (the
        epoch must advance) but stack no cooldown strike
        (``HostManager.blacklist`` idempotency)."""
        applied: List[str] = []
        for rep in reports:
            rank = rep["rank"]
            with self._lock:
                host = next((s.hostname for s in self._slots
                             if s.rank == rank), None)
            host = host or rep.get("hostname")
            if not isinstance(host, str) or not host:
                log.warning("demotion report for rank %s names no "
                            "resolvable host; ignoring", rank)
                continue
            evidence = (f"rank {rank} readiness-lag EWMA {rep.get('ewma')}s "
                        f"over demote threshold {rep.get('threshold')}s for "
                        f"{rep.get('cycles')} consecutive busy cycles")
            new_strike = self.hosts.blacklist(host, evidence=evidence)
            key = (str(rep.get("reporter")), self.epoch, rank)
            if key not in self._demotion_seen:
                self._demotion_seen.add(key)
                metrics.inc("straggler_demotions_total",
                            rank=str(rank), host=host)
                posted = rep.get("posted_unix")
                if isinstance(posted, (int, float)):
                    # Wall-clock across processes (coordinator vs
                    # driver): same-host skew is negligible against the
                    # multi-tick latencies this histogram bounds.
                    metrics.observe("demotion_latency_seconds",
                                    max(0.0, time.time() - posted))
                flight_recorder.record(
                    "demotion", epoch=self.epoch, rank=rank, host=host,
                    ewma=rep.get("ewma"), new_strike=new_strike,
                    reporter=rep.get("reporter"))
                log.warning("demoting host %s: %s", host, evidence)
            applied.append(f"rank {rank}@{host}")
        return applied

    # -- lease liveness / store outage (docs/control_plane.md) ---------

    def _scan_leases(
            self, raws: Optional[Dict[str, object]] = None) -> Set[str]:
        """Identities whose lease EXPIRED while the store was reachable.

        Identities that never posted a lease are exempt (metrics pushes
        disabled, or a pre-survivability worker) — exit-watching still
        covers those.  Raises the store error on outage: the caller's
        partitioned mode is the only place that decides what that means.
        ``raws`` is the tick's batched prefetch (every slot identity,
        None where no lease exists — same exemption); None falls back to
        the keys-then-get scan."""
        now = time.monotonic()
        if raws is None:
            with self._lock:
                identities = {f"{s.hostname}:{s.local_rank}"
                              for s in self._slots}
            leased = set(self.rendezvous.keys(LEASE_SCOPE))
            raws = {identity: self.rendezvous.get(LEASE_SCOPE, identity)
                    for identity in identities & leased}
        else:
            identities = set(raws)
        expired: Set[str] = set()
        min_ttl: Optional[float] = None
        for identity in sorted(raws):
            raw = raws[identity]
            if raw is None:
                continue
            seen = self._lease_seen.get(identity)
            if seen is None or seen[0] != raw:
                self._lease_seen[identity] = (raw, now)
                ttl = self.lease_timeout  # fresh renewal: full budget
            else:
                ttl = self.lease_timeout - (now - seen[1])
                if now >= self._lease_grace_until and \
                        now - seen[1] > self.lease_timeout:
                    expired.add(identity)
            if min_ttl is None or ttl < min_ttl:
                min_ttl = ttl
        # Drop tracking for identities that left the slot table.
        for identity in list(self._lease_seen):
            if identity not in identities:
                del self._lease_seen[identity]
        if metrics.ENABLED:
            metrics.set_gauge("leases_live",
                              len(self._lease_seen) - len(expired))
            if min_ttl is not None:
                metrics.set_gauge("lease_min_ttl_seconds", min_ttl)
        return expired

    def _store_outage(self, err: Exception) -> None:
        if self._store_outage_since is None:
            self._store_outage_since = time.monotonic()
            log.warning("rendezvous store unreachable (%s); entering "
                        "partitioned mode — no membership changes until "
                        "it returns", err)

    def _store_recovered(self) -> None:
        if self._store_outage_since is None:
            return
        outage = time.monotonic() - self._store_outage_since
        self._store_outage_since = None
        # Workers could not renew through the outage (their pushes go to
        # the same store); restart the judgment clock so a restarted
        # server's replayed leases don't read as instantly expired.
        self._lease_grace_until = time.monotonic() + self.lease_timeout
        log.info("rendezvous store reachable again after %.1fs outage; "
                 "lease clocks re-graced for %.0fs", outage,
                 self.lease_timeout)

    def recover_from_store(self) -> bool:
        """Driver crash-recovery: re-adopt a previous incarnation's state
        from a (journaled) store before :meth:`start`.

        Restores the epoch and seeds ``_known_identities`` from the
        leases of workers whose slot entry holds a rank at that epoch, so
        ``start()`` republishes the SAME epoch and spawns only identities
        with no surviving worker — instead of resetting to epoch 0 and
        respawning the world.  Returns True when prior state was found."""
        try:
            raw = self.rendezvous.get(DRIVER_SCOPE, "epoch")
            if raw is None:
                return False
            self.epoch = int(raw.decode())
            now = time.monotonic()
            adopted = []
            leased = self.rendezvous.keys(LEASE_SCOPE)
            fetch_ops: List[tuple] = []
            for identity in leased:
                fetch_ops.append(("get", LEASE_SCOPE, identity))
                fetch_ops.append(("get",
                                  rendezvous_client.RANK_AND_SIZE_SCOPE,
                                  identity))
            fetched = self.rendezvous.batch(fetch_ops)
            for i, identity in enumerate(leased):
                lease, slot_raw = fetched[2 * i], fetched[2 * i + 1]
                if lease is None or slot_raw is None:
                    continue
                try:
                    slot = json.loads(slot_raw.decode())
                except ValueError:
                    continue
                if slot.get("rank", -1) < 0 or \
                        slot.get("epoch", -1) != self.epoch:
                    continue
                info = SlotInfo(
                    hostname=slot["hostname"], rank=slot["rank"],
                    local_rank=slot["local_rank"],
                    cross_rank=slot["cross_rank"], size=slot["size"],
                    local_size=slot["local_size"],
                    cross_size=slot["cross_size"])
                with self._lock:
                    self._known_identities[identity] = info
                    self._lease_seen[identity] = (lease, now)
                adopted.append(identity)
        except (self._STORE_ERRORS, ValueError) as e:
            log.warning("driver state recovery failed (%s); starting "
                        "fresh at epoch 0", e)
            return False
        self._lease_grace_until = time.monotonic() + self.lease_timeout
        log.info("recovered driver state from store: epoch %d, re-adopted "
                 "live workers %s", self.epoch,
                 sorted(adopted) or "(none)")
        return True

    # ------------------------------------------------------------------

    def _renotify_unacked(
            self, acks: Optional[Dict[str, object]] = None) -> None:
        """Notification is racy against worker startup (a worker may
        register its endpoint just after a change fired).  Until every
        current identity acks the epoch, keep pinging the UNACKED ones each
        tick (pinging acked workers too would feed them stale interrupts).
        ``acks`` is the tick's batched prefetch (identity -> raw ack);
        None falls back to per-identity reads."""
        if self._await_ack is None or self.epoch == 0:
            return
        if acks is None:
            with self._lock:
                identities = {f"{s.hostname}:{s.local_rank}"
                              for s in self._slots}
                # Removed identities need the ping too (it is what makes
                # their worker see rank −1 and exit promptly); they ack
                # before exiting.  Identities whose process exited have
                # nobody listening.
                identities.update(self._removed_identities)
                identities -= self._exited_identities
            acks = {identity: self.rendezvous.get("epoch_ack", identity)
                    for identity in identities}
        unacked = set()
        for identity, raw in acks.items():
            if raw is None or int(raw.decode()) < self.epoch:
                unacked.add(identity)
        if not unacked:
            self._await_ack = None
            return
        self._notify_workers(added_only=self._await_ack, identities=unacked)

    def record_worker_exit(self, slot: SlotInfo, exit_code: int) -> None:
        """Called by the launcher's process monitor (reference
        ``_handle_worker_exit``, ``driver.py:292-308``).

        Crash exits (kill/segv/user error) count toward a low blacklist
        threshold; ``TRANSIENT_EXIT_CODE`` exits (worker gave up
        re-initializing, usually because a peer died first) toward a higher
        one — the survivor of someone else's crash must not poison its own
        host (VERDICT round 1 weak #1)."""
        if self._shutdown.is_set():
            return
        identity = f"{slot.hostname}:{slot.local_rank}"
        if exit_code == 0:
            self._registry.record_success(slot.rank)
            with self._lock:
                self._exited_identities.add(identity)
                self._success = True
                # A clean exit clears the host's record: sporadic transient
                # strikes spread over a long job must not accumulate into a
                # blacklist of a healthy host.
                self._crash_failures.pop(slot.hostname, None)
                self._transient_failures.pop(slot.hostname, None)
            return
        self._registry.record_failure(slot.rank)
        transient = exit_code == TRANSIENT_EXIT_CODE
        with self._lock:
            self._exited_identities.add(identity)
            counters = self._transient_failures if transient \
                else self._crash_failures
            counters[slot.hostname] += 1
            strikes = counters[slot.hostname]
            limit = self.transient_failure_limit if transient \
                else self.crash_failure_limit
            if strikes >= limit:
                self.hosts.blacklist(slot.hostname)
            else:
                log.warning("worker %s exited %d (%s, strike %d/%d); host "
                            "stays eligible", identity, exit_code,
                            "transient" if transient else "crash",
                            strikes, limit)
            self._known_identities.pop(identity, None)
        self._wakeup.set()

    @property
    def current_slots(self) -> List[SlotInfo]:
        with self._lock:
            return list(self._slots)
