"""The elastic driver: discovery loop, membership epochs, worker lifecycle.

Reference: ``runner/elastic/driver.py:1-309`` — a background thread polls
discovery every second (``DISCOVER_HOSTS_FREQUENCY_SECS``), host-set diffs
trigger worker notification + a new rendezvous epoch, failed workers
blacklist their host after repeated failures, and rank assignments stay
stable for surviving hosts (``_update_host_assignments``).

Membership protocol (epoch-based, coordinator-authoritative like the rest
of this framework):

1. every epoch the driver publishes a slot table (rank/local/cross + epoch)
   under ``rank_and_size/{hostname}:{local_rank}``;
2. workers (re)initialize from their identity's entry; removed identities
   see ``rank: -1`` and exit;
3. on change: epoch += 1, publish, notify live workers with the NEW epoch
   number (they raise ``HostsUpdatedInterrupt`` at the next commit; pings
   carrying an epoch ≤ the worker's own are ignored as stale — the race
   that livelocked round 1); spawn processes for new identities, which the
   driver marks as implicitly acked (they are born at the new epoch);
4. worker process death ⇒ failure recorded; crash exits blacklist the host
   after ``crash_failure_limit`` strikes, transient exits (the worker gave
   up re-initializing, exit code ``TRANSIENT_EXIT_CODE``) after
   ``transient_failure_limit``; identities whose process died but whose
   host is still healthy are respawned at the next epoch (reference
   ``registration.py:75-135`` resume semantics).
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..common import env as env_mod
from ..common import faults
from ..common.logging_util import get_logger
from ..core import flight_recorder
from ..core import metrics
from ..core import timeline as timeline_mod
from ..runner.hosts import SlotInfo, get_host_assignments
from ..runner.rendezvous import ExternalRendezvous, RendezvousServer
from ..transport.scopes import EPOCH_ACK_SCOPE, RANK_AND_SIZE_SCOPE
from ..transport.store import LEASE_SCOPE
from .constants import (
    DEFAULT_CRASH_FAILURE_LIMIT,
    DEFAULT_TRANSIENT_FAILURE_LIMIT,
    DISCOVER_HOSTS_FREQUENCY_SECS,
    ELASTIC_TIMEOUT_SECS,
    TRANSIENT_EXIT_CODE,
)
from . import rendezvous_client
from .discovery import HostManager
from .registration import WorkerStateRegistry
from .worker import WORKERS_SCOPE, WorkerNotificationClient

log = get_logger("horovod_tpu.elastic.driver")

#: Scope the driver persists its own durable state in (currently just the
#: epoch) so a restarted driver can re-adopt instead of resetting to 0.
#: Re-exported from the scope registry (transport/scopes.py, HVD010).
from ..transport.scopes import DRIVER_SCOPE  # noqa: E402  (re-export)


# -- epoch-judgment kernel (model-checked; see tools/mck proto) ---------------
#
# The per-tick membership judgment — fetch, stale-report filtering, lease
# scan, blacklist-before-discovery-poll, cause-precedence epoch advance —
# is written ONCE, as pure generators over an abstract driver: every
# side effect is one yielded step tuple, in exact program order, and the
# caller executes it against the live store/host manager/clock — or,
# under ``hvd-mck proto``, against a model cluster where messages
# reorder, processes crash at any yield point, and the lease clock is an
# explored action.  The model-checked code IS the production code; the
# orderings the checker proves (blacklist strictly before the host poll,
# at most one advance per judged tick, stale reports filtered before
# they can name a cause) are properties of THESE generators, not of a
# parallel description that could drift (exactly the extraction pattern
# transport/shm.py uses for the ring protocol).
#
# Step vocabulary (first element is the kind; the driver answers reads
# through ``generator.send``):
#
#   (STEP_TXN, ops, tag)          -> results   one batched store round-trip
#   (STEP_CLOCK,)                 -> float     monotonic clock read
#   (STEP_GRACE, until)                        arm the lease re-grace window
#   (STEP_BLACKLIST, host, rep)                shed a demoted host NOW —
#                                   strictly before this tick's host poll
#   (STEP_POLL_HOSTS,)            -> (changed, removal)   discovery poll
#   (STEP_GATE, which)            -> bool      advance gate ("success" /
#                                   "reset_limit" / "capacity"); True blocks
#   (STEP_EXPIRE, identity)                    drop a dead-leased identity
#   (STEP_ADVANCE, cause, removalish)          THE epoch advance (at most
#                                   one per judged tick, cause-tagged)

STEP_TXN = "txn"
STEP_CLOCK = "clock"
STEP_GRACE = "grace"
STEP_BLACKLIST = "blacklist"
STEP_POLL_HOSTS = "poll_hosts"
STEP_GATE = "gate"
STEP_EXPIRE = "expire"
STEP_ADVANCE = "advance"


def pending_reset_reasons(raws: Dict[str, object], epoch: int) -> List[str]:
    """Worker reset requests carrying the CURRENT epoch; anything older
    was answered by a later bump already and expires in place."""
    reasons = []
    for identity in sorted(raws or {}):
        raw = raws[identity]
        if raw is None:
            continue
        try:
            req = json.loads(bytes(raw).decode())
        except ValueError:
            continue
        if isinstance(req, dict) and req.get("epoch", -1) == epoch:
            reasons.append(
                f"{identity}: {req.get('reason', 'unspecified')}")
    return reasons


def parse_demotion_reports(raws: Optional[Dict[str, object]],
                           epoch: int) -> List[Dict[str, object]]:
    """Coordinator demotion reports for the CURRENT epoch (same staleness
    rule as reset requests); malformed payloads are skipped — this
    channel is advisory."""
    reports: List[Dict[str, object]] = []
    for identity in sorted(raws or {}):
        raw = raws[identity]
        if raw is None:
            continue
        try:
            rep = json.loads(bytes(raw).decode())
        except (ValueError, TypeError):
            continue
        if isinstance(rep, dict) and rep.get("epoch", -1) == epoch \
                and isinstance(rep.get("rank"), int):
            rep["reporter"] = identity
            reports.append(rep)
    return reports


def decide_cause(expired, demoted, reset_reasons, missing_workers) -> str:
    """Cause precedence, mirroring the judgment order: an expired lease
    explains the missing worker it produced, a demotion is a deliberate
    shed of a live-but-slow host, a reset request means everyone is
    alive, worker_exit is a death the exit monitor saw first,
    host_change is pure discovery movement."""
    return ("lease_expiry" if expired else
            "demotion" if demoted else
            "reset_request" if reset_reasons else
            "worker_exit" if missing_workers else "host_change")


def tick_read_steps(epoch: int, await_ack, slot_ids, removed, exited):
    """Coalesce one tick's store reads into ONE batched round-trip and
    unpack the results; returns the fetched dict (``epoch_ack`` /
    ``reset`` / ``demotion`` / ``lease`` maps keyed by identity).  A get
    of an absent key returns None, which every consumer treats as "not
    present", so no keys-then-intersect dance is needed."""
    slot_ids = sorted(slot_ids)
    ack_ids = None
    if await_ack is not None and epoch != 0:
        ids = set(slot_ids) | set(removed)
        ids -= set(exited)
        ack_ids = sorted(ids)
    ops: List[tuple] = []
    if ack_ids is not None:
        ops.extend(("get", EPOCH_ACK_SCOPE, i) for i in ack_ids)
    ops.extend(("get", rendezvous_client.RESET_REQUEST_SCOPE, i)
               for i in slot_ids)
    ops.extend(("get", rendezvous_client.DEMOTION_REPORT_SCOPE, i)
               for i in slot_ids)
    ops.extend(("get", LEASE_SCOPE, i) for i in slot_ids)
    results = yield (STEP_TXN, tuple(ops), "tick_reads")
    idx = 0
    out: Dict[str, Optional[Dict[str, object]]] = {"epoch_ack": None}
    if ack_ids is not None:
        out["epoch_ack"] = dict(
            zip(ack_ids, results[idx:idx + len(ack_ids)]))
        idx += len(ack_ids)
    out["reset"] = dict(zip(slot_ids, results[idx:idx + len(slot_ids)]))
    idx += len(slot_ids)
    out["demotion"] = dict(
        zip(slot_ids, results[idx:idx + len(slot_ids)]))
    idx += len(slot_ids)
    out["lease"] = dict(zip(slot_ids, results[idx:]))
    return out


def scan_lease_steps(raws: Dict[str, object],
                     lease_seen: Dict[str, Tuple[bytes, float]],
                     grace_until: float, lease_timeout: float):
    """Judge lease freshness: time-since-last-VALUE-CHANGE on the clock
    this generator reads (worker clocks never enter the judgment), with
    no expiry before ``grace_until``.  Mutates ``lease_seen`` in place
    (it IS the driver's tracking dict).  Returns ``(expired, min_ttl)``;
    identities that never posted a lease are exempt."""
    now = yield (STEP_CLOCK,)
    identities = set(raws)
    expired: Set[str] = set()
    min_ttl: Optional[float] = None
    for identity in sorted(raws):
        raw = raws[identity]
        if raw is None:
            continue
        seen = lease_seen.get(identity)
        if seen is None or seen[0] != raw:
            lease_seen[identity] = (raw, now)
            ttl = lease_timeout  # fresh renewal: full budget
        else:
            ttl = lease_timeout - (now - seen[1])
            if now >= grace_until and now - seen[1] > lease_timeout:
                expired.add(identity)
        if min_ttl is None or ttl < min_ttl:
            min_ttl = ttl
    # Drop tracking for identities that left the slot table.
    for identity in list(lease_seen):
        if identity not in identities:
            del lease_seen[identity]
    return expired, min_ttl


def tick_judgment_steps(epoch: int, fetched: Dict[str, object],
                        rank_to_host: Dict[int, str],
                        known_identities, slot_identities,
                        lease_seen, grace_until: float,
                        lease_timeout: float):
    """One judged tick, from a successful fetch to the advance decision.

    The orderings the checker proves live HERE: demotion blacklists are
    yielded strictly before the discovery poll (so a shed host drops out
    of this very tick's host set), expiries before the missing-worker
    computation, the gates before the advance, and STEP_ADVANCE at most
    once.  Returns the judgment record (cause, removalish, expired,
    missing, plus bookkeeping for logs/metrics)."""
    reset_reasons = pending_reset_reasons(fetched["reset"], epoch)
    reports = parse_demotion_reports(fetched["demotion"], epoch)
    expired, min_ttl = yield from scan_lease_steps(
        fetched["lease"], lease_seen, grace_until, lease_timeout)
    demoted: List[str] = []
    unresolvable: List[int] = []
    for rep in reports:
        rank = rep["rank"]
        host = rank_to_host.get(rank) or rep.get("hostname")
        if not isinstance(host, str) or not host:
            unresolvable.append(rank)
            continue
        # Blacklist BEFORE the discovery poll, never after.
        yield (STEP_BLACKLIST, host, rep)
        demoted.append(f"rank {rank}@{host}")
    changed, removal = yield (STEP_POLL_HOSTS,)
    j = {
        "advanced": False, "cause": None, "removalish": False,
        "removal": removal, "expired": expired, "missing": set(),
        "reset_reasons": reset_reasons, "demoted": demoted,
        "unresolvable": unresolvable, "min_ttl": min_ttl,
        "leases_live": len(lease_seen) - len(expired), "blocked": None,
    }
    if (yield (STEP_GATE, "success")):
        # Winding down: never rendezvous a new epoch once a worker
        # finished — a fresh slot table would assign a rank to the
        # dead-but-successful identity and hang the survivors' mesh.
        j["blocked"] = "success"
        return j
    for identity in sorted(expired):
        # Expired with the store REACHABLE: genuinely dead (or wedged
        # past saving) — drop it so the missing-workers path advances
        # the epoch THIS tick, cause-tagged lease_expiry.
        yield (STEP_EXPIRE, identity)
    missing = set(slot_identities) - (set(known_identities) - expired)
    j["missing"] = missing
    if not changed and not missing and not reset_reasons and not demoted:
        return j
    if (yield (STEP_GATE, "reset_limit")):
        j["blocked"] = "reset_limit"
        return j
    if (yield (STEP_GATE, "capacity")):
        j["blocked"] = "capacity"
        return j
    # A worker-initiated reset (e.g. corruption abort with every process
    # still alive) is removal-LIKE for sync purposes: the workers rolled
    # back and must state.sync() after the reset.
    removalish = removal or bool(missing) or bool(reset_reasons) \
        or bool(demoted)
    cause = decide_cause(expired, demoted, reset_reasons, missing)
    yield (STEP_ADVANCE, cause, removalish)
    j.update(advanced=True, cause=cause, removalish=removalish)
    return j


def reshard_plan(table: Dict[str, dict], known_identities,
                 enabled: bool, pending: Optional[dict],
                 recent_joiners=()) -> Dict[str, object]:
    """Pure reshard judgment for one epoch publish (model-checked; the
    production ``_rendezvous_epoch`` and the ``hvd-mck proto`` model
    driver both call THIS).

    ``table`` is the slot table about to be published; ``known_identities``
    is the set of identities with a live worker process from the previous
    epoch (the spawn loop's exact complement: everything ranked but not
    known gets spawned).  ``survivors`` are the process-keeping ranked
    identities — the set whose epoch acks gate the commit.  ``joiners``
    (the sync targets) are the about-to-be-spawned identities PLUS any
    survivor that was itself a joiner of the immediately previous epoch
    (``recent_joiners``): its ack proves adoption, not a completed state
    sync, so until an epoch with it as a plain survivor commits it may
    still hold blank init state.  ``sync_root`` is therefore the lowest
    rank among SEASONED survivors only — rank 0 itself may be the fresh
    process being state-filled, and a recent joiner as root could
    broadcast blank state over everyone's progress.  No seasoned
    survivor ⇒ not eligible (legacy full sync from rank 0).

    The fallback rule is load-bearing: while a previous reshard is
    ``pending`` (published but never survivor-acked to commit — a
    survivor crashed mid-reshard), the NEXT publish must NOT carry the
    marker, degrading those workers to the legacy full-teardown path
    (mck: V_RESHARD_FALLBACK_MISSED / ``reshard_fallback_dropped``)."""
    keepers = sorted(i for i, s in table.items()
                     if s["rank"] >= 0 and i in known_identities)
    spawning = sorted(i for i, s in table.items()
                      if s["rank"] >= 0 and i not in known_identities)
    recent = set(recent_joiners)
    seasoned = [i for i in keepers if i not in recent]
    joiners = sorted(set(spawning) | (set(keepers) & recent))
    fallback = pending is not None
    eligible = enabled and bool(seasoned) and not fallback
    sync_root = min((table[i]["rank"] for i in seasoned), default=0)
    return {"eligible": eligible, "fallback": fallback,
            "survivors": keepers, "joiners": joiners,
            "sync_root": sync_root}


def reshard_commit_steps(epoch: int, survivors):
    """One commit-probe of a pending zero-restart reshard.

    The ordering the checker proves lives HERE: the durable commit record
    is written ONLY after every listed survivor's epoch ack for ``epoch``
    is readable in the store — writing it earlier is exactly the seeded
    ``reshard_commit_unguarded`` mutant (V_RESHARD_EARLY_COMMIT): a
    crash after an early commit would adopt a topology some survivor
    never agreed to rejoin.  Returns ``{"committed", "missing"}``; the
    caller re-probes next tick while survivors are still rendezvousing,
    and an epoch ADVANCE while still missing is the fallback path."""
    if not survivors:
        return {"committed": False, "missing": []}
    acks = yield (STEP_TXN,
                  tuple(("get", EPOCH_ACK_SCOPE, i) for i in survivors),
                  "reshard_acks")
    missing = []
    for identity, raw in zip(survivors, acks):
        try:
            acked = int(bytes(raw).decode()) if raw is not None else -1
        except ValueError:
            acked = -1
        if acked < epoch:
            missing.append(identity)
    if missing:
        return {"committed": False, "missing": missing}
    yield (STEP_TXN,
           (("set", DRIVER_SCOPE, "reshard_commit", str(epoch).encode()),),
           "reshard_commit")
    return {"committed": True, "missing": []}


def outage_recovery_steps(lease_timeout: float):
    """Steps on the first successful fetch after a store outage: workers
    could not renew through it (their pushes go to the same store), so
    the judgment clock restarts — every lease gets one full timeout to
    show life before it may expire.  Dropping this re-grace is exactly
    the seeded ``regrace_dropped`` mutant: a restarted store's replayed
    leases read as instantly expired and a live worker is shed."""
    now = yield (STEP_CLOCK,)
    yield (STEP_GRACE, now + lease_timeout)


def recover_steps(lease_timeout: float):
    """Driver crash-recovery judgment: re-adopt the durable epoch and
    the live-leased identities whose slot entry holds a rank AT that
    epoch, then re-grace (replayed lease values are pre-crash).  Returns
    None when no prior state exists, else ``{"epoch", "adopted"}`` with
    ``adopted`` mapping identity -> (slot dict, lease value).  The
    checker proves the adopted epoch equals the journal-replayed one
    exactly — never 0, never a stale predecessor."""
    res = yield (STEP_TXN, (("get", DRIVER_SCOPE, "epoch"),),
                 "recover_epoch")
    raw = res[0]
    if raw is None:
        return None
    epoch = int(bytes(raw).decode())
    leased = (yield (STEP_TXN, (("keys", LEASE_SCOPE),),
                     "recover_lease_keys"))[0]
    fetch_ops: List[tuple] = []
    for identity in leased:
        fetch_ops.append(("get", LEASE_SCOPE, identity))
        fetch_ops.append(("get", rendezvous_client.RANK_AND_SIZE_SCOPE,
                          identity))
    fetched: List[object] = []
    if fetch_ops:
        fetched = yield (STEP_TXN, tuple(fetch_ops), "recover_slots")
    adopted: Dict[str, Tuple[dict, object]] = {}
    for i, identity in enumerate(leased):
        lease, slot_raw = fetched[2 * i], fetched[2 * i + 1]
        if lease is None or slot_raw is None:
            continue
        try:
            slot = json.loads(bytes(slot_raw).decode())
        except ValueError:
            continue
        if slot.get("rank", -1) < 0 or slot.get("epoch", -1) != epoch:
            continue
        adopted[identity] = (slot, lease)
    now = yield (STEP_CLOCK,)
    yield (STEP_GRACE, now + lease_timeout)
    return {"epoch": epoch, "adopted": adopted}


class ElasticDriver:
    #: Store-outage shapes: a dead/restarting rendezvous server surfaces
    #: from the HTTP client as URLError/ConnectionError — both OSError.
    #: The in-process server never raises, so partitioned mode only ever
    #: engages against an external (HOROVOD_RENDEZVOUS_EXTERNAL) store.
    _STORE_ERRORS = OSError

    def __init__(self, rendezvous: RendezvousServer, host_manager: HostManager,
                 min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 timeout: float = ELASTIC_TIMEOUT_SECS,
                 crash_failure_limit: Optional[int] = None,
                 transient_failure_limit: Optional[int] = None,
                 lease_timeout: Optional[float] = None):
        self.rendezvous = rendezvous
        self.hosts = host_manager
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.timeout = timeout
        self.epoch = 0
        self.resets = 0
        self.stopped_error: Optional[str] = None
        self.crash_failure_limit = crash_failure_limit if crash_failure_limit \
            is not None else env_mod.get_int(
                env_mod.HOROVOD_ELASTIC_CRASH_FAILURE_LIMIT,
                DEFAULT_CRASH_FAILURE_LIMIT)
        self.transient_failure_limit = transient_failure_limit \
            if transient_failure_limit is not None else env_mod.get_int(
                env_mod.HOROVOD_ELASTIC_TRANSIENT_FAILURE_LIMIT,
                DEFAULT_TRANSIENT_FAILURE_LIMIT)
        self._crash_failures: Dict[str, int] = defaultdict(int)
        self._transient_failures: Dict[str, int] = defaultdict(int)
        self._slots: List[SlotInfo] = []
        self._known_identities: Dict[str, SlotInfo] = {}
        self._create_worker: Optional[Callable[[SlotInfo, int], None]] = None
        self._registry = WorkerStateRegistry(0)
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._wakeup = threading.Event()
        self._discovery_thread: Optional[threading.Thread] = None
        self._await_ack: Optional[bool] = None  # added_only flavor, or None
        self._removed_identities: set = set()
        self._exited_identities: set = set()
        # (reporter identity, epoch, rank) demotions already counted: a
        # current-epoch report stays readable in the store until the
        # epoch advances (e.g. across waiting-for-capacity ticks), and
        # re-reading it must not re-count metrics or re-log the shed.
        self._demotion_seen: Set[Tuple[str, int, int]] = set()
        # Once any worker succeeds the job is winding down: membership no
        # longer changes, so a finished (dead-but-successful) identity can
        # never be handed a rank in a fresh epoch (reference
        # registration.py:139-143 stops the driver on first SUCCESS).
        self._success = False
        # -- lease-based liveness (docs/control_plane.md) --------------
        self.lease_timeout = lease_timeout if lease_timeout is not None \
            else env_mod.get_float(env_mod.HOROVOD_LEASE_TIMEOUT_SECS,
                                   env_mod.DEFAULT_LEASE_TIMEOUT_SECS)
        # identity -> (last lease value seen, monotonic time it CHANGED).
        # Freshness is time-since-last-value-change on OUR clock — worker
        # clocks never enter the judgment (renewals bump a counter, so a
        # live worker's value always changes).
        self._lease_seen: Dict[str, Tuple[bytes, float]] = {}
        # Monotonic deadline before which no lease may expire: armed
        # after a store outage ends (workers couldn't renew through it)
        # and after driver recovery (replayed values are pre-crash), so
        # every worker gets one full timeout to show life first.
        self._lease_grace_until = 0.0
        self._store_outage_since: Optional[float] = None
        # -- zero-restart resharding (docs/elastic.md "Live resharding") --
        self.reshard_enabled = env_mod.get_bool(env_mod.HOROVOD_RESHARD,
                                                True)
        # The published-but-uncommitted reshard, or None: {"epoch",
        # "survivors", "published_ns", "missing"}.  Commit lands when
        # every listed survivor has acked the epoch (reshard_commit_steps,
        # probed each tick); an epoch advance while still pending is the
        # legacy-fallback path and publishes WITHOUT the marker.
        self._reshard_pending: Optional[dict] = None
        # Joiners of the most recent MARKED publish: their acks prove
        # epoch adoption, not a completed state sync, so the next plan
        # re-lists them as joiners and never picks them as sync root
        # (see reshard_plan).  Cleared by any unmarked publish — a legacy
        # epoch full-syncs everyone from rank 0.
        self._last_reshard_joiners: set = set()
        # Epoch adopted by recover_from_store (None = fresh start): the
        # value the initial republish CAS-fences on, so a crashed
        # incarnation's in-flight publish landing after our recovery
        # read fails the republish instead of being stomped with a
        # stale epoch (mck: reshard_driver_crash / epoch-regression).
        self._recovered_epoch: Optional[int] = None

    # ------------------------------------------------------------------

    def wait_for_available_slots(self, min_np: Optional[int] = None) -> None:
        """Block until discovery provides enough slots
        (reference ``driver.py:145``)."""
        need = min_np or self.min_np
        deadline = time.monotonic() + self.timeout
        while True:
            self.hosts.update_available_hosts()
            if self.hosts.total_slots() >= need:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {need} slots "
                    f"(have {self.hosts.total_slots()})")
            time.sleep(DISCOVER_HOSTS_FREQUENCY_SECS)

    def start(self, create_worker: Callable[[SlotInfo, int], None]) -> None:
        """Publish epoch 0 assignments, spawn workers, start discovery."""
        self._create_worker = create_worker
        self.wait_for_available_slots()
        for attempt in range(5):
            if self._rendezvous_epoch(initial=True):
                break
            # The initial publish lost its epoch fence: a previous
            # incarnation's in-flight publish landed after our recovery
            # read.  Re-adopt from the store and republish at the newer
            # epoch instead of stomping it with the stale one.
            log.warning("initial epoch publish lost its fence (attempt "
                        "%d); re-adopting driver state from the store",
                        attempt + 1)
            self.recover_from_store()
        else:
            raise RuntimeError(
                "could not fence the initial epoch publish after 5 "
                "recovery attempts: the store's epoch keeps moving "
                "under us")
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, name="hvd-elastic-discovery",
            daemon=True)
        self._discovery_thread.start()

    def stop(self, error_message: Optional[str] = None) -> None:
        if error_message and not self.stopped_error:
            self.stopped_error = error_message
        self._shutdown.set()
        self._wakeup.set()

    def finished(self) -> bool:
        return self._shutdown.is_set()

    # ------------------------------------------------------------------

    def _assignments(self) -> List[SlotInfo]:
        hosts = self.hosts.current_hosts
        total = sum(h.slots for h in hosts)
        np_ = min(total, self.max_np) if self.max_np else total
        return get_host_assignments(hosts, min(self.min_np, np_), np_)

    def _rendezvous_epoch(self, initial: bool = False) -> bool:
        with self._lock:
            if not initial:
                self.epoch += 1
                self.resets += 1
            new_slots = self._assignments()
            self._slots = new_slots
            self._registry.reset(len(new_slots))

            # Publish the new table; removed identities get rank -1 so a
            # surviving process on a removed host exits cleanly.
            table = {}
            for s in new_slots:
                table[f"{s.hostname}:{s.local_rank}"] = {
                    "hostname": s.hostname, "rank": s.rank,
                    "local_rank": s.local_rank, "cross_rank": s.cross_rank,
                    "size": s.size, "local_size": s.local_size,
                    "cross_size": s.cross_size, "epoch": self.epoch,
                }
            for identity in self._known_identities:
                if identity not in table:
                    host, lr = identity.rsplit(":", 1)
                    table[identity] = {
                        "hostname": host, "rank": -1, "local_rank": int(lr),
                        "cross_rank": -1, "size": 0, "local_size": 0,
                        "cross_size": 0, "epoch": self.epoch,
                    }
            # Zero-restart reshard judgment (pure kernel, shared with the
            # mck model driver): survivors/joiners/sync_root from the
            # table about to go out.  Eligible ⇒ every entry carries the
            # marker in the SAME atomic publish; a still-pending previous
            # reshard forces the fallback (no marker — survivors of the
            # failed reshard take the legacy full-teardown path).
            plan = reshard_plan(
                table, set(self._known_identities),
                enabled=self.reshard_enabled and not initial,
                pending=self._reshard_pending,
                recent_joiners=self._last_reshard_joiners)
            if plan["fallback"]:
                failed = self._reshard_pending
                self._reshard_pending = None
                metrics.inc("reshard_fallbacks_total")
                flight_recorder.record(
                    "reshard_fallback", epoch=self.epoch,
                    pending_epoch=failed["epoch"],
                    missing=sorted(failed.get("missing") or []))
                log.warning(
                    "reshard for epoch %d never committed (unacked: %s); "
                    "epoch %d falls back to the full-teardown path",
                    failed["epoch"], sorted(failed.get("missing") or []),
                    self.epoch)
            if plan["eligible"]:
                # "survivors" rides the published entries so the store
                # holds ground truth for the commit's ack set (the mck
                # store-side V_RESHARD_EARLY_COMMIT check reads it; it
                # also makes a wedged reshard diagnosable from the store
                # alone).
                for slot in table.values():
                    slot["reshard"] = True
                    slot["sync_root"] = plan["sync_root"]
                    slot["joiners"] = plan["joiners"]
                    slot["survivors"] = plan["survivors"]
            # One batched transaction: the whole slot table plus the
            # durable epoch land atomically (a driver crash mid-publish
            # can no longer leave a half-written table for
            # recover_from_store to adopt).  The epoch record rides the
            # same group so a restarted driver re-adopts this epoch
            # instead of resetting to 0 and respawning the world.
            publish_ops = [
                ("set", RANK_AND_SIZE_SCOPE, identity,
                 json.dumps(slot).encode())
                for identity, slot in table.items()]
            publish_ops.append(("set", DRIVER_SCOPE, "epoch",
                                str(self.epoch).encode()))
            if initial:
                # Fence the initial/recovery republish on the epoch we
                # adopted (absent on a fresh start): a crashed
                # incarnation's in-flight publish landing after our
                # recovery read must fail this batch, not get stomped
                # with a stale epoch.  start() re-adopts and retries.
                expected = None if self._recovered_epoch is None \
                    else str(self._recovered_epoch).encode()
                publish_ops.insert(
                    0, ("check", DRIVER_SCOPE, "epoch", expected))
            if plan["eligible"]:
                # Armed BEFORE the publish on purpose: a store error on
                # the batch does not prove the marked table never landed
                # (the lost half may be the ack), and an armed pending
                # is safe either way — if the marker never landed, no
                # survivor can ack this epoch, the commit never fires,
                # and the next advance falls back to the legacy path.
                self._reshard_pending = {
                    "epoch": self.epoch,
                    "survivors": plan["survivors"],
                    "published_ns": time.monotonic_ns(),
                    "missing": list(plan["survivors"]),
                }
                self._last_reshard_joiners = set(plan["joiners"])
            else:
                self._last_reshard_joiners = set()
            results = self.rendezvous.batch(publish_ops)
            if initial and results and results[0] is False:
                return False  # fence lost; start() re-adopts + retries
            if plan["eligible"]:
                flight_recorder.record(
                    "reshard_publish", epoch=self.epoch,
                    survivors=plan["survivors"], joiners=plan["joiners"],
                    sync_root=plan["sync_root"])
                log.info("epoch %d published with reshard marker "
                         "(%d survivors, %d joiners, sync_root=%d)",
                         self.epoch, len(plan["survivors"]),
                         len(plan["joiners"]), plan["sync_root"])

            # Spawn processes for identities that have none yet.  A
            # driver-spawned worker is born at this epoch, so it is
            # implicitly acked — without this, `_renotify_unacked` pings
            # every worker forever after a scale-up (workers spawned fresh
            # never pass through `refresh_topology_from_rendezvous`, the
            # only other place the ack is written).  The ack writes are
            # collected and batched after the spawn loop: the first read
            # of them is a LATER tick's renotify scan.
            ack_ops = []
            for s in new_slots:
                identity = f"{s.hostname}:{s.local_rank}"
                if identity not in self._known_identities:
                    log.info("spawning worker %s (epoch %d, rank %d)",
                             identity, self.epoch, s.rank)
                    t_spawn = time.monotonic_ns() \
                        if timeline_mod.control_active() else None
                    self._create_worker(s, self.epoch)
                    if t_spawn is not None:
                        timeline_mod.control_span_since(
                            "driver", "DRV_SPAWN", t_spawn,
                            identity=identity, epoch=self.epoch)
                    self._exited_identities.discard(identity)
                    ack_ops.append(("set", EPOCH_ACK_SCOPE, identity,
                                    str(self.epoch).encode()))
                self._known_identities[identity] = s
            if ack_ops:
                self.rendezvous.batch(ack_ops)
            current = {f"{s.hostname}:{s.local_rank}" for s in new_slots}
            self._removed_identities = {
                i for i in self._known_identities if i not in current}
            for identity in self._removed_identities:
                self._known_identities.pop(identity)
            return True

    def _notify_workers(self, added_only: bool,
                        identities: Optional[set] = None,
                        reshard: bool = False) -> None:
        if identities is None:
            # Removed identities are notified too: their table entry says
            # rank −1, and the ping is what makes them exit promptly
            # instead of waiting to hit a dead socket.
            identities = {f"{s.hostname}:{s.local_rank}" for s in self._slots}
            identities.update(self._removed_identities)
        ordered = sorted(identities)
        raws = self.rendezvous.batch(
            [("get", WORKERS_SCOPE, identity) for identity in ordered])
        addresses = []
        missing = []
        for identity, raw in zip(ordered, raws):
            if raw:
                addresses.append(raw.decode())
            else:
                missing.append(identity)
        log.info("notifying %d workers of host change at epoch %d "
                 "(unregistered: %s)", len(addresses), self.epoch,
                 missing or "none")
        WorkerNotificationClient(addresses).notify_hosts_updated(
            added_only, epoch=self.epoch, reshard=reshard)

    def _discovery_loop(self) -> None:
        while not self._shutdown.is_set():
            t_wait = time.monotonic_ns() \
                if timeline_mod.control_active() else None
            self._wakeup.wait(DISCOVER_HOSTS_FREQUENCY_SECS)
            if t_wait is not None:
                timeline_mod.control_span_since("driver", "DRV_WAIT", t_wait)
            self._wakeup.clear()
            if self._shutdown.is_set():
                return
            # Chaos site for driver-death scenarios: action=raise kills
            # this thread (a wedged driver), exit kills the launcher.
            # Deliberately OUTSIDE the tick timing — an injected raise
            # must not land a latency sample.
            if faults.ACTIVE:
                faults.inject("driver.tick")
            t0 = time.monotonic_ns()
            try:
                self._tick(t0)
            finally:
                if metrics.ENABLED:
                    metrics.observe("driver_tick_seconds",
                                    (time.monotonic_ns() - t0) / 1e9)

    def _tick(self, t0_ns: int) -> None:
        """One discovery tick (the former loop body; early returns are the
        old ``continue``s).  ``t0_ns`` anchors the CHURN_EVENT span when
        this tick advances the epoch, so the span covers the detection
        work (lease scan, reset-request reads) that led to it."""
        # Every per-tick store op rides one try: a failure means the
        # store is down/partitioned, NOT that workers died — freeze
        # membership judgment (no lease expiry, no epoch advance)
        # until it answers again, then re-grace the lease clocks.
        try:
            fetched = self._tick_store_reads()
            self._renotify_unacked(fetched.get("epoch_ack"))
            self._store_recovered()
            self._push_driver_metrics()
            self._reshard_commit_probe()
        except self._STORE_ERRORS as e:
            self._store_outage(e)
            return
        # Drive the pure judgment kernel (model-checked by ``hvd-mck
        # proto``) against the live host manager and clock.  The
        # orderings — blacklist-before-poll, expire-before-missing,
        # gates-before-advance — live in :func:`tick_judgment_steps`;
        # this loop only executes its steps.
        with self._lock:
            rank_to_host = {s.rank: s.hostname for s in self._slots}
            slot_identities = {f"{s.hostname}:{s.local_rank}"
                               for s in self._slots}
            known = set(self._known_identities)
        steps = tick_judgment_steps(
            self.epoch, fetched, rank_to_host, known, slot_identities,
            self._lease_seen, self._lease_grace_until, self.lease_timeout)
        resp = None
        while True:
            try:
                step = steps.send(resp)
            except StopIteration as fin:
                j = fin.value
                break
            kind = step[0]
            resp = None
            if kind == STEP_CLOCK:
                resp = time.monotonic()
            elif kind == STEP_BLACKLIST:
                self._blacklist_for_demotion(step[1], step[2])
            elif kind == STEP_POLL_HOSTS:
                try:
                    resp = self.hosts.update_available_hosts()
                except Exception as e:  # noqa: BLE001 — discovery
                    # script hiccups must not kill the judgment loop
                    log.warning("host discovery failed: %s", e)
                    steps.close()
                    return
            elif kind == STEP_GATE:
                resp = self._judgment_gate(step[1])
            elif kind == STEP_EXPIRE:
                self._expire_identity(step[1])
            # STEP_ADVANCE needs no in-loop action: it is the last yield,
            # and the advance below consumes the returned judgment.
        for rank in j["unresolvable"]:
            log.warning("demotion report for rank %s names no "
                        "resolvable host; ignoring", rank)
        if metrics.ENABLED:
            metrics.set_gauge("leases_live", j["leases_live"])
            if j["min_ttl"] is not None:
                metrics.set_gauge("lease_min_ttl_seconds", j["min_ttl"])
        if j["expired"]:
            metrics.inc("lease_expirations_total", len(j["expired"]))
        if not j["advanced"]:
            return
        cause, removalish = j["cause"], j["removalish"]
        missing_workers = j["missing"]
        log.info("host set changed (removal=%s, dead_workers=%s, "
                 "reset_requests=%s, demotions=%s, cause=%s); "
                 "advancing epoch",
                 j["removal"], sorted(missing_workers), j["reset_reasons"],
                 j["demoted"], cause)
        self._rendezvous_epoch()
        self._await_ack = not removalish  # remember flavor for re-notify
        self._notify_workers(added_only=not removalish,
                             reshard=self._reshard_pending is not None)
        metrics.inc("driver_epoch_transitions_total", cause=cause)
        flight_recorder.record(
            "epoch_transition", epoch=self.epoch, cause=cause,
            removal=j["removal"], dead_workers=sorted(missing_workers),
            reset_requests=j["reset_reasons"], demotions=j["demoted"])
        if timeline_mod.control_active():
            timeline_mod.control_span_since(
                "driver", "CHURN_EVENT", t0_ns,
                epoch=self.epoch, cause=cause)
            timeline_mod.control_instant(
                "driver", "EPOCH_TRANSITION", epoch=self.epoch, cause=cause)

    def _judgment_gate(self, which: str) -> bool:
        """Answer one STEP_GATE: True blocks this tick's advance."""
        if which == "success":
            with self._lock:
                return self._success
        if which == "reset_limit":
            if self.reset_limit is not None and \
                    self.resets >= self.reset_limit:
                msg = (f"elastic reset limit {self.reset_limit} reached; "
                       "stopping job (reference RESET_LIMIT_EXCEEDED)")
                log.error(msg)
                self.stop(error_message=msg)
                return True
            return False
        # capacity
        if self.hosts.total_slots() < self.min_np:
            log.warning("host change leaves fewer than min_np slots; "
                        "waiting for capacity")
            return True
        return False

    def _expire_identity(self, identity: str) -> None:
        """Execute one STEP_EXPIRE: drop a dead-leased identity so the
        missing-workers path advances the epoch this tick."""
        log.warning("worker %s lease expired (no renewal in %.0fs "
                    "with the store reachable); declaring dead",
                    identity, self.lease_timeout)
        with self._lock:
            self._known_identities.pop(identity, None)
            self._lease_seen.pop(identity, None)

    def _blacklist_for_demotion(self, host: str,
                                rep: Dict[str, object]) -> None:
        """Execute one STEP_BLACKLIST: shed the demoted host and record
        the evidence (idempotent per (reporter, epoch, rank) — repeated
        reports still drive the advance but stack no cooldown strike and
        re-count no metrics)."""
        rank = rep["rank"]
        evidence = (f"rank {rank} readiness-lag EWMA {rep.get('ewma')}s "
                    f"over demote threshold {rep.get('threshold')}s for "
                    f"{rep.get('cycles')} consecutive busy cycles")
        new_strike = self.hosts.blacklist(host, evidence=evidence)
        key = (str(rep.get("reporter")), self.epoch, rank)
        if key not in self._demotion_seen:
            self._demotion_seen.add(key)
            metrics.inc("straggler_demotions_total",
                        rank=str(rank), host=host)
            posted = rep.get("posted_unix")
            if isinstance(posted, (int, float)):
                # Wall-clock across processes (coordinator vs driver):
                # same-host skew is negligible against the multi-tick
                # latencies this histogram bounds.
                metrics.observe("demotion_latency_seconds",
                                max(0.0, time.time() - posted))
            flight_recorder.record(
                "demotion", epoch=self.epoch, rank=rank, host=host,
                ewma=rep.get("ewma"), new_strike=new_strike,
                reporter=rep.get("reporter"))
            log.warning("demoting host %s: %s", host, evidence)

    def _reshard_commit_probe(self) -> None:
        """Drive one commit-probe of the pending reshard (kernel:
        :func:`reshard_commit_steps`) against the live store.  Commit ⇒
        observe ``reshard_seconds`` (marker publish → survivor-acked
        commit), count the extra ``cause=reshard`` transition sample, and
        flight-record it; still-missing acks just carry to the next tick
        (an epoch advance meanwhile is the fallback path).  Store errors
        propagate to the tick's partitioned-mode handler."""
        pending = self._reshard_pending
        if pending is None:
            return
        res = self._drive_txn_steps(reshard_commit_steps(
            pending["epoch"], pending["survivors"]))
        pending["missing"] = res["missing"]
        if not res["committed"]:
            return
        self._reshard_pending = None
        elapsed = (time.monotonic_ns() - pending["published_ns"]) / 1e9
        metrics.observe("reshard_seconds", elapsed)
        metrics.inc("driver_epoch_transitions_total", cause="reshard")
        flight_recorder.record(
            "reshard_commit", epoch=pending["epoch"],
            survivors=pending["survivors"], seconds=round(elapsed, 6))
        log.info("reshard committed at epoch %d (%d survivors, %.3fs "
                 "publish-to-commit)", pending["epoch"],
                 len(pending["survivors"]), elapsed)

    def _tick_store_reads(self) -> Dict[str, Optional[Dict[str, object]]]:
        """Coalesce this tick's store reads into ONE batched round-trip.

        The pre-batching tick issued ``keys + 2–3 ops per identity``
        sequentially — at np=64 that is ~81% of a churn event's latency
        (``benchmarks/results/controller_churn_np64.json``, r14).  A
        single ``/batch`` carries the renotify ack reads, the
        reset-request reads, and the lease reads; a get of an absent key
        returns None, which each consumer already treats as "not
        present", so the old keys-then-intersect dance is unnecessary.
        Raises the store error on outage, like every other tick op."""
        with self._lock:
            slot_ids = sorted({f"{s.hostname}:{s.local_rank}"
                               for s in self._slots})
            removed = set(self._removed_identities)
            exited = set(self._exited_identities)
            await_ack = self._await_ack
        return self._drive_txn_steps(tick_read_steps(
            self.epoch, await_ack, slot_ids, removed, exited))

    def _drive_txn_steps(self, steps):
        """Execute a kernel generator whose only step kind is STEP_TXN,
        answering each with one batched store round-trip.  Store errors
        propagate to the caller (the tick's partitioned-mode handler)."""
        resp = None
        while True:
            try:
                step = steps.send(resp)
            except StopIteration as fin:
                return fin.value
            assert step[0] == STEP_TXN, step
            resp = self.rendezvous.batch(list(step[1]))

    def _push_driver_metrics(self) -> None:
        """External-server deployments only: the driver's gauges and
        counters live in the launcher process, which the (remote) server's
        ``GET /metrics`` cannot see — push an epoch-stamped snapshot under
        the reserved ``driver`` key, like a worker does.  The in-process
        server snapshots this same registry directly; pushing there too
        would double-count every series."""
        if not metrics.ENABLED or \
                not isinstance(self.rendezvous, ExternalRendezvous):
            return
        snap = metrics.registry.snapshot()
        snap["rank"] = "driver"
        snap["epoch"] = self.epoch
        self.rendezvous.set(metrics.METRICS_SCOPE, "driver",
                            json.dumps(snap).encode())

    @staticmethod
    def _parse_demotion_reports(
            raws: Optional[Dict[str, object]],
            epoch: int) -> List[Dict[str, object]]:
        """Thin delegate kept for callers/tests; the logic lives in the
        module-level :func:`parse_demotion_reports` so the judgment
        kernel and the checker share it."""
        return parse_demotion_reports(raws, epoch)

    # -- lease liveness / store outage (docs/control_plane.md) ---------

    def _store_outage(self, err: Exception) -> None:
        if self._store_outage_since is None:
            self._store_outage_since = time.monotonic()
            log.warning("rendezvous store unreachable (%s); entering "
                        "partitioned mode — no membership changes until "
                        "it returns", err)

    def _store_recovered(self) -> None:
        if self._store_outage_since is None:
            return
        outage = time.monotonic() - self._store_outage_since
        self._store_outage_since = None
        # Workers could not renew through the outage (their pushes go to
        # the same store); restart the judgment clock so a restarted
        # server's replayed leases don't read as instantly expired.  The
        # re-grace decision is the kernel's (checked: regrace_dropped).
        steps = outage_recovery_steps(self.lease_timeout)
        resp = None
        while True:
            try:
                step = steps.send(resp)
            except StopIteration:
                break
            resp = None
            if step[0] == STEP_CLOCK:
                resp = time.monotonic()
            elif step[0] == STEP_GRACE:
                self._lease_grace_until = step[1]
        log.info("rendezvous store reachable again after %.1fs outage; "
                 "lease clocks re-graced for %.0fs", outage,
                 self.lease_timeout)

    def recover_from_store(self) -> bool:
        """Driver crash-recovery: re-adopt a previous incarnation's state
        from a (journaled) store before :meth:`start`.

        Restores the epoch and seeds ``_known_identities`` from the
        leases of workers whose slot entry holds a rank at that epoch, so
        ``start()`` republishes the SAME epoch and spawns only identities
        with no surviving worker — instead of resetting to epoch 0 and
        respawning the world.  Returns True when prior state was found.

        The adoption judgment (which epoch, which identities) is the
        kernel's :func:`recover_steps` — the checker proves the adopted
        epoch equals the journal-replayed one exactly."""
        try:
            steps = recover_steps(self.lease_timeout)
            resp = None
            while True:
                try:
                    step = steps.send(resp)
                except StopIteration as fin:
                    recovered = fin.value
                    break
                resp = None
                if step[0] == STEP_TXN:
                    resp = self.rendezvous.batch(list(step[1]))
                elif step[0] == STEP_CLOCK:
                    resp = time.monotonic()
                elif step[0] == STEP_GRACE:
                    self._lease_grace_until = step[1]
        except (self._STORE_ERRORS, ValueError) as e:
            log.warning("driver state recovery failed (%s); starting "
                        "fresh at epoch 0", e)
            self._recovered_epoch = None
            return False
        if recovered is None:
            self._recovered_epoch = None
            return False
        self.epoch = recovered["epoch"]
        self._recovered_epoch = recovered["epoch"]
        now = time.monotonic()
        for identity, (slot, lease) in recovered["adopted"].items():
            info = SlotInfo(
                hostname=slot["hostname"], rank=slot["rank"],
                local_rank=slot["local_rank"],
                cross_rank=slot["cross_rank"], size=slot["size"],
                local_size=slot["local_size"],
                cross_size=slot["cross_size"])
            with self._lock:
                self._known_identities[identity] = info
                self._lease_seen[identity] = (lease, now)
        log.info("recovered driver state from store: epoch %d, re-adopted "
                 "live workers %s", self.epoch,
                 sorted(recovered["adopted"]) or "(none)")
        return True

    # ------------------------------------------------------------------

    def _renotify_unacked(
            self, acks: Optional[Dict[str, object]] = None) -> None:
        """Notification is racy against worker startup (a worker may
        register its endpoint just after a change fired).  Until every
        current identity acks the epoch, keep pinging the UNACKED ones each
        tick (pinging acked workers too would feed them stale interrupts).
        ``acks`` is the tick's batched prefetch (identity -> raw ack);
        None falls back to per-identity reads."""
        if self._await_ack is None or self.epoch == 0:
            return
        if acks is None:
            with self._lock:
                identities = {f"{s.hostname}:{s.local_rank}"
                              for s in self._slots}
                # Removed identities need the ping too (it is what makes
                # their worker see rank −1 and exit promptly); they ack
                # before exiting.  Identities whose process exited have
                # nobody listening.
                identities.update(self._removed_identities)
                identities -= self._exited_identities
            acks = {identity: self.rendezvous.get(EPOCH_ACK_SCOPE, identity)
                    for identity in identities}
        unacked = set()
        for identity, raw in acks.items():
            if raw is None or int(raw.decode()) < self.epoch:
                unacked.add(identity)
        if not unacked:
            self._await_ack = None
            return
        self._notify_workers(added_only=self._await_ack, identities=unacked,
                             reshard=self._reshard_pending is not None)

    def record_worker_exit(self, slot: SlotInfo, exit_code: int) -> None:
        """Called by the launcher's process monitor (reference
        ``_handle_worker_exit``, ``driver.py:292-308``).

        Crash exits (kill/segv/user error) count toward a low blacklist
        threshold; ``TRANSIENT_EXIT_CODE`` exits (worker gave up
        re-initializing, usually because a peer died first) toward a higher
        one — the survivor of someone else's crash must not poison its own
        host (VERDICT round 1 weak #1)."""
        if self._shutdown.is_set():
            return
        identity = f"{slot.hostname}:{slot.local_rank}"
        if exit_code == 0:
            self._registry.record_success(slot.rank)
            with self._lock:
                self._exited_identities.add(identity)
                self._success = True
                # A clean exit clears the host's record: sporadic transient
                # strikes spread over a long job must not accumulate into a
                # blacklist of a healthy host.
                self._crash_failures.pop(slot.hostname, None)
                self._transient_failures.pop(slot.hostname, None)
            return
        self._registry.record_failure(slot.rank)
        transient = exit_code == TRANSIENT_EXIT_CODE
        with self._lock:
            self._exited_identities.add(identity)
            counters = self._transient_failures if transient \
                else self._crash_failures
            counters[slot.hostname] += 1
            strikes = counters[slot.hostname]
            limit = self.transient_failure_limit if transient \
                else self.crash_failure_limit
            if strikes >= limit:
                self.hosts.blacklist(slot.hostname)
            else:
                log.warning("worker %s exited %d (%s, strike %d/%d); host "
                            "stays eligible", identity, exit_code,
                            "transient" if transient else "crash",
                            strikes, limit)
            self._known_identities.pop(identity, None)
        self._wakeup.set()

    @property
    def current_slots(self) -> List[SlotInfo]:
        with self._lock:
            return list(self._slots)
