"""Driver↔worker notification channel.

Reference: ``runner/elastic/worker.py:1-110`` — each worker runs a tiny
notification server; the driver pings it when discovery sees a host-set
change, and the worker surfaces that as ``HostsUpdatedInterrupt`` at its
next ``state.commit()``.  Ours is a threaded HTTP server whose address is
registered in the rendezvous ``workers`` scope.
"""

from __future__ import annotations

import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..common import env as env_mod
from ..common.logging_util import get_logger
from ..transport.scopes import WORKERS_SCOPE  # noqa: F401  (re-export)
from ..transport.store import Store

log = get_logger("horovod_tpu.elastic.worker")


class _NotifyHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def do_POST(self):
        from urllib.parse import parse_qs, urlparse

        from ..common import secret as secret_mod
        from .state import notify_hosts_updated

        secret = secret_mod.job_secret()
        if secret is not None and not secret_mod.verify(
                secret, self.command, self.path, b"",
                self.headers.get(secret_mod.SIG_HEADER)):
            self.send_error(403, "bad or missing request signature")
            return

        parsed = urlparse(self.path)
        added_only = parsed.path.rstrip("/").endswith("added")
        query = parse_qs(parsed.query)
        epoch_vals = query.get("epoch")
        epoch = int(epoch_vals[0]) if epoch_vals else None
        reshard_vals = query.get("reshard")
        notify_hosts_updated(added_only=added_only, epoch=epoch)
        if reshard_vals and reshard_vals[0] == "1":
            # Zero-restart reshard ping: abort in-flight collectives NOW
            # so a survivor blocked on a SIGKILL'd peer re-rendezvouses
            # within one poll quantum instead of riding out the TCP
            # progress deadline.  Epoch-filtered inside (stale pings are
            # the round-1 livelock); best-effort by contract.
            from ..core.state import abort_for_reshard

            abort_for_reshard(epoch)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


def start_notification_service(store: Optional[Store] = None) -> int:
    """Start the worker's notify server and register its port; returns the
    bound port (0 when no rendezvous is configured — single-process runs)."""
    server = ThreadingHTTPServer(("0.0.0.0", 0), _NotifyHandler)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="hvd-elastic-notify").start()
    port = server.server_address[1]

    if store is None:
        from .rendezvous_client import store_client

        store = store_client()
        if store is None:
            return 0
    identity = (f"{env_mod.get_str(env_mod.HOROVOD_HOSTNAME) or 'localhost'}:"
                f"{env_mod.get_int(env_mod.HOROVOD_LOCAL_RANK, 0)}")
    from ..transport.tcp import _default_advertise_addr

    try:
        store.set(WORKERS_SCOPE, identity,
                  f"{_default_advertise_addr()}:{port}".encode())
    except OSError as e:
        # Store mid-restart: registration is best-effort — a journaled
        # server replays a PREVIOUS registration of this identity (same
        # address, new ephemeral port is the loss), and the driver's
        # re-notify loop logs the identity as unregistered rather than
        # failing the worker's init over an observability channel.
        log.warning("worker notify-address registration failed (store "
                    "unreachable: %s); driver pings may miss this "
                    "worker until re-registration", e)
    return port


class WorkerNotificationClient:
    """Driver side: ping registered workers about host changes."""

    def __init__(self, addresses: List[str]):
        self._addresses = addresses

    def notify_hosts_updated(self, added_only: bool,
                             epoch: Optional[int] = None,
                             reshard: bool = False) -> None:
        suffix = "added" if added_only else "changed"
        query = f"?epoch={epoch}" if epoch is not None else ""
        if reshard:
            query += ("&" if query else "?") + "reshard=1"
        from ..common import secret as secret_mod

        secret = secret_mod.job_secret()
        for addr in self._addresses:
            try:
                path = f"/notify/{suffix}{query}"
                req = urllib.request.Request(
                    f"http://{addr}{path}", data=b"", method="POST")
                if secret is not None:
                    req.add_header(secret_mod.SIG_HEADER,
                                   secret_mod.sign(secret, "POST", path, b""))
                with urllib.request.urlopen(req, timeout=5):
                    pass
            except OSError as e:
                log.debug("worker notify %s failed: %s", addr, e)
