"""Elastic (fault-tolerant, autoscaling) training.

Reference: the three cooperating pieces of SURVEY §5.3 —

- **worker side** (:mod:`.state`): ``State`` objects with
  commit/restore/sync, the ``@hvd.elastic.run`` wrapper that retries the
  training function across membership changes
  (``common/elastic.py:147-168``);
- **driver side** (:mod:`.driver`, :mod:`.discovery`,
  :mod:`.registration`): discovery-script polling, host diff + blacklist,
  stable rank reassignment, worker lifecycle counting
  (``runner/elastic/driver.py``, ``discovery.py``, ``registration.py``);
- **notification channel** (:mod:`.worker`): driver→worker host-change
  pings (``runner/elastic/worker.py``).

TPU deployment note: the discovery script is where pod-slice preemption
notices surface — a script that lists healthy TPU-VM workers makes
preemption behave exactly like the reference's host-removal flow.
"""

from .state import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
    JaxState,
    ObjectState,
    State,
    run,
)
