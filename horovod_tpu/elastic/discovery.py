"""Host discovery + host-set management for elastic jobs.

Reference: ``runner/elastic/discovery.py:1-164`` — ``HostDiscoveryScript``
shells out to the user-provided script (one ``host[:slots]`` per line) and
``HostManager`` diffs successive host sets, maintains the blacklist, and
orders hosts stably so surviving hosts keep their relative rank order
across updates (``discovery.py:114-122``).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import env as env_mod
from ..common.logging_util import get_logger
from ..runner.hosts import HostInfo, parse_hosts

log = get_logger("horovod_tpu.elastic.discovery")


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """{hostname: slots} of currently healthy hosts."""
        raise NotImplementedError


class FixedHosts(HostDiscovery):
    def __init__(self, hosts: List[HostInfo]):
        self._hosts = {h.hostname: h.slots for h in hosts}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; output = one ``host`` or ``host:slots`` per
    line (reference ``discovery.py:130-163``).  On TPU deployments the
    script typically lists non-preempted TPU-VM workers."""

    def __init__(self, script: str, default_slots: int = 1):
        self._script = script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(self._script, shell=True, text=True,
                                      timeout=30)
        hosts: Dict[str, int] = {}
        for part in out.splitlines():
            part = part.strip()
            if not part:
                continue
            info = HostInfo.from_string(
                part if ":" in part else f"{part}:{self._default_slots}")
            hosts[info.hostname] = info.slots
        return hosts


class HostManager:
    """Tracks the current host set, stable ordering, and the blacklist
    (reference ``discovery.py:79-121``).

    The blacklist supports a COOLDOWN (``HOROVOD_BLACKLIST_COOLDOWN_SECS``
    or the constructor arg; 0 = permanent, the reference behavior): on a
    preemptible TPU-VM fleet a host is usually "bad" only transiently —
    preempted, rebooting, migrating — and a permanent blacklist shrinks
    the pool monotonically until the job starves below min_np.  After the
    cooldown the host rejoins the candidate pool; if it fails again a
    FRESH strike restarts the clock.  Strikes are idempotent while
    active: re-blacklisting an already-listed host (repeated demotion
    reports within one epoch, a crash racing a demotion) keeps the
    original expiry, so strikes never stack into a de-facto permanent
    ban."""

    def __init__(self, discovery: HostDiscovery,
                 blacklist_cooldown: Optional[float] = None):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._order: List[str] = []       # stable rank order
        self._slots: Dict[str, int] = {}
        # hostname -> expiry (monotonic seconds; inf = permanent)
        self._blacklist: Dict[str, float] = {}
        self._cooldown = env_mod.get_float(
            env_mod.HOROVOD_BLACKLIST_COOLDOWN_SECS, 0.0) \
            if blacklist_cooldown is None else blacklist_cooldown

    @staticmethod
    def _now() -> float:
        return time.monotonic()

    def blacklist(self, hostname: str,
                  evidence: Optional[str] = None) -> bool:
        """Blacklist ``hostname``; True on a NEW strike, False when the
        host was already listed (the existing expiry is kept — see the
        class docstring).  ``evidence`` (e.g. the straggler EWMA behind a
        demotion) is logged with the strike so the driver log and the
        flight recorder agree on *why* the host was shed."""
        expiry = self._now() + self._cooldown \
            if self._cooldown > 0 else float("inf")
        with self._lock:
            self._expire_blacklist_locked()
            if hostname in self._blacklist:
                log.debug("host %s already blacklisted; strike not stacked",
                          hostname)
                return False
            log.warning(
                "blacklisting host %s%s%s", hostname,
                f" for {self._cooldown:g}s" if self._cooldown > 0
                else " permanently",
                f" (evidence: {evidence})" if evidence else "")
            self._blacklist[hostname] = expiry
            return True

    def _expire_blacklist_locked(self) -> None:
        now = self._now()
        for host in [h for h, exp in self._blacklist.items() if exp <= now]:
            log.warning("blacklist cooldown expired for host %s; it may "
                        "rejoin the pool", host)
            del self._blacklist[host]

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            self._expire_blacklist_locked()
            return hostname in self._blacklist

    @property
    def current_hosts(self) -> List[HostInfo]:
        with self._lock:
            return [HostInfo(h, self._slots[h]) for h in self._order]

    def update_available_hosts(self) -> Tuple[bool, bool]:
        """Polls discovery; returns (changed, removal_or_failure).

        Ordering rule: surviving hosts keep their existing positions, new
        hosts append — rank assignments stay stable across growth."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            self._expire_blacklist_locked()
            found = {h: s for h, s in found.items()
                     if h not in self._blacklist}
            removed = [h for h in self._order if h not in found]
            added = [h for h in found if h not in self._slots]
            slots_changed = any(
                h in self._slots and self._slots[h] != s
                for h, s in found.items())
            changed = bool(removed or added or slots_changed)
            new_order = [h for h in self._order if h in found]
            new_order.extend(h for h in found if h not in new_order)
            self._order = new_order
            self._slots = found
            return changed, bool(removed or slots_changed)

    def total_slots(self) -> int:
        with self._lock:
            return sum(self._slots.values())
