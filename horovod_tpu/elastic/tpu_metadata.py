"""TPU-preemption host discovery via GCE metadata notices.

Reference analog: the pluggable ``HostDiscovery`` family
(``/root/reference/horovod/runner/elastic/discovery.py:130-163``), which on
GPU clusters is a user script listing healthy hosts.  SURVEY §5.3 maps that
to "TPU pod-slice health/preemption notices": on GCE, a preemptible TPU VM
learns about its own termination through the instance metadata server —
``instance/preempted`` flips to ``TRUE`` and ``instance/maintenance-event``
announces host maintenance ~60 s ahead.  This module makes those notices a
first-class discovery source, so elastic jobs on preemptible TPU VMs
(BASELINE config #5) need no hand-written discovery script.

Two pieces:

- :class:`TpuMetadataDiscovery` — driver-side.  Polls, for every candidate
  host, ``{base}/preempted`` and ``{base}/maintenance-event`` and reports
  the hosts that are neither preempted nor scheduled for termination.  The
  URL is a template with a ``{host}`` placeholder: the GCE metadata server
  (``metadata.google.internal``) is only reachable from the VM it
  describes, so the default template points at the per-host relay below.
  Tests and non-GCE deployments point it anywhere
  (``HOROVOD_TPU_METADATA_URL``).

- :func:`serve_metadata_relay` — worker-side.  A tiny HTTP server each TPU
  VM runs (``python -m horovod_tpu.elastic.tpu_metadata``) that proxies
  GET requests to its local metadata server with the required
  ``Metadata-Flavor: Google`` header.  Run it from the VM startup script
  alongside the worker.

Wiring: ``hvdrun --host-discovery tpu-metadata -H host1:8,host2:8 ...``
(the host list is the slice's full membership; discovery decides, per
poll, which of them are currently healthy).
"""

from __future__ import annotations

import threading
import urllib.error
import urllib.request
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..common import env as env_mod
from ..common.logging_util import get_logger
from ..runner.hosts import HostInfo
from .discovery import HostDiscovery

log = get_logger("horovod_tpu.elastic.tpu_metadata")

#: Port the per-host relay serves on (driver polls ``http://host:PORT``).
DEFAULT_RELAY_PORT = 8677

DEFAULT_URL_TEMPLATE = (
    "http://{host}:%d/computeMetadata/v1/instance" % DEFAULT_RELAY_PORT)

#: ``maintenance-event`` values that mean "this host is going away".
#: (``MIGRATE_ON_HOST_MAINTENANCE`` live-migrates without a restart and is
#: not a removal signal.)
_TERMINAL_EVENTS = ("TERMINATE",)


def _get(url: str, timeout: float) -> str:
    req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


class TpuMetadataDiscovery(HostDiscovery):
    """Reports the subset of ``hosts`` not flagged by preemption notices.

    Host states per poll:

    - **ok** — reachable, ``preempted`` ≠ TRUE, no terminal maintenance
      event → listed.
    - **preempted / terminating** — dropped immediately (GCE gives ~30-60 s
      of notice; the sooner the epoch turns, the less work is lost).
    - **unreachable** (timeout / no route) — kept for ``unreachable_grace``
      consecutive failed polls, then dropped.  A preempted VM usually
      stops answering before (or instead of) flipping the flag, so
      unreachability IS the common preemption signal — but a single
      dropped packet must not churn the membership.
    - **relay-down** (connection refused) — kept indefinitely.  A refused
      connection means the host's TCP stack answered with a RST: the VM
      is alive, only the relay process on it has died.  Evicting a
      healthy worker because its *monitoring* plane crashed would shrink
      the job on a non-failure; instead the host stays in the membership
      and the condition is logged (supervise the relay — see
      ``docs/elastic.md``).
    """

    def __init__(self, hosts: List[HostInfo],
                 url_template: Optional[str] = None,
                 timeout: float = 2.0,
                 unreachable_grace: int = 3,
                 max_pollers: int = 16):
        self._hosts = {h.hostname: h.slots for h in hosts}
        self._url = (url_template
                     or env_mod.get_str(env_mod.HOROVOD_TPU_METADATA_URL)
                     or DEFAULT_URL_TEMPLATE)
        if "{host}" not in self._url:
            raise ValueError(
                "tpu-metadata URL template must contain '{host}' "
                f"(got {self._url!r})")
        self._timeout = timeout
        self._grace = unreachable_grace
        self._fail_counts: Dict[str, int] = defaultdict(int)
        self._relay_down_counts: Dict[str, int] = defaultdict(int)
        self._pool = ThreadPoolExecutor(
            max_workers=min(max_pollers, max(1, len(hosts))),
            thread_name_prefix="tpu-metadata-poll")
        self._lock = threading.Lock()

    # -- per-host probe -------------------------------------------------

    @staticmethod
    def _is_refused(exc: BaseException) -> bool:
        """True when the failure is a TCP connection refusal — the host's
        network stack actively answered (RST), so the VM is alive and only
        the relay endpoint is closed.  Timeouts and no-route errors give
        no such liveness evidence and stay 'unreachable'."""
        e, seen = exc, set()
        while isinstance(e, BaseException) and id(e) not in seen:
            seen.add(id(e))
            if isinstance(e, ConnectionRefusedError):
                return True
            # URLError wraps the socket error in .reason, not __cause__.
            e = e.reason if isinstance(e, urllib.error.URLError) \
                else e.__cause__
        return False

    def _host_state(self, host: str) -> str:
        base = self._url.format(host=host)
        try:
            if _get(f"{base}/preempted",
                    self._timeout).strip().upper() == "TRUE":
                return "preempted"
            event = _get(f"{base}/maintenance-event",
                         self._timeout).strip().upper()
            if event.startswith(_TERMINAL_EVENTS):
                return "terminating"
            return "ok"
        except urllib.error.HTTPError as e:
            # An HTTP status (relay 502: its local metadata fetch failed;
            # any 5xx) is a live HTTP server answering from the host —
            # even stronger liveness evidence than a RST.  The monitoring
            # plane is degraded, the host is not.
            log.debug("metadata relay on %s answered HTTP %s: %s",
                      host, e.code, e)
            return "relay-down"
        except (urllib.error.URLError, OSError, ValueError) as e:
            if self._is_refused(e):
                log.debug("metadata relay on %s refused connection: %s",
                          host, e)
                return "relay-down"
            log.debug("metadata poll for %s failed: %s", host, e)
            return "unreachable"

    # -- HostDiscovery --------------------------------------------------

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        with self._lock:
            hosts = list(self._hosts.items())
            states = dict(zip(
                (h for h, _ in hosts),
                self._pool.map(self._host_state, (h for h, _ in hosts))))
            available: Dict[str, int] = {}
            for host, slots in hosts:
                state = states[host]
                if state == "unreachable":
                    self._fail_counts[host] += 1
                    # Kept for `grace` consecutive failed polls, dropped
                    # on the (grace+1)-th.
                    if self._fail_counts[host] <= self._grace:
                        available[host] = slots   # grace period
                    elif self._fail_counts[host] == self._grace + 1:
                        log.warning(
                            "host %s unreachable for %d polls; treating "
                            "as gone", host, self._fail_counts[host])
                    continue
                self._fail_counts[host] = 0
                if state == "relay-down":
                    # Host alive (TCP RST came back), monitoring relay
                    # dead: never evict on a monitoring-plane failure —
                    # keep the host, nag periodically so someone restarts
                    # the relay (it should run supervised; docs/elastic.md).
                    self._relay_down_counts[host] += 1
                    if self._relay_down_counts[host] % 10 == 1:
                        log.warning(
                            "host %s is reachable but its metadata relay "
                            "refuses connections (%d consecutive polls); "
                            "keeping the host — preemption notices from it "
                            "are BLIND until the relay is restarted",
                            host, self._relay_down_counts[host])
                    available[host] = slots
                    continue
                self._relay_down_counts[host] = 0
                if state == "ok":
                    available[host] = slots
                else:
                    log.warning("host %s reports %s; removing from the "
                                "membership", host, state)
            return available


# ---------------------------------------------------------------------------
# Worker-side relay


def serve_metadata_relay(port: int = DEFAULT_RELAY_PORT,
                         metadata_base: str =
                         "http://metadata.google.internal",
                         bind: str = "0.0.0.0",
                         block: bool = True):
    """Serve this VM's metadata to the elastic driver.

    Forwards ``GET`` requests for exactly the two health keys the driver
    polls — ``instance/preempted`` and ``instance/maintenance-event`` — to
    the VM-local metadata server (adding the mandatory ``Metadata-Flavor:
    Google`` header) and returns the body verbatim.  Nothing else is
    relayed: the metadata tree also serves the VM's service-account
    tokens and SSH keys, and this is a health relay reachable from the
    whole VPC, not an open proxy.

    Returns the ``HTTPServer`` (already serving on a daemon thread when
    ``block=False``).
    """
    import http.server

    allowed = {
        "/computeMetadata/v1/instance/preempted",
        "/computeMetadata/v1/instance/maintenance-event",
    }

    class _Relay(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.split("?", 1)[0] not in allowed:
                self.send_error(
                    404, "only preempted/maintenance-event are relayed")
                return
            try:
                body = _get(metadata_base + self.path, timeout=2.0).encode()
            except (urllib.error.URLError, OSError) as e:
                self.send_error(502, f"metadata fetch failed: {e}")
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # noqa: A003
            log.debug("relay: " + fmt, *args)

    server = http.server.ThreadingHTTPServer((bind, port), _Relay)
    if block:
        log.info("serving metadata relay on %s:%d", bind, port)
        server.serve_forever()
    else:
        threading.Thread(target=server.serve_forever,
                         name="tpu-metadata-relay", daemon=True).start()
    return server


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(
        description="Relay this VM's GCE metadata to the elastic driver")
    ap.add_argument("--port", type=int, default=DEFAULT_RELAY_PORT)
    ap.add_argument("--metadata-base",
                    default="http://metadata.google.internal")
    ns = ap.parse_args()
    serve_metadata_relay(port=ns.port, metadata_base=ns.metadata_base)
