"""Worker-side elastic state + the ``@hvd.elastic.run`` wrapper.

Reference: ``common/elastic.py:1-168`` (``State``/``ObjectState``/``run_fn``)
and ``torch/elastic/state.py:27-178`` (handler-based ``TorchState``).  The
contract:

- ``state.commit()`` — snapshot to host memory + raise
  ``HostsUpdatedInterrupt`` if the driver notified us of membership change;
- ``HorovodInternalError`` (collective failed: peer died) → roll back to
  the last commit, re-rendezvous, retry;
- ``HostsUpdatedInterrupt`` (graceful change) → keep state, re-rendezvous,
  retry;
- after every reset the coordinator broadcasts its state so new/restored
  workers agree (``state.sync()``).

``JaxState`` snapshots pytrees (params/opt_state/any arrays) by copying to
host numpy — cheap, and exactly the commit/rollback semantics the
reference implements with ``deepcopy`` of torch state dicts.
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Callable, Dict, List, Optional

from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt

_host_update_lock = threading.Lock()
_host_update_event = threading.Event()
_host_update_skip_sync = [True]
_host_update_epoch = [-1.0]  # highest epoch seen; inf for epoch-less pings


def notify_hosts_updated(added_only: bool = False,
                         epoch: Optional[int] = None) -> None:
    """Called by the worker notification service when the driver reports a
    host-set change; surfaces at the next ``commit()``/``check`` point.

    ``epoch`` is the driver's epoch at ping time.  Staleness is judged at
    CONSUME time (a ping can arrive before the worker re-rendezvouses into
    the very epoch it advertises — acting on it afterwards would strand the
    worker waiting for an epoch that never comes, the round-1 failure)."""
    with _host_update_lock:
        _host_update_skip_sync[0] = _host_update_skip_sync[0] and added_only
        _host_update_epoch[0] = max(
            _host_update_epoch[0], float("inf") if epoch is None else epoch)
        _host_update_event.set()


def _consume_host_update() -> Optional[bool]:
    from ..common import env as env_mod

    with _host_update_lock:
        if not _host_update_event.is_set():
            return None
        _host_update_event.clear()
        skip = _host_update_skip_sync[0]
        _host_update_skip_sync[0] = True
        epoch = _host_update_epoch[0]
        _host_update_epoch[0] = -1.0
    if epoch <= env_mod.get_epoch():
        return None  # stale: we already adopted this (or a newer) epoch
    return skip


class State:
    """Base elastic state (reference ``common/elastic.py:24-100``)."""

    def __init__(self, **kwargs):
        self._reset_callbacks: List[Callable[[], None]] = []

    def register_reset_callbacks(self, callbacks: List[Callable[[], None]]) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def commit(self) -> None:
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        skip = _consume_host_update()
        if skip is not None:
            raise HostsUpdatedInterrupt(skip_sync=skip)

    # subclass responsibilities -----------------------------------------
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self, root_rank: int = 0) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """Arbitrary picklable attributes, synced by coordinator broadcast
    (reference ``common/elastic.py:103-144``)."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._known = list(kwargs.keys())
        self.save()

    def save(self) -> None:
        self._saved = {k: copy.deepcopy(getattr(self, k)) for k in self._known}

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self, root_rank: int = 0) -> None:
        from ..frameworks.jax.functions import broadcast_object

        values = {k: getattr(self, k) for k in self._known}
        synced = broadcast_object(values, root_rank=root_rank,
                                  name="elastic.objstate")
        # Adopt the ROOT's attribute set, not just its values: a joiner
        # whose constructor defaults differ from the coordinator's
        # evolved set (attributes added/dropped across restarts) must
        # track exactly what the root tracks, or its next save/restore
        # cycle snapshots keys nobody else agrees on.
        self._known = list(synced.keys())
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


class JaxState(ObjectState):
    """Pytree-aware elastic state: array leaves snapshot to host numpy and
    sync via per-leaf broadcast (cheaper + dtype-exact vs pickling)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def _trees(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._known}

    def save(self) -> None:
        import jax
        import numpy as np

        def snap(x):
            if hasattr(x, "device") or hasattr(x, "sharding"):
                return np.asarray(jax.device_get(x))
            return copy.deepcopy(x)

        self._saved = {
            k: jax.tree_util.tree_map(snap, v) for k, v in self._trees().items()
        }

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self, root_rank: int = 0) -> None:
        import jax

        from ..frameworks.jax.functions import broadcast_parameters

        for k in self._known:
            tree = getattr(self, k)
            leaves = jax.tree_util.tree_leaves(tree)
            if leaves and all(hasattr(l, "shape") for l in leaves):
                setattr(self, k, broadcast_parameters(
                    tree, root_rank=root_rank))
            else:
                from ..frameworks.jax.functions import broadcast_object

                setattr(self, k, broadcast_object(
                    tree, root_rank=root_rank, name=f"elastic.sync.{k}"))
        self.save()


def _reset_and_reinit() -> None:
    """Full runtime teardown + re-init from the (possibly new) rendezvous
    assignment — the analog of the reference's shutdown/re-init reset path
    (``tensorflow/elastic.py:64-67`` + ``gloo_context.cc:154-189``)."""
    from ..core import state as core_state
    from ..frameworks.jax import basics

    basics._internal_reset()
    from .rendezvous_client import refresh_topology_from_rendezvous

    topo = refresh_topology_from_rendezvous()
    _reinit_xla_plane(topo)
    core_state.global_state().initialize(topology=topo)


def _reinit_xla_plane(topo) -> None:
    """Re-establish the XLA data plane for the NEW world (the part SURVEY
    §7.4 flags as hard; reference analog: the Gloo elastic re-rendezvous
    branch, ``gloo_context.cc:154-189``).

    jax refuses ``distributed.initialize`` once backends exist, so the
    sequence is: shut the old multi-controller runtime down, drop the
    backend singletons (old-world device arrays become invalid — elastic
    state lives in host numpy snapshots, so nothing live depends on them),
    then bring the runtime up against a coordinator for THIS epoch.  The
    new rank 0 binds a free port and publishes ``host:port`` to the
    rendezvous store under an epoch-scoped key; everyone else polls it.
    """
    import os

    from ..backend import xla as xla_backend
    from ..common import env as env_mod

    plane = xla_backend.data_plane_requested()
    if plane not in ("xla", "auto"):
        return
    xla_backend.context().reset()
    import jax

    # Tear the OLD world's runtime down whenever one exists — including a
    # shrink to size 1, where a leftover distributed client would keep
    # heartbeating a coordinator that may live on the dead host.
    if xla_backend.jax_distributed_initialized():
        from jax._src import xla_bridge

        jax.distributed.shutdown()
        jax.clear_caches()
        try:
            # Supported path first (also invalidates pjit/device caches);
            # fall back to the private bridge hook on jax versions where
            # jax.extend lacks it.
            import jax.extend.backend

            jax.extend.backend.clear_backends()
        except (ImportError, AttributeError):
            xla_bridge._clear_backends()
    elif plane != "xla":
        return  # auto mode never had a device plane; keep TCP

    if topo.size <= 1:
        return  # single survivor: local mesh only, no distributed runtime

    # Epoch-scoped coordinator handoff (the old coordinator host may be
    # the one that died).
    coord = negotiate_jax_coordinator(topo)
    os.environ[env_mod.HOROVOD_JAX_COORDINATOR] = coord
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=topo.size,
                               process_id=topo.rank)
    # Verify the NEW world actually took: a stale backend surviving the
    # clear (or a straggler thread rebuilding it mid-teardown) would
    # otherwise poison every later jax call with the OLD topology and
    # surface as a confusing mismatch deep inside core init.  Fail fast
    # and specific here instead; the run wrapper's retry tears down again.
    if jax.process_count() != topo.size or \
            jax.process_index() != topo.rank:
        raise HorovodInternalError(
            f"jax.distributed re-init did not take: jax reports "
            f"{jax.process_index()}/{jax.process_count()} but the new "
            f"world is {topo.rank}/{topo.size} (stale backend survived "
            f"teardown)")


def negotiate_jax_coordinator(topo) -> str:
    """Publish/fetch the jax.distributed coordinator for THIS elastic
    epoch through the rendezvous store: the new rank 0 binds a free port
    and publishes ``host:port``; everyone else polls.  Epoch-scoped keys
    keep a stale coordinator from a previous incarnation out of play."""
    from ..common import env as env_mod
    from ..common.exceptions import HorovodInternalError
    from ..transport.store import HTTPStoreClient
    from ..transport.tcp import candidate_advertise_addrs

    addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
    port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
    if not addr or not port:
        raise HorovodInternalError(
            "jax coordinator negotiation requires the rendezvous store")
    store = HTTPStoreClient(addr, port)
    epoch = env_mod.get_epoch()
    scope = f"jaxcoord.{epoch}"
    if topo.rank == 0:
        import socket as _socket

        s = _socket.socket()
        s.bind(("", 0))
        coord_port = s.getsockname()[1]
        s.close()  # jax's coordinator service rebinds it immediately
        coord = f"{candidate_advertise_addrs()[0]}:{coord_port}"
        store.set(scope, "addr", coord.encode())
        return coord
    import time as _time

    deadline = _time.monotonic() + 120
    while True:
        raw = store.get(scope, "addr")
        if raw:
            return raw.decode()
        if _time.monotonic() > deadline:
            raise HorovodInternalError(
                "no jax coordinator published for epoch %d" % epoch)
        _time.sleep(0.25)


def _request_epoch_reset(err: BaseException) -> None:
    """Best-effort worker→driver epoch-reset request (elastic jobs only;
    static jobs have no driver and surface the error to the caller).

    Deliberately fires for EVERY HorovodInternalError, not just
    corruption aborts: any all-survivors abort (wire corruption, stall
    shutdown, a deadline trip on a wedged-but-alive peer) leaves no
    process exit for the driver to react to.  When the failure WAS a
    process death, the request can race the driver's exit monitor and
    cost one spurious epoch bump (the dead identity is respawned one
    epoch later) — a bounded waste that self-corrects, accepted over the
    alternative of filtering by error type and silently breaking
    recovery for whichever alive-abort flavor the filter missed."""
    from ..common import env as env_mod

    if not env_mod.get_bool(env_mod.HOROVOD_ELASTIC):
        return
    from .rendezvous_client import request_reset

    request_reset(f"{type(err).__name__}: {err}")


def _sync_for_epoch(state: State) -> None:
    """Post-reinit state sync, reshard-aware (docs/elastic.md "Live
    resharding").

    Legacy path: broadcast everything from rank 0.  Under a
    reshard-marked epoch: a pure shrink (no joiners) skips the sync
    entirely — every participant is a survivor restored to the same
    commit, so the broadcast would move zero information; with joiners,
    broadcast from ``sync_root`` (the lowest SURVIVING rank — rank 0
    itself may be the fresh process being state-filled, which on the
    legacy root-0 rule would broadcast its blank init state over the
    survivors' progress).  The marker is read from the store per
    identity+epoch, so spawned joiners and re-rendezvoused survivors
    agree on the same root without a side channel; any read miss
    degrades to the legacy full sync, never the reverse."""
    from ..common import env as env_mod

    info = None
    if env_mod.get_bool(env_mod.HOROVOD_ELASTIC) and \
            env_mod.get_bool(env_mod.HOROVOD_RESHARD, True):
        from .rendezvous_client import current_reshard_info

        info = current_reshard_info()
    if info is None:
        state.sync()
        return
    from ..core import flight_recorder

    if not info["joiners"]:
        flight_recorder.record("reshard_sync_skipped", epoch=info["epoch"])
        return
    flight_recorder.record("reshard_sync", epoch=info["epoch"],
                           root=info["sync_root"],
                           joiners=len(info["joiners"]))
    state.sync(root_rank=info["sync_root"])


def _teardown() -> None:
    """Best-effort runtime teardown; never raises (used between retries)."""
    try:
        from ..frameworks.jax import basics

        basics._internal_reset()
    except Exception:  # noqa: BLE001
        pass


def run(func: Callable) -> Callable:
    """Decorator: retry ``func(state, ...)`` across membership changes
    (reference ``run_fn``, ``common/elastic.py:147-168``).

    Re-initialization failures (rendezvous timeout, mesh rebuild races
    against a concurrent epoch bump) RETRY instead of killing the worker;
    after ``WORKER_REINIT_ATTEMPTS`` consecutive failures the worker exits
    with ``TRANSIENT_EXIT_CODE`` so the driver respawns a fresh process
    rather than blacklisting the host."""

    def wrapper(state: State, *args, **kwargs):
        import sys

        from ..common.logging_util import get_logger
        from ..core.state import global_state
        from .constants import TRANSIENT_EXIT_CODE, WORKER_REINIT_ATTEMPTS

        log = get_logger("horovod_tpu.elastic.run")
        notification_manager.start()
        reset_limit = notification_manager.reset_limit
        resets = 0
        skip_sync = False
        reinit_failures = 0
        pending_reset = False
        while True:
            if not global_state().initialized.is_set():
                try:
                    _reset_and_reinit()
                except (SystemExit, KeyboardInterrupt):
                    raise  # removed from the job / user interrupt
                except BaseException as e:  # noqa: BLE001
                    reinit_failures += 1
                    log.warning("elastic re-init failed (%d/%d): %s",
                                reinit_failures, WORKER_REINIT_ATTEMPTS, e)
                    if reinit_failures >= WORKER_REINIT_ATTEMPTS:
                        log.error("giving up after %d re-init failures; "
                                  "exiting for a driver respawn",
                                  reinit_failures)
                        sys.exit(TRANSIENT_EXIT_CODE)
                    _teardown()
                    continue
                reinit_failures = 0
            if pending_reset:
                # AFTER re-init (reference run_fn order: reset() then
                # on_reset()): handlers see the NEW rank/size — e.g. an
                # ElasticSampler reshards here, which matters on the
                # skip-sync path where sync() won't run to do it.
                state.on_reset()
                pending_reset = False
            try:
                if not skip_sync:
                    _sync_for_epoch(state)
                return func(state, *args, **kwargs)
            except HorovodInternalError as e:
                state.restore()
                skip_sync = False
                # Integrity-plane recovery trigger: a corruption abort
                # (FrameCorruptError / CoordinatedAbortError relaying one)
                # leaves EVERY worker alive, so no exit or host change
                # would ever produce the new epoch the retry below waits
                # for.  Ask the driver for one; stale/duplicate requests
                # are epoch-filtered driver-side, and a dead store just
                # falls back to the slow transient-exit path.
                _request_epoch_reset(e)
            except HostsUpdatedInterrupt as e:
                skip_sync = e.skip_sync
            resets += 1
            if reset_limit is not None and resets >= reset_limit:
                raise RuntimeError(
                    f"Exceeded elastic reset limit ({reset_limit})")
            pending_reset = True
            _teardown()

    return wrapper


class _NotificationManager:
    """Lazily starts the worker-side notification server (reference
    ``elastic/worker.py``: an RPC server the driver pings on host
    changes)."""

    def __init__(self):
        self._started = False
        self.reset_limit: Optional[int] = None

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        from ..common import env as env_mod

        if not env_mod.get_bool(env_mod.HOROVOD_ELASTIC):
            return
        from .worker import start_notification_service

        start_notification_service()
        limit = env_mod.get_int(env_mod.HOROVOD_ELASTIC_RESET_LIMIT, 0)
        self.reset_limit = limit if limit > 0 else None


notification_manager = _NotificationManager()
