"""Worker lifecycle registry.

Reference: ``runner/elastic/registration.py:1-173`` — ``WorkerStateRegistry``
counts READY / SUCCESS / FAILURE per slot for the current rendezvous epoch
and decides when to trigger a new rendezvous (all slots accounted for) or
finish the job (success quorum / total failure), bounded by
``--reset-limit``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Set

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, world_size: int):
        self._lock = threading.Lock()
        self._barrier = threading.Event()
        self.reset(world_size)

    def reset(self, world_size: int) -> None:
        with self._lock:
            self._world_size = world_size
            self._states: Dict[int, str] = {}
            self._barrier.clear()

    def record(self, rank: int, state: str) -> None:
        with self._lock:
            self._states[rank] = state
            if len(self._states) >= self._world_size:
                self._barrier.set()

    def record_ready(self, rank: int) -> None:
        self.record(rank, READY)

    def record_success(self, rank: int) -> None:
        self.record(rank, SUCCESS)

    def record_failure(self, rank: int) -> None:
        self.record(rank, FAILURE)

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def failed_ranks(self) -> Set[int]:
        with self._lock:
            return {r for r, s in self._states.items() if s == FAILURE}

    def all_accounted(self) -> bool:
        return self._barrier.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every slot reported a terminal/ready state."""
        return self._barrier.wait(timeout)
