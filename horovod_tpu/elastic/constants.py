"""Elastic subsystem constants (reference ``runner/elastic/constants.py``)."""

# Exit code a worker uses when it gives up after repeated re-init failures
# (rendezvous unreachable, mesh rebuild timeout).  The driver treats this as
# a *transient* casualty — respawn the identity, count toward a higher
# blacklist threshold — distinct from a crash/kill exit, which indicates the
# host itself is suspect (VERDICT round 1, weak #1: a survivor dying because
# its peer died must not blacklist the survivor's host).
TRANSIENT_EXIT_CODE = 73

# A host is blacklisted after this many crash-type worker exits ...
DEFAULT_CRASH_FAILURE_LIMIT = 1
# ... or this many transient-type exits (re-init gave up).
DEFAULT_TRANSIENT_FAILURE_LIMIT = 3

DISCOVER_HOSTS_FREQUENCY_SECS = 1.0
ELASTIC_TIMEOUT_SECS = 600.0

# Worker-side: consecutive re-init failures before exiting with
# TRANSIENT_EXIT_CODE so the driver can respawn a fresh process.
WORKER_REINIT_ATTEMPTS = 3
