"""Host-level fan-in for worker control-plane pushes.

At np ranks the rendezvous store sees np lease renewals and np metrics
snapshots per push period even though colocated ranks share a host and a
filesystem.  This module makes control traffic scale with HOSTS, not
ranks (ROADMAP item 2's tree-shaped fan-in, built on the batched
``POST /batch`` frame): one rank per host — the **aggregator**, always
``local_rank == 0``, no election protocol — forwards every colocated
rank's ops in a single batched transaction.

Mechanism (filesystem spool, no new sockets):

- every peer rank serializes its period's ops (the same tuples
  ``Store.batch`` takes, encoded with the wire codec from
  ``transport/store.py``) into a per-rank spool file under a directory
  derived from the store endpoint + host identity
  (``transport/select.py``), written atomically via tmp+rename;
- the aggregator, each period, reads the spools, concatenates the ops of
  every file whose **content changed** since its last forward, appends
  its own ops, and sends ONE ``store.batch``; it then touches a
  heartbeat file;
- a spool whose bytes did not change is NOT re-forwarded: a dead rank's
  stale lease must age out at the store, not be renewed on its behalf
  forever (lease values embed a renewal counter, so a live rank's spool
  always differs period-to-period).

Failure behavior (the part the chaos test pins): peers check the
aggregator heartbeat before trusting the spool — if it is older than
``HEARTBEAT_STALE_PERIODS`` push periods (or absent, e.g. before the
aggregator's first period or after its death), ``submit`` returns False
and the caller pushes its ops DIRECTLY.  Aggregator death therefore
degrades to the pre-fan-in per-rank traffic within ~1.5 periods; it
never silences a host, and the only lease that expires is the dead
aggregator's own (docs/control_plane.md "Host-level fan-in").
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import time
from typing import Dict, List, Optional

from ..common import env as env_mod
from ..common.logging_util import get_logger
from ..transport.select import host_identity
from ..transport.store import Store, decode_batch_ops, encode_batch_ops

log = get_logger("horovod_tpu.elastic.fanin")

#: Heartbeat older than this many push periods ⇒ aggregator presumed
#: dead ⇒ peers push directly.  1.5 keeps the degrade window well under
#: the default lease timeout (3 push periods) with one period of slack
#: for scheduler jitter.
HEARTBEAT_STALE_PERIODS = 1.5

_HEARTBEAT = "aggregator.hb"


def _spool_root(store: Store, fanin_dir: str) -> str:
    """Spool directory shared by this job's ranks on this host: keyed by
    the store endpoint (job-unique — two jobs on one box must not merge
    spools) and the host identity (boot id — two "hosts" simulated on
    one box share a spool only if they share an identity override)."""
    endpoint = getattr(store, "_base", "in-process")
    token = hashlib.sha1(
        f"{endpoint}|{host_identity()}".encode()).hexdigest()[:16]
    return os.path.join(fanin_dir, f"hvd-fanin-{token}")


class HostFanin:
    """One per worker process; see module docstring.  ``submit`` is
    called from the metrics-push thread only (single-threaded per
    instance)."""

    def __init__(self, store: Store, local_rank: int, period: float,
                 spool_dir: Optional[str] = None):
        self._store = store
        self._local_rank = local_rank
        self._period = period
        fanin_dir = env_mod.get_str(env_mod.HOROVOD_FANIN_DIR) or "/dev/shm"
        self._dir = spool_dir or _spool_root(store, fanin_dir)
        os.makedirs(self._dir, exist_ok=True)
        self._is_aggregator = local_rank == 0
        # Aggregator: last-forwarded bytes per spool file, the
        # change-detection state that keeps dead ranks' leases honest.
        self._forwarded: Dict[str, bytes] = {}

    # -- peer side -----------------------------------------------------

    def _heartbeat_fresh(self) -> bool:
        try:
            age = time.time() - os.stat(
                os.path.join(self._dir, _HEARTBEAT)).st_mtime
        except OSError:
            return False
        return age < HEARTBEAT_STALE_PERIODS * self._period

    def _write_spool(self, ops: List[tuple]) -> None:
        path = os.path.join(self._dir, f"rank-{self._local_rank}.ops")
        fd, tmp = tempfile.mkstemp(dir=self._dir,
                                   prefix=f".rank-{self._local_rank}.")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(encode_batch_ops(ops))
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- aggregator side -----------------------------------------------

    def _collect_peers(self) -> List[tuple]:
        merged: List[tuple] = []
        try:
            names = sorted(os.listdir(self._dir))
        except OSError:
            return merged
        own = f"rank-{self._local_rank}.ops"
        for name in names:
            if not name.startswith("rank-") or not name.endswith(".ops") \
                    or name == own:
                continue
            path = os.path.join(self._dir, name)
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
            except OSError:
                continue
            if self._forwarded.get(name) == blob:
                continue  # stale spool: let its lease age out
            try:
                ops = decode_batch_ops(blob)
            except (ValueError, KeyError, TypeError):
                continue  # torn/corrupt spool: next period's rewrite wins
            self._forwarded[name] = blob
            merged.extend(ops)
        return merged

    def _touch_heartbeat(self) -> None:
        hb = os.path.join(self._dir, _HEARTBEAT)
        try:
            with open(hb, "a"):
                os.utime(hb, None)
        except OSError as e:
            log.warning("fan-in heartbeat write failed (%s); peers will "
                        "degrade to direct pushes", e)

    # -- entry point ---------------------------------------------------

    def submit(self, ops: List[tuple]) -> bool:
        """Hand this period's ops to the fan-in.  Returns True when the
        ops were delivered (aggregator) or spooled under a live
        aggregator (peer); False means the caller must push directly.
        Aggregator store errors propagate — the caller's outage
        accounting owns them."""
        if self._is_aggregator:
            merged = self._collect_peers() + list(ops)
            self._store.batch(merged)
            # Heartbeat AFTER the successful forward: a wedged store
            # must not keep advertising a live aggregator while spools
            # pile up undelivered.
            self._touch_heartbeat()
            return True
        try:
            self._write_spool(ops)
        except OSError as e:
            log.warning("fan-in spool write failed (%s); pushing "
                        "directly", e)
            return False
        return self._heartbeat_fresh()


def maybe_create(store: Store, period: float) -> Optional[HostFanin]:
    """The gate (``HOROVOD_FANIN``): "1" forces fan-in on, "0" off,
    "auto" (default) enables it when the host actually has colocated
    ranks AND batching is on (fan-in forwards via ``/batch``; against an
    old server the per-op fallback would erase the win)."""
    mode = (env_mod.get_str(env_mod.HOROVOD_FANIN) or "auto").lower()
    if mode == "0":
        return None
    if mode == "auto":
        local_size = env_mod.get_int(env_mod.HOROVOD_LOCAL_SIZE, 1)
        batching = env_mod.get_bool(env_mod.HOROVOD_RENDEZVOUS_BATCH, True)
        if local_size <= 1 or not batching:
            return None
    local_rank = env_mod.get_int(env_mod.HOROVOD_LOCAL_RANK, 0)
    try:
        return HostFanin(store, local_rank, period)
    except OSError as e:
        log.warning("fan-in disabled: spool dir unavailable (%s)", e)
        return None
