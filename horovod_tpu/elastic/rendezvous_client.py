"""Worker-side re-rendezvous: fetch the current slot assignment.

Reference: ``gloo_context.cc:154-189`` — on elastic re-init the worker asks
the rendezvous server's ``rank_and_size`` scope for its new rank/size keyed
by ``hostname:local_rank``; a removed host gets rank −1 and exits.
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

from ..common import env as env_mod
from ..common.topology import ProcessTopology
from ..transport.store import HTTPStoreClient

#: Scope names re-exported from the registry (transport/scopes.py,
#: HVD010).  RESET_REQUEST_SCOPE is the worker → driver back-channel: a
#: surviving-but-aborted worker posts ``{"epoch": N, "reason": ...}``
#: there to ask for a fresh membership epoch (see ``request_reset``); the
#: driver treats a CURRENT-epoch request like a membership change.
#: DEMOTION_REPORT_SCOPE is the coordinator → driver demotion channel:
#: the straggler plane's verdict (``core/controller.py`` DemotionPolicy)
#: posts ``{"epoch": N, "rank": R, "hostname": ..., "ewma": ...}`` there
#: (see ``post_demotion_report``); the driver honors a CURRENT-epoch
#: report only and blacklists the named host before advancing the epoch
#: (docs/elastic.md "self-healing demotion").
from ..transport.scopes import (  # noqa: F401  (re-exports)
    DEMOTION_REPORT_SCOPE,
    EPOCH_ACK_SCOPE,
    RANK_AND_SIZE_SCOPE,
    RESET_REQUEST_SCOPE,
)


def _identity() -> str:
    hostname = env_mod.get_str(env_mod.HOROVOD_HOSTNAME) or "localhost"
    local_rank = env_mod.get_int(env_mod.HOROVOD_LOCAL_RANK, 0)
    return f"{hostname}:{local_rank}"


# -- worker-post payload builders (model-checked; see tools/mck proto) ----
#
# Every worker → driver post is built by a pure function here, so the
# production posters below (and core/state.py's lease renewer) and the
# ``hvd-mck proto`` model workers put byte-identical payloads on the
# wire.  The staleness rule the driver enforces (current-epoch-only)
# hinges on these stamps; the checker proves a post carrying an older
# epoch never advances anything.

def lease_renew_ops(identity: str, rank: int, epoch: int, renewals: int,
                    snap_json: bytes):
    """The metrics-snapshot + lease-renewal pair that rides one batched
    transaction each push period.  The lease VALUE must change every
    renewal (the driver judges freshness by value-change time on its own
    clock, never by worker clocks) — ``renewals`` is that changing
    component."""
    from ..core import metrics
    from ..transport.store import LEASE_SCOPE

    lease = json.dumps({"rank": rank, "epoch": epoch,
                        "renewals": renewals}).encode()
    return [("set", metrics.METRICS_SCOPE, f"rank-{rank}", snap_json),
            ("set", LEASE_SCOPE, identity, lease)]


def reset_request_payload(epoch: int, reason: str) -> bytes:
    """Epoch-stamped reset request; the driver honors the CURRENT epoch
    only (anything older was answered by a later bump already)."""
    return json.dumps({"epoch": epoch, "reason": reason[:512]}).encode()


def demotion_report_payload(epoch: int, rank: int, hostname, ewma: float,
                            threshold: float, cycles: int,
                            posted_unix: float) -> bytes:
    """Epoch-stamped demotion report carrying the EWMA evidence, so the
    driver log and flight recorder agree on *why* the host was shed."""
    return json.dumps({
        "epoch": epoch,
        "rank": rank,
        "hostname": hostname,
        "ewma": round(ewma, 6),
        "threshold": threshold,
        "cycles": cycles,
        "posted_unix": posted_unix,
    }).encode()


def store_client() -> Optional[HTTPStoreClient]:
    """The worker's rendezvous store client, resolved from the ambient
    env (None outside launched jobs).  Resolved FRESH on every call by
    design: clients are stateless over HTTP, and re-resolving is what
    lets a worker re-attach (and re-authenticate — the HMAC secret is
    re-read from env) to a rendezvous server that restarted on the same
    address mid-outage (docs/control_plane.md)."""
    addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
    port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
    if not addr or not port:
        return None
    return HTTPStoreClient(addr, port)


def request_reset(reason: str) -> bool:
    """Ask the elastic driver to advance the membership epoch.

    The gap this fills (integrity plane): after a CORRUPTION abort every
    worker process is still alive, so the driver sees no exit and no host
    change — nothing would ever publish the new epoch the survivors'
    ``refresh_topology_from_rendezvous`` is waiting for.  Posting the
    request makes an all-survivors abort recover in one discovery tick
    instead of timing out into TRANSIENT-exit respawns.

    Best-effort and epoch-stamped: the driver only honors a request
    carrying its CURRENT epoch (anything older was answered by a later
    bump already).  Returns whether the request was posted."""
    store = store_client()
    if store is None:
        return False
    payload = reset_request_payload(env_mod.get_epoch(), reason)
    try:
        from ..core import flight_recorder

        flight_recorder.record("reset_request", epoch=env_mod.get_epoch(),
                               reason=reason[:300])
        store.set(RESET_REQUEST_SCOPE, _identity(), payload)
        return True
    except Exception:  # noqa: BLE001 — the retry loop falls back to the
        # slow path (reinit timeout → transient exit → respawn) if the
        # store is unreachable; failing the fast path must not mask the
        # original error being recovered from.
        return False


def _resolve_hostname(store: HTTPStoreClient, rank: int) -> Optional[str]:
    """Best-effort reverse lookup rank → hostname from the driver's
    published slot table (identities are ``hostname:local_rank`` keys).
    The driver re-resolves authoritatively from its own slot table; this
    only makes the report's evidence human-readable."""
    try:
        keys = store.keys(RANK_AND_SIZE_SCOPE)
        if not keys:
            return None
        vals = store.batch([("get", RANK_AND_SIZE_SCOPE, k) for k in keys])
        epoch = env_mod.get_epoch()
        for key, raw in zip(keys, vals):
            if raw is None:
                continue
            slot = json.loads(bytes(raw).decode())
            if slot.get("rank") == rank and slot.get("epoch", 0) == epoch:
                return key.rsplit(":", 1)[0]
    except Exception:  # noqa: BLE001 — evidence only, never load-bearing
        pass
    return None


def post_demotion_report(rank: int, ewma: float, threshold: float,
                         cycles: int) -> bool:
    """Post the coordinator's chronic-straggler verdict to the driver.

    Epoch-stamped and best-effort, mirroring ``request_reset``: the
    driver honors a CURRENT-epoch report only, so a report that races an
    epoch bump simply expires.  The payload carries the EWMA evidence so
    the driver log and flight recorder agree on *why* the host was shed.
    Returns whether the report was posted (False outside elastic jobs —
    the verdict is then detector-only)."""
    store = store_client()
    if store is None:
        return False
    payload = demotion_report_payload(
        env_mod.get_epoch(), rank, _resolve_hostname(store, rank),
        ewma, threshold, cycles, time.time())
    try:
        store.set(DEMOTION_REPORT_SCOPE, _identity(), payload)
        return True
    except Exception:  # noqa: BLE001 — a slow host is a degradation, not
        # an emergency; an unreachable store must not turn the verdict
        # into a job-killing error
        return False


def current_reshard_info() -> Optional[dict]:
    """Reshard marker on THIS identity's slot entry at the CURRENT epoch,
    or None (no marker / stale entry / no store / store error — every
    miss degrades to the legacy full-sync path, never the reverse).

    Read fresh from the store rather than cached from
    ``refresh_topology_from_rendezvous`` on purpose: spawned joiners
    never pass through refresh (they are born at the new epoch, env
    pre-set by the driver), yet must agree with the survivors on
    ``sync_root`` for the state broadcast to be one collective.  The
    epoch check kills both race directions — a fallback republish at
    E+1 while we sync for E, and a late reshard publish while we still
    run E−1."""
    store = store_client()
    if store is None:
        return None
    try:
        raw = store.get(RANK_AND_SIZE_SCOPE, _identity())
    except Exception:  # noqa: BLE001 — advisory fast path only
        return None
    if raw is None:
        return None
    try:
        slot = json.loads(bytes(raw).decode())
    except ValueError:
        return None
    if not isinstance(slot, dict) or not slot.get("reshard") \
            or slot.get("epoch", -1) != env_mod.get_epoch():
        return None
    return {"epoch": slot["epoch"],
            "sync_root": int(slot.get("sync_root", 0)),
            "joiners": list(slot.get("joiners") or [])}


def refresh_topology_from_rendezvous(timeout: float = 120.0) -> ProcessTopology:
    """Blocks until the driver publishes a slot table for a NEW epoch, then
    adopts this process's new coordinates (exits if removed)."""
    store = store_client()
    if store is None:
        raise RuntimeError("elastic re-init requires a rendezvous server")
    my_epoch = env_mod.get_epoch()

    # Exponential backoff with jitter (capped ~2 s): after a host failure
    # EVERY surviving worker re-rendezvouses at once, and a fixed-period
    # poll hammers the (possibly still restarting) store in lockstep.
    # Store errors are tolerated — the server may be mid-restart — but the
    # LAST one is carried into the TimeoutError so a dead store is
    # diagnosable instead of reading like a driver that never published.
    deadline = time.monotonic() + timeout
    delay = 0.05
    last_err = None
    while True:
        try:
            raw = store.get(RANK_AND_SIZE_SCOPE, _identity())
        except Exception as e:  # noqa: BLE001
            last_err = e
            raw = None
        if raw is not None:
            slot = json.loads(raw.decode())
            if slot.get("epoch", 0) > my_epoch:
                break
        if time.monotonic() > deadline:
            detail = f" (last store error: {last_err})" if last_err else ""
            raise TimeoutError(
                f"no new rendezvous assignment within {timeout:.0f}s"
                f"{detail}")
        # Jitter WITHIN the cap (0.5x-1x of delay): the cap is the real
        # worst-case poll gap, not a number jitter can double.
        time.sleep(delay * (0.5 + 0.5 * random.random()))
        delay = min(delay * 2.0, 2.0)

    # Ack adoption so the driver stops re-notifying this identity.
    store.set(EPOCH_ACK_SCOPE, _identity(), str(slot["epoch"]).encode())

    if slot["rank"] < 0:
        # Host was removed from the job (reference exits the worker).
        sys.exit(0)

    for key, var in [("rank", env_mod.HOROVOD_RANK),
                     ("size", env_mod.HOROVOD_SIZE),
                     ("local_rank", env_mod.HOROVOD_LOCAL_RANK),
                     ("local_size", env_mod.HOROVOD_LOCAL_SIZE),
                     ("cross_rank", env_mod.HOROVOD_CROSS_RANK),
                     ("cross_size", env_mod.HOROVOD_CROSS_SIZE)]:
        os.environ[var] = str(slot[key])
    os.environ[env_mod.HOROVOD_EPOCH] = str(slot["epoch"])
    from ..core import flight_recorder, metrics

    metrics.inc("elastic_epoch_changes_total")
    metrics.set_gauge("elastic_epoch", slot["epoch"])
    flight_recorder.record("epoch_change", epoch=slot["epoch"],
                           rank=slot["rank"], size=slot["size"],
                           reshard=bool(slot.get("reshard")))
    return ProcessTopology(
        rank=slot["rank"], size=slot["size"],
        local_rank=slot["local_rank"], local_size=slot["local_size"],
        cross_rank=slot["cross_rank"], cross_size=slot["cross_size"],
        hostname=slot["hostname"])
