"""Worker-side re-rendezvous: fetch the current slot assignment.

Reference: ``gloo_context.cc:154-189`` — on elastic re-init the worker asks
the rendezvous server's ``rank_and_size`` scope for its new rank/size keyed
by ``hostname:local_rank``; a removed host gets rank −1 and exits.
"""

from __future__ import annotations

import json
import os
import sys
import time

from ..common import env as env_mod
from ..common.topology import ProcessTopology
from ..transport.store import HTTPStoreClient

RANK_AND_SIZE_SCOPE = "rank_and_size"


def _identity() -> str:
    hostname = env_mod.get_str(env_mod.HOROVOD_HOSTNAME) or "localhost"
    local_rank = env_mod.get_int(env_mod.HOROVOD_LOCAL_RANK, 0)
    return f"{hostname}:{local_rank}"


def refresh_topology_from_rendezvous(timeout: float = 120.0) -> ProcessTopology:
    """Blocks until the driver publishes a slot table for a NEW epoch, then
    adopts this process's new coordinates (exits if removed)."""
    addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
    port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
    if not addr or not port:
        raise RuntimeError("elastic re-init requires a rendezvous server")
    store = HTTPStoreClient(addr, port)
    my_epoch = env_mod.get_int("HOROVOD_EPOCH", 0)

    deadline = time.monotonic() + timeout
    while True:
        raw = store.get(RANK_AND_SIZE_SCOPE, _identity())
        if raw is not None:
            slot = json.loads(raw.decode())
            if slot.get("epoch", 0) > my_epoch:
                break
        if time.monotonic() > deadline:
            raise TimeoutError("no new rendezvous assignment within timeout")
        time.sleep(0.25)

    # Ack adoption so the driver stops re-notifying this identity.
    store.set("epoch_ack", _identity(), str(slot["epoch"]).encode())

    if slot["rank"] < 0:
        # Host was removed from the job (reference exits the worker).
        sys.exit(0)

    for key, var in [("rank", env_mod.HOROVOD_RANK),
                     ("size", env_mod.HOROVOD_SIZE),
                     ("local_rank", env_mod.HOROVOD_LOCAL_RANK),
                     ("local_size", env_mod.HOROVOD_LOCAL_SIZE),
                     ("cross_rank", env_mod.HOROVOD_CROSS_RANK),
                     ("cross_size", env_mod.HOROVOD_CROSS_SIZE)]:
        os.environ[var] = str(slot[key])
    os.environ["HOROVOD_EPOCH"] = str(slot["epoch"])
    return ProcessTopology(
        rank=slot["rank"], size=slot["size"],
        local_rank=slot["local_rank"], local_size=slot["local_size"],
        cross_rank=slot["cross_rank"], cross_size=slot["cross_size"],
        hostname=slot["hostname"])
