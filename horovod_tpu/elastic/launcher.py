"""Elastic launch: wires ElasticDriver into the ``hvdrun`` CLI.

Reference: ``runner/gloo_run.py:287-336`` (``launch_gloo_elastic``) — start
the rendezvous server, build discovery from the script (or fixed hosts),
spawn a worker per slot with elastic env, monitor exits, and finish when
the surviving workers complete.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List

from ..common import env as env_mod
from ..common.logging_util import get_logger
from ..runner import config_parser
from ..runner.hosts import SlotInfo, parse_host_files, parse_hosts
from ..runner.launch import (
    _is_local,
    _slot_env,
    _OutputPump,
    spawn_worker,
)
from ..runner.rendezvous import RendezvousServer
from ..transport.shm import sweep_dead_segments
from .discovery import FixedHosts, HostDiscoveryScript, HostManager
from .driver import ElasticDriver
from .registration import FAILURE, SUCCESS

log = get_logger("horovod_tpu.elastic.launcher")


def launch_elastic_job(args, command: List[str]) -> int:
    mode = args.host_discovery or (
        "script" if args.host_discovery_script else None)
    hosts_str = args.hosts
    if args.hostfile:
        hosts_str = parse_host_files(args.hostfile)
    if mode == "tpu-metadata":
        from .tpu_metadata import TpuMetadataDiscovery

        if not hosts_str:
            raise SystemExit(
                "hvdrun: --host-discovery tpu-metadata needs the slice "
                "membership via -H/--hostfile (discovery decides which of "
                "those hosts are currently healthy)")
        discovery = TpuMetadataDiscovery(
            parse_hosts(hosts_str),
            url_template=getattr(args, "tpu_metadata_url", None))
    elif mode == "script":
        if not args.host_discovery_script:
            raise SystemExit("hvdrun: --host-discovery script needs "
                             "--host-discovery-script")
        discovery = HostDiscoveryScript(args.host_discovery_script)
    else:
        discovery = FixedHosts(parse_hosts(
            hosts_str or f"localhost:{args.num_proc}"))

    from ..common import secret as secret_mod

    job_secret = secret_mod.ensure_job_secret()
    # Survivable deployment (docs/control_plane.md): with
    # HOROVOD_RENDEZVOUS_EXTERNAL=host:port the launcher attaches to a
    # supervisor-managed, journaled rendezvous server instead of owning
    # one — a SIGKILL'd server restarts and replays, and the driver's
    # partitioned mode rides out the outage without epoch churn.  Both
    # sides must share HOROVOD_SECRET_KEY (ensure_job_secret generated
    # one just now if the operator didn't set it — set it explicitly for
    # external mode or the signatures won't match).
    external = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_EXTERNAL)
    if external:
        from ..runner.rendezvous import ExternalRendezvous

        host, _, p = external.rpartition(":")
        if not host or not p.isdigit():
            raise SystemExit(
                "hvdrun: HOROVOD_RENDEZVOUS_EXTERNAL must be host:port, "
                f"got {external!r}")
        server = ExternalRendezvous(host, int(p))
        port = server.port
    else:
        server = RendezvousServer(bind_addr="0.0.0.0",
                                  job_secret=job_secret.encode())
        port = server.start()
    min_np = args.min_np or args.num_proc
    # --start-timeout in elastic mode bounds slot assembly (reference:
    # elastic settings use start_timeout for wait_for_available_slots).
    driver_kwargs = {}
    if getattr(args, "start_timeout", None):
        driver_kwargs["timeout"] = args.start_timeout
    driver = ElasticDriver(
        server, HostManager(discovery), min_np=min_np, max_np=args.max_np,
        reset_limit=args.reset_limit, **driver_kwargs)
    if external:
        # A restarted launcher re-adopts a previous incarnation's epoch
        # and live workers from the journaled store (no-op on a fresh
        # journal).  Use a per-job journal dir: stale state from an OLD
        # job would be re-adopted too.
        driver.recover_from_store()

    from ..transport.tcp import _default_advertise_addr

    rdv_addr = _default_advertise_addr()
    extra = config_parser.env_from_args(args)
    extra[env_mod.HOROVOD_ELASTIC] = "1"
    if args.reset_limit:
        extra[env_mod.HOROVOD_ELASTIC_RESET_LIMIT] = str(args.reset_limit)

    # Driver lifecycle trace (docs/observability.md "Control-plane
    # attribution"): when the operator asked for timelines, the launcher
    # writes <path>.driver with the reserved driver pid — DRV_* tick/
    # spawn spans and CHURN_EVENT windows, hvd-control-path's anchor.
    # Same-host as the in-process server (offset 0); external servers
    # are assumed clock-synced like any worker host without an estimate.
    driver_timeline = None
    timeline_path = env_mod.get_str(env_mod.HOROVOD_TIMELINE)
    if timeline_path:
        from ..core.timeline import DRIVER_TRACE_PID, Timeline

        try:
            driver_timeline = Timeline(
                f"{timeline_path}.driver", rank=DRIVER_TRACE_PID,
                clock_offset_ns=0, process_name="elastic driver")
        except OSError as e:
            log.warning("cannot write driver timeline %s.driver: %s",
                        timeline_path, e)

    procs: Dict[str, subprocess.Popen] = {}
    pumps: List[_OutputPump] = []
    lock = threading.Lock()

    def create_worker(slot: SlotInfo, epoch: int) -> None:
        # No per-chip binding in elastic mode: libtpu reads TPU_PROCESS_*
        # once at process start, but elastic epochs respawn only NEW
        # identities — survivors would keep a stale tiling and the slice
        # could never re-form.  Elastic TPU jobs therefore run one process
        # per host (the host's default libtpu ownership of all its chips),
        # which also matches how preemption works: whole hosts come & go.
        # External mode: every worker dials the external server's address
        # (it need not be on this host); otherwise the launcher's own.
        slot_rdv_addr = server.addr if external else (
            rdv_addr if not _is_local(slot.hostname) else "127.0.0.1")
        env = _slot_env(slot, slot_rdv_addr, port, extra,
                        tpu_chip_binding=False)
        env[env_mod.HOROVOD_EPOCH] = str(epoch)
        proc = spawn_worker(slot, command, env)
        identity = f"{slot.hostname}:{slot.local_rank}"
        with lock:
            procs[identity] = proc
        prefix = f"[{slot.rank}]<stdout>: " if args.verbose else ""
        eprefix = f"[{slot.rank}]<stderr>: " if args.verbose else ""
        pumps.append(_OutputPump(proc.stdout, sys.stdout, prefix, None,
                                 name=f"hvd-pump-r{slot.rank}-out"))
        pumps.append(_OutputPump(proc.stderr, sys.stderr, eprefix, None,
                                 name=f"hvd-pump-r{slot.rank}-err"))
        threading.Thread(target=_monitor, args=(identity, slot, proc),
                         name=f"hvd-elastic-mon-{identity}",
                         daemon=True).start()

    def _monitor(identity: str, slot: SlotInfo, proc: subprocess.Popen):
        code = proc.wait()
        with lock:
            if procs.get(identity) is proc:
                procs.pop(identity, None)
        log.info("worker %s exited with %d", identity, code)
        if code != 0:
            # A crashed worker never ran ShmMesh.close(); reclaim its
            # /dev/shm ring segments before the next epoch respawns here.
            sweep_dead_segments([proc.pid])
        driver.record_worker_exit(slot, code)

    try:
        driver.start(create_worker)
        while True:
            time.sleep(0.5)
            with lock:
                alive = len(procs)
            successes = driver._registry.count(SUCCESS)
            failures = driver._registry.count(FAILURE)
            current = len(driver.current_slots)
            if successes and successes >= current and alive == 0:
                return 0
            if alive == 0 and failures and \
                    driver.hosts.total_slots() < min_np:
                log.error("all capacity lost (%d failures)", failures)
                return 1
            if driver.stopped_error:
                log.error("elastic driver stopped: %s", driver.stopped_error)
                return 1
    finally:
        driver.stop()
        with lock:
            for proc in procs.values():
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
        time.sleep(0.2)
        with lock:
            for proc in procs.values():
                if proc.poll() is None:
                    proc.kill()
            sweep_dead_segments([proc.pid for proc in procs.values()])
        server.stop()
        if driver_timeline is not None:
            driver_timeline.close()
