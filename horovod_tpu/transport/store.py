"""Key-value rendezvous stores.

Role of the reference's ``gloo::rendezvous::Store`` implementations:
``HTTPStore`` (``horovod/common/gloo/http_store.cc:1-138``) lets C++ workers
rendezvous through the launcher's HTTP KV server with scope-prefixed
GET/PUT/DELETE, and ``MemoryStore`` (``gloo/memory_store.cc``) serves the
single-process case.  Ours are Python: the TCP mesh transport uses a Store to
exchange listen addresses, and the elastic path uses it for rank
reassignment.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

#: Scope workers renew their liveness lease in (``PUT /lease/<identity>``
#: on the metrics-push cadence); the elastic driver judges dead-vs-
#: partitioned from it (docs/control_plane.md).  Defined here, at the
#: store layer, because both the worker pusher (core/state.py) and the
#: driver (elastic/driver.py) need it without importing each other.
LEASE_SCOPE = "lease"

#: Reserved pseudo-scope for the server's key-enumeration endpoint
#: (``GET /__keys__/<scope>`` → JSON list); never used as a real scope.
KEYS_PSEUDO_SCOPE = "__keys__"


class Store:
    """Abstract scope-prefixed KV store with blocking waits."""

    def set(self, scope: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, scope: str, key: str) -> Optional[bytes]:
        """Non-blocking read; None when absent."""
        raise NotImplementedError

    def delete(self, scope: str, key: str) -> None:
        raise NotImplementedError

    def wait(self, scope: str, keys: List[str], timeout: float = 60.0) -> Dict[str, bytes]:
        """Block until every key exists; returns the values.

        Reference analog: ``gloo::rendezvous::Store::wait`` used during
        full-mesh connect (``gloo_context.cc:63-84``)."""
        deadline = time.monotonic() + timeout
        out: Dict[str, bytes] = {}
        pending = list(keys)
        while pending:
            still = []
            for k in pending:
                v = self.get(scope, k)
                if v is None:
                    still.append(k)
                else:
                    out[k] = v
            pending = still
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"store wait timed out after {timeout}s for keys {pending} "
                        f"in scope {scope!r}")
                time.sleep(0.01)
        return out


class MemoryStore(Store):
    """In-process store for single-process jobs and unit tests."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}
        self._cv = threading.Condition()
        # Server-side observability (runner/rendezvous.py enables it on
        # the store backing the KV server): lock-acquire waits become the
        # rendezvous_store_lock_wait_seconds histogram + RV_LOCK_WAIT
        # server-trace spans.  Worker/test stores keep the bare acquire.
        self._observed = False
        self._trace = None

    def enable_observability(self, trace=None) -> None:
        self._observed = True
        self._trace = trace

    def _acquire(self) -> None:
        """Acquire the store lock, timing the wait when observed.  Pair
        with ``self._cv.release()`` (callers use try/finally).  Recording
        happens while holding the lock — metrics registry and timeline
        locks are both terminal, so no new lock-order edges."""
        if not self._observed:
            self._cv.acquire()
            return
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if not metrics.ENABLED and self._trace is None:
            # HOROVOD_METRICS=0 and no server trace: stay a bare acquire
            # (the churn-sim A/B overhead guard measures this arm).
            self._cv.acquire()
            return
        t0 = time.monotonic_ns()
        self._cv.acquire()
        wait_s = (time.monotonic_ns() - t0) / 1e9
        if metrics.ENABLED:
            metrics.observe("rendezvous_store_lock_wait_seconds", wait_s)
        tr = self._trace
        if tr is not None and wait_s >= 50e-6 \
                and timeline_mod.CONTROL_PLANE_ENABLED:
            # Sub-50µs uncontended acquires would flood the trace; the
            # skipped slivers sit inside the covering RV_* request span,
            # so hvd-control-path attribution loses nothing.
            tr.span_since("store_lock", "RV_LOCK_WAIT", t0)

    def set(self, scope: str, key: str, value: bytes) -> None:
        self._acquire()
        try:
            self._data[f"{scope}/{key}"] = value
            self._cv.notify_all()
        finally:
            self._cv.release()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        self._acquire()
        try:
            return self._data.get(f"{scope}/{key}")
        finally:
            self._cv.release()

    def delete(self, scope: str, key: str) -> None:
        self.pop(scope, key)

    def pop(self, scope: str, key: str) -> Optional[bytes]:
        """Atomic check-and-delete (one lock) — callers that need to know
        whether the key existed must use this, not get()+delete()."""
        self._acquire()
        try:
            return self._data.pop(f"{scope}/{key}", None)
        finally:
            self._cv.release()

    def keys(self, scope: str) -> List[str]:
        """All keys currently present in a scope (driver-side enumeration
        of dynamically-registered workers)."""
        prefix = f"{scope}/"
        self._acquire()
        try:
            return [k[len(prefix):] for k in self._data
                    if k.startswith(prefix)]
        finally:
            self._cv.release()


class DurableMemoryStore(MemoryStore):
    """MemoryStore + write-ahead journal (``transport/journal.py``).

    Every mutation is journaled (and, under the default fsync policy,
    synced) BEFORE it is applied to memory, so any op the server
    acknowledged survives a SIGKILL: a restarted store constructed over
    the same ``journal_dir`` replays to the exact pre-crash KV state.
    ``journal_dir=None`` degrades to a plain MemoryStore — durability is
    opt-in per job (``HOROVOD_RENDEZVOUS_JOURNAL_DIR``).

    Lock order: journal appends run under the store's condition lock
    (mutation order and journal order must agree), and the journal's own
    lock is a leaf inside it — lockdep-clean by construction."""

    def __init__(self, journal_dir: Optional[str] = None,
                 fsync: Optional[bool] = None,
                 snapshot_every: Optional[int] = None,
                 timeline=None):
        super().__init__()
        self._journal = None
        if not journal_dir:
            return
        from ..common import env as env_mod
        from .journal import StoreJournal

        if fsync is None:
            fsync = env_mod.get_bool(
                env_mod.HOROVOD_RENDEZVOUS_JOURNAL_FSYNC, True)
        if snapshot_every is None:
            snapshot_every = env_mod.get_int(
                env_mod.HOROVOD_RENDEZVOUS_SNAPSHOT_EVERY,
                env_mod.DEFAULT_RENDEZVOUS_SNAPSHOT_EVERY)
        self._journal = StoreJournal(journal_dir, fsync=fsync,
                                     snapshot_every=snapshot_every,
                                     trace=timeline)
        recovered = self._journal.recover()
        with self._cv:
            self._data.update(recovered)

    def set(self, scope: str, key: str, value: bytes) -> None:
        if self._journal is None:
            return super().set(scope, key, value)
        self._acquire()
        try:
            flat = f"{scope}/{key}"
            self._journal.append_set(flat, value)
            self._data[flat] = value
            self._journal.maybe_compact(self._data)
            self._cv.notify_all()
        finally:
            self._cv.release()

    def pop(self, scope: str, key: str) -> Optional[bytes]:
        if self._journal is None:
            return super().pop(scope, key)
        self._acquire()
        try:
            flat = f"{scope}/{key}"
            if flat not in self._data:
                return None  # no journal record for a no-op delete
            self._journal.append_delete(flat)
            value = self._data.pop(flat)
            self._journal.maybe_compact(self._data)
            return value
        finally:
            self._cv.release()

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()


class HTTPStoreClient(Store):
    """Client for the launcher's rendezvous HTTP KV server.

    Wire contract (shared with ``horovod_tpu.runner.rendezvous``):
    ``PUT /scope/key`` stores the body; ``GET /scope/key`` returns 200+body or
    404; ``DELETE /scope/key`` removes (and serves as the worker-finalized
    hook, reference ``runner/http/http_server.py:112-133``)."""

    def __init__(self, addr: str, port: int, timeout: float = 30.0):
        self._base = f"http://{addr}:{port}"
        self._timeout = timeout
        # Per-job HMAC key (common/secret.py); None = unsigned dev mode.
        from ..common import secret as secret_mod

        self._secret = secret_mod.job_secret()

    def _url(self, scope: str, key: str) -> str:
        return f"{self._base}/{urllib.parse.quote(scope)}/{urllib.parse.quote(key)}"

    def _request(self, scope: str, key: str, method: str,
                 data: Optional[bytes] = None) -> urllib.request.Request:
        url = self._url(scope, key)
        req = urllib.request.Request(url, data=data, method=method)
        if self._secret is not None:
            from ..common import secret as secret_mod

            path = url[len(self._base):]
            req.add_header(secret_mod.SIG_HEADER,
                           secret_mod.sign(self._secret, method, path,
                                           data or b""))
        return req

    def _open_with_retry(self, req: urllib.request.Request):
        """Transient-failure retry: a whole job's workers hit the server
        at once and connections can be reset under burst load; signed
        requests are idempotent KV ops, safe to replay."""
        last: Optional[Exception] = None
        for attempt in range(4):
            try:
                return urllib.request.urlopen(req, timeout=self._timeout)
            except urllib.error.HTTPError:
                raise  # protocol-level answer (404/403): not transient
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e
                time.sleep(0.05 * (attempt + 1))
        raise last

    def set(self, scope: str, key: str, value: bytes) -> None:
        from ..common import faults
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if faults.ACTIVE:
            faults.inject("store.put")
        metrics.inc("rendezvous_store_ops_total", op="set")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            with self._open_with_retry(
                    self._request(scope, key, "PUT", value)):
                pass
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_SET", t0, scope=scope)

    def keys(self, scope: str) -> List[str]:
        """Enumerate a scope's keys (``GET /__keys__/<scope>``) — the
        driver-side lease scan and crash-recovery both need enumeration
        over the wire, which plain /scope/key GETs cannot express."""
        from ..core import metrics
        from ..core import timeline as timeline_mod

        metrics.inc("rendezvous_store_ops_total", op="keys")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            with self._open_with_retry(
                    self._request(KEYS_PSEUDO_SCOPE, scope, "GET")) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return []  # pre-survivability server: treat as empty
            raise
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_KEYS", t0, scope=scope)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        from ..common import faults
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if faults.ACTIVE:
            faults.inject("rendezvous.get")
        metrics.inc("rendezvous_store_ops_total", op="get")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            with self._open_with_retry(
                    self._request(scope, key, "GET")) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_GET", t0, scope=scope)

    def delete(self, scope: str, key: str) -> None:
        from ..core import metrics
        from ..core import timeline as timeline_mod

        metrics.inc("rendezvous_store_ops_total", op="delete")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        req = self._request(scope, key, "DELETE")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_DELETE", t0, scope=scope)
