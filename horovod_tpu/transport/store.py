"""Key-value rendezvous stores.

Role of the reference's ``gloo::rendezvous::Store`` implementations:
``HTTPStore`` (``horovod/common/gloo/http_store.cc:1-138``) lets C++ workers
rendezvous through the launcher's HTTP KV server with scope-prefixed
GET/PUT/DELETE, and ``MemoryStore`` (``gloo/memory_store.cc``) serves the
single-process case.  Ours are Python: the TCP mesh transport uses a Store to
exchange listen addresses, and the elastic path uses it for rank
reassignment.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional


class Store:
    """Abstract scope-prefixed KV store with blocking waits."""

    def set(self, scope: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, scope: str, key: str) -> Optional[bytes]:
        """Non-blocking read; None when absent."""
        raise NotImplementedError

    def delete(self, scope: str, key: str) -> None:
        raise NotImplementedError

    def wait(self, scope: str, keys: List[str], timeout: float = 60.0) -> Dict[str, bytes]:
        """Block until every key exists; returns the values.

        Reference analog: ``gloo::rendezvous::Store::wait`` used during
        full-mesh connect (``gloo_context.cc:63-84``)."""
        deadline = time.monotonic() + timeout
        out: Dict[str, bytes] = {}
        pending = list(keys)
        while pending:
            still = []
            for k in pending:
                v = self.get(scope, k)
                if v is None:
                    still.append(k)
                else:
                    out[k] = v
            pending = still
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"store wait timed out after {timeout}s for keys {pending} "
                        f"in scope {scope!r}")
                time.sleep(0.01)
        return out


class MemoryStore(Store):
    """In-process store for single-process jobs and unit tests."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}
        self._cv = threading.Condition()

    def set(self, scope: str, key: str, value: bytes) -> None:
        with self._cv:
            self._data[f"{scope}/{key}"] = value
            self._cv.notify_all()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._cv:
            return self._data.get(f"{scope}/{key}")

    def delete(self, scope: str, key: str) -> None:
        self.pop(scope, key)

    def pop(self, scope: str, key: str) -> Optional[bytes]:
        """Atomic check-and-delete (one lock) — callers that need to know
        whether the key existed must use this, not get()+delete()."""
        with self._cv:
            return self._data.pop(f"{scope}/{key}", None)

    def keys(self, scope: str) -> List[str]:
        """All keys currently present in a scope (driver-side enumeration
        of dynamically-registered workers)."""
        prefix = f"{scope}/"
        with self._cv:
            return [k[len(prefix):] for k in self._data
                    if k.startswith(prefix)]


class HTTPStoreClient(Store):
    """Client for the launcher's rendezvous HTTP KV server.

    Wire contract (shared with ``horovod_tpu.runner.rendezvous``):
    ``PUT /scope/key`` stores the body; ``GET /scope/key`` returns 200+body or
    404; ``DELETE /scope/key`` removes (and serves as the worker-finalized
    hook, reference ``runner/http/http_server.py:112-133``)."""

    def __init__(self, addr: str, port: int, timeout: float = 30.0):
        self._base = f"http://{addr}:{port}"
        self._timeout = timeout
        # Per-job HMAC key (common/secret.py); None = unsigned dev mode.
        from ..common import secret as secret_mod

        self._secret = secret_mod.job_secret()

    def _url(self, scope: str, key: str) -> str:
        return f"{self._base}/{urllib.parse.quote(scope)}/{urllib.parse.quote(key)}"

    def _request(self, scope: str, key: str, method: str,
                 data: Optional[bytes] = None) -> urllib.request.Request:
        url = self._url(scope, key)
        req = urllib.request.Request(url, data=data, method=method)
        if self._secret is not None:
            from ..common import secret as secret_mod

            path = url[len(self._base):]
            req.add_header(secret_mod.SIG_HEADER,
                           secret_mod.sign(self._secret, method, path,
                                           data or b""))
        return req

    def _open_with_retry(self, req: urllib.request.Request):
        """Transient-failure retry: a whole job's workers hit the server
        at once and connections can be reset under burst load; signed
        requests are idempotent KV ops, safe to replay."""
        last: Optional[Exception] = None
        for attempt in range(4):
            try:
                return urllib.request.urlopen(req, timeout=self._timeout)
            except urllib.error.HTTPError:
                raise  # protocol-level answer (404/403): not transient
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e
                time.sleep(0.05 * (attempt + 1))
        raise last

    def set(self, scope: str, key: str, value: bytes) -> None:
        from ..core import metrics

        metrics.inc("rendezvous_store_ops_total", op="set")
        with self._open_with_retry(self._request(scope, key, "PUT", value)):
            pass

    def get(self, scope: str, key: str) -> Optional[bytes]:
        from ..common import faults
        from ..core import metrics

        if faults.ACTIVE:
            faults.inject("rendezvous.get")
        metrics.inc("rendezvous_store_ops_total", op="get")
        try:
            with self._open_with_retry(
                    self._request(scope, key, "GET")) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, scope: str, key: str) -> None:
        from ..core import metrics

        metrics.inc("rendezvous_store_ops_total", op="delete")
        req = self._request(scope, key, "DELETE")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
