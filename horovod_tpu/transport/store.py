"""Key-value rendezvous stores.

Role of the reference's ``gloo::rendezvous::Store`` implementations:
``HTTPStore`` (``horovod/common/gloo/http_store.cc:1-138``) lets C++ workers
rendezvous through the launcher's HTTP KV server with scope-prefixed
GET/PUT/DELETE, and ``MemoryStore`` (``gloo/memory_store.cc``) serves the
single-process case.  Ours are Python: the TCP mesh transport uses a Store to
exchange listen addresses, and the elastic path uses it for rank
reassignment.
"""

from __future__ import annotations

import base64
import http.client
import io
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

#: Scope workers renew their liveness lease in (``PUT /lease/<identity>``
#: on the metrics-push cadence); the elastic driver judges dead-vs-
#: partitioned from it (docs/control_plane.md).  Re-exported here for the
#: worker pusher (core/state.py) and the driver (elastic/driver.py),
#: which historically imported it from the store layer; the defining
#: literal lives in the scope registry (transport/scopes.py, HVD010).
from .scopes import LEASE_SCOPE  # noqa: F401  (re-export)

#: Reserved pseudo-scope for the server's key-enumeration endpoint
#: (``GET /__keys__/<scope>`` → JSON list); never used as a real scope.
KEYS_PSEUDO_SCOPE = "__keys__"

#: Endpoint for batched rendezvous transactions (``POST /batch``): one
#: signed request carrying an ordered op list, applied under one store-
#: lock acquisition and journaled as one atomic record group
#: (docs/control_plane.md "Batched transactions").
BATCH_PATH = "/batch"

#: Overlay marker for a key deleted earlier in the same batch.
_TOMBSTONE = object()


# -- batched-transaction kernel (model-checked; see tools/mck proto) ----------
#
# The batch-apply + WAL-ordering logic is written ONCE, as a pure
# generator over an abstract store: every state access is one yielded
# step tuple, in exact program order, and the caller executes it against
# the real ``_data`` dict and journal — or, under ``hvd-mck proto``,
# against a model store whose journal is a byte blob that can be torn at
# any offset by a modeled crash.  The model-checked code IS the
# production code; the journal-before-apply ordering and the
# one-frame-per-group atomicity the checker proves are properties of
# THIS generator, not of a parallel description that could drift.
#
# Step vocabulary (first element is the kind; the driver answers loads
# and key scans through ``generator.send``):
#
#   (STEP_LOAD, flat)             -> Optional[bytes]   read one key
#   (STEP_KEYS, prefix)           -> List[str]         flat keys w/ prefix
#   (STEP_JOURNAL, mutations)                          append the group
#                                    record (one frame) BEFORE any apply
#   (STEP_APPLY, flat, value)                          apply one mutation
#                                    (value None = delete)
#   (STEP_NOTIFY,)                                     wake blocked waiters
#   (STEP_REPLY, results)                              the ack point: after
#                                    this step the caller has promised the
#                                    results (durability must already hold)
#
# The generator returns the results list.

STEP_LOAD = "load"
STEP_KEYS = "keys"
STEP_JOURNAL = "journal_append"
STEP_APPLY = "store_apply"
STEP_NOTIFY = "notify"
STEP_REPLY = "reply"


def batch_steps(ops: List[tuple]):
    """Evaluate one ordered batch: stage mutations in an overlay (so
    later ops read their writes), then journal the WHOLE group as one
    record, then apply — journal strictly before the first apply, reply
    strictly after the last.  Crash-at-any-yield plus truncating replay
    keeps this atomic: the group frame either fully made the journal (all
    mutations replay) or it didn't (none do); there is no prefix.

    ``("check", scope, key, expected)`` guards the whole batch: if the
    key's current value (``expected=None`` = must be absent) does not
    match, NOTHING journals or applies and the reply carries ``False``
    at the check's position with no further ops evaluated.  This is the
    fencing a restarted elastic driver's recovery republish needs — a
    crashed incarnation's in-flight epoch publish landing between the
    new driver's recovery read and its republish must fail the
    republish, not be silently overwritten with a stale epoch."""
    from .journal import OP_DELETE, OP_SET

    overlay: Dict[str, object] = {}
    mutations: List[Tuple[int, str, bytes]] = []
    results: List[object] = []
    any_set = False
    for op in ops:
        kind = op[0]
        if kind == "check":
            _, scope, key, expected = op
            flat = f"{scope}/{key}"
            if flat in overlay:
                v = overlay[flat]
                actual = None if v is _TOMBSTONE else v
            else:
                actual = yield (STEP_LOAD, flat)
            if actual != expected:
                yield (STEP_REPLY, tuple(results) + (False,))
                return results + [False]
            results.append(True)
        elif kind == "set":
            _, scope, key, value = op
            flat = f"{scope}/{key}"
            overlay[flat] = value
            mutations.append((OP_SET, flat, value))
            results.append(True)
            any_set = True
        elif kind == "get":
            flat = f"{op[1]}/{op[2]}"
            if flat in overlay:
                v = overlay[flat]
                results.append(None if v is _TOMBSTONE else v)
            else:
                results.append((yield (STEP_LOAD, flat)))
        elif kind == "delete":
            flat = f"{op[1]}/{op[2]}"
            if flat in overlay:
                existed = overlay[flat] is not _TOMBSTONE
            else:
                existed = (yield (STEP_LOAD, flat)) is not None
            if existed:  # no journal record for a no-op delete
                overlay[flat] = _TOMBSTONE
                mutations.append((OP_DELETE, flat, b""))
            results.append(existed)
        elif kind == "keys":
            prefix = f"{op[1]}/"
            base = yield (STEP_KEYS, prefix)
            names = {k[len(prefix):] for k in base}
            for flat, v in overlay.items():
                if flat.startswith(prefix):
                    if v is _TOMBSTONE:
                        names.discard(flat[len(prefix):])
                    else:
                        names.add(flat[len(prefix):])
            results.append(sorted(names))
        else:
            raise ValueError(f"unknown batch op {kind!r}")
    yield (STEP_JOURNAL, tuple(mutations))
    for flat, v in overlay.items():
        yield (STEP_APPLY, flat, None if v is _TOMBSTONE else v)
    if any_set:
        yield (STEP_NOTIFY,)
    yield (STEP_REPLY, tuple(results))
    return results


# -- batch wire codec (shared with runner/rendezvous.py's /batch handler;
#    JSON + base64 values, signed like every KV op) -----------------------

def encode_batch_ops(ops: List[tuple]) -> bytes:
    """Serialize an ordered op list — ``("set", scope, key, value)`` /
    ``("get", scope, key)`` / ``("delete", scope, key)`` /
    ``("keys", scope)`` — into one request body."""
    out = []
    for op in ops:
        kind = op[0]
        if kind == "set":
            out.append({"op": "set", "scope": op[1], "key": op[2],
                        "value": base64.b64encode(op[3]).decode("ascii")})
        elif kind == "check":
            item = {"op": "check", "scope": op[1], "key": op[2]}
            if op[3] is not None:  # absent "value" = key must not exist
                item["value"] = base64.b64encode(op[3]).decode("ascii")
            out.append(item)
        elif kind in ("get", "delete"):
            out.append({"op": kind, "scope": op[1], "key": op[2]})
        elif kind == "keys":
            out.append({"op": "keys", "scope": op[1]})
        else:
            raise ValueError(f"unknown batch op {kind!r}")
    return json.dumps({"ops": out}).encode()


def decode_batch_ops(body: bytes) -> List[tuple]:
    doc = json.loads(body.decode())
    ops: List[tuple] = []
    for item in doc["ops"]:
        kind = item["op"]
        if kind == "set":
            ops.append(("set", item["scope"], item["key"],
                        base64.b64decode(item["value"])))
        elif kind == "check":
            expected = base64.b64decode(item["value"]) \
                if "value" in item else None
            ops.append(("check", item["scope"], item["key"], expected))
        elif kind in ("get", "delete"):
            ops.append((kind, item["scope"], item["key"]))
        elif kind == "keys":
            ops.append(("keys", item["scope"]))
        else:
            raise ValueError(f"unknown batch op {kind!r}")
    return ops


def encode_batch_results(results: List[object]) -> bytes:
    """Per-op results, positionally aligned with the request's op list:
    set → True, get → bytes or None, delete → existed bool, keys →
    sorted name list.  bytes ride base64 under a distinct wrapper key so
    a JSON ``null`` get-result stays distinguishable."""
    out = []
    for r in results:
        if isinstance(r, bytes):
            out.append({"b64": base64.b64encode(r).decode("ascii")})
        else:
            out.append({"v": r})
    return json.dumps({"results": out}).encode()


def decode_batch_results(body: bytes) -> List[object]:
    out: List[object] = []
    for item in json.loads(body.decode())["results"]:
        if "b64" in item:
            out.append(base64.b64decode(item["b64"]))
        else:
            out.append(item["v"])
    return out


class Store:
    """Abstract scope-prefixed KV store with blocking waits."""

    def set(self, scope: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, scope: str, key: str) -> Optional[bytes]:
        """Non-blocking read; None when absent."""
        raise NotImplementedError

    def delete(self, scope: str, key: str) -> None:
        raise NotImplementedError

    def batch(self, ops: List[tuple]) -> List[object]:
        """Ordered multi-op transaction; results align positionally with
        ``ops`` (set → True, get → bytes|None, delete → existed bool,
        keys → name list).

        Base implementation: a per-op loop — the compatibility path a
        batching client degrades to against an old-protocol server (no
        atomicity; the delete-existed answer is a get+delete pair).
        :class:`MemoryStore` applies the whole list under ONE lock
        acquisition; :class:`HTTPStoreClient` ships ONE ``POST /batch``."""
        results: List[object] = []
        for op in ops:
            kind = op[0]
            if kind == "check":
                # Best-effort on the per-op compatibility path (no
                # atomicity to protect, but the stop-on-failure contract
                # holds: nothing after a failed guard executes).
                if self.get(op[1], op[2]) != op[3]:
                    results.append(False)
                    return results
                results.append(True)
            elif kind == "set":
                self.set(op[1], op[2], op[3])
                results.append(True)
            elif kind == "get":
                results.append(self.get(op[1], op[2]))
            elif kind == "delete":
                existed = self.get(op[1], op[2]) is not None
                self.delete(op[1], op[2])
                results.append(existed)
            elif kind == "keys":
                results.append(self.keys(op[1]))
            else:
                raise ValueError(f"unknown batch op {kind!r}")
        return results

    def keys(self, scope: str) -> List[str]:
        raise NotImplementedError

    def wait(self, scope: str, keys: List[str], timeout: float = 60.0) -> Dict[str, bytes]:
        """Block until every key exists; returns the values.

        Reference analog: ``gloo::rendezvous::Store::wait`` used during
        full-mesh connect (``gloo_context.cc:63-84``)."""
        deadline = time.monotonic() + timeout
        out: Dict[str, bytes] = {}
        pending = list(keys)
        while pending:
            still = []
            for k in pending:
                v = self.get(scope, k)
                if v is None:
                    still.append(k)
                else:
                    out[k] = v
            pending = still
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"store wait timed out after {timeout}s for keys {pending} "
                        f"in scope {scope!r}")
                time.sleep(0.01)
        return out


class MemoryStore(Store):
    """In-process store for single-process jobs and unit tests."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}
        self._cv = threading.Condition()
        # Server-side observability (runner/rendezvous.py enables it on
        # the store backing the KV server): lock-acquire waits become the
        # rendezvous_store_lock_wait_seconds histogram + RV_LOCK_WAIT
        # server-trace spans.  Worker/test stores keep the bare acquire.
        self._observed = False
        self._trace = None

    def enable_observability(self, trace=None) -> None:
        self._observed = True
        self._trace = trace

    def _acquire(self) -> None:
        """Acquire the store lock, timing the wait when observed.  Pair
        with ``self._cv.release()`` (callers use try/finally).  Recording
        happens while holding the lock — metrics registry and timeline
        locks are both terminal, so no new lock-order edges."""
        if not self._observed:
            self._cv.acquire()
            return
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if not metrics.ENABLED and self._trace is None:
            # HOROVOD_METRICS=0 and no server trace: stay a bare acquire
            # (the churn-sim A/B overhead guard measures this arm).
            self._cv.acquire()
            return
        t0 = time.monotonic_ns()
        self._cv.acquire()
        wait_s = (time.monotonic_ns() - t0) / 1e9
        if metrics.ENABLED:
            metrics.observe("rendezvous_store_lock_wait_seconds", wait_s)
        tr = self._trace
        if tr is not None and wait_s >= 50e-6 \
                and timeline_mod.CONTROL_PLANE_ENABLED:
            # Sub-50µs uncontended acquires would flood the trace; the
            # skipped slivers sit inside the covering RV_* request span,
            # so hvd-control-path attribution loses nothing.
            tr.span_since("store_lock", "RV_LOCK_WAIT", t0)

    def set(self, scope: str, key: str, value: bytes) -> None:
        self._acquire()
        try:
            self._data[f"{scope}/{key}"] = value
            self._cv.notify_all()
        finally:
            self._cv.release()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        self._acquire()
        try:
            return self._data.get(f"{scope}/{key}")
        finally:
            self._cv.release()

    def delete(self, scope: str, key: str) -> None:
        self.pop(scope, key)

    def pop(self, scope: str, key: str) -> Optional[bytes]:
        """Atomic check-and-delete (one lock) — callers that need to know
        whether the key existed must use this, not get()+delete()."""
        self._acquire()
        try:
            return self._data.pop(f"{scope}/{key}", None)
        finally:
            self._cv.release()

    def keys(self, scope: str) -> List[str]:
        """All keys currently present in a scope (driver-side enumeration
        of dynamically-registered workers)."""
        prefix = f"{scope}/"
        self._acquire()
        try:
            return [k[len(prefix):] for k in self._data
                    if k.startswith(prefix)]
        finally:
            self._cv.release()

    def batch(self, ops: List[tuple]) -> List[object]:
        """The whole ordered op list under ONE lock acquisition.

        Ordered read-your-writes semantics: a get/keys op observes the
        batch's earlier mutations (staged in an overlay) but nothing is
        applied — or journaled — until every op has been evaluated, so
        the journal group matches exactly what the memory apply does.
        WAL ordering holds batch-wide: the group record is (fsync'd and)
        written before the first byte of the overlay lands in ``_data``.

        The op evaluation and ordering live in the pure
        :func:`batch_steps` kernel (model-checked by ``hvd-mck proto``);
        this method is the production driver executing its steps against
        the live dict and journal under one lock acquisition."""
        self._acquire()
        try:
            data = self._data
            steps = batch_steps(ops)
            resp = None
            while True:
                try:
                    step = steps.send(resp)
                except StopIteration as fin:
                    results = fin.value
                    break
                kind = step[0]
                resp = None
                if kind == STEP_LOAD:
                    resp = data.get(step[1])
                elif kind == STEP_KEYS:
                    prefix = step[1]
                    resp = [k for k in data if k.startswith(prefix)]
                elif kind == STEP_JOURNAL:
                    self._journal_group(list(step[1]))
                elif kind == STEP_APPLY:
                    _, flat, v = step
                    if v is None:
                        data.pop(flat, None)
                    else:
                        data[flat] = v
                elif kind == STEP_NOTIFY:
                    self._cv.notify_all()
                # STEP_REPLY needs no action here: returning below IS the
                # reply, and it already follows journal + apply.
            self._after_batch_locked()
            return list(results)
        finally:
            self._cv.release()

    def _journal_group(self, mutations: List[Tuple[int, str, bytes]]
                       ) -> None:
        """Durability hook, called (with the store lock held) before a
        batch's mutations are applied; plain MemoryStore has no journal."""

    def _after_batch_locked(self) -> None:
        """Post-apply hook (store lock held): DurableMemoryStore checks
        the compaction budget here."""


class DurableMemoryStore(MemoryStore):
    """MemoryStore + write-ahead journal (``transport/journal.py``).

    Every mutation is journaled (and, under the default fsync policy,
    synced) BEFORE it is applied to memory, so any op the server
    acknowledged survives a SIGKILL: a restarted store constructed over
    the same ``journal_dir`` replays to the exact pre-crash KV state.
    ``journal_dir=None`` degrades to a plain MemoryStore — durability is
    opt-in per job (``HOROVOD_RENDEZVOUS_JOURNAL_DIR``).

    Lock order: journal appends run under the store's condition lock
    (mutation order and journal order must agree), and the journal's own
    lock is a leaf inside it — lockdep-clean by construction."""

    def __init__(self, journal_dir: Optional[str] = None,
                 fsync: Optional[bool] = None,
                 snapshot_every: Optional[int] = None,
                 timeline=None):
        super().__init__()
        self._journal = None
        if not journal_dir:
            return
        from ..common import env as env_mod
        from .journal import StoreJournal

        if fsync is None:
            fsync = env_mod.get_bool(
                env_mod.HOROVOD_RENDEZVOUS_JOURNAL_FSYNC, True)
        if snapshot_every is None:
            snapshot_every = env_mod.get_int(
                env_mod.HOROVOD_RENDEZVOUS_SNAPSHOT_EVERY,
                env_mod.DEFAULT_RENDEZVOUS_SNAPSHOT_EVERY)
        self._journal = StoreJournal(journal_dir, fsync=fsync,
                                     snapshot_every=snapshot_every,
                                     trace=timeline)
        recovered = self._journal.recover()
        with self._cv:
            self._data.update(recovered)

    def set(self, scope: str, key: str, value: bytes) -> None:
        if self._journal is None:
            return super().set(scope, key, value)
        self._acquire()
        try:
            flat = f"{scope}/{key}"
            self._journal.append_set(flat, value)
            self._data[flat] = value
            self._journal.maybe_compact(self._data)
            self._cv.notify_all()
        finally:
            self._cv.release()

    def pop(self, scope: str, key: str) -> Optional[bytes]:
        if self._journal is None:
            return super().pop(scope, key)
        self._acquire()
        try:
            flat = f"{scope}/{key}"
            if flat not in self._data:
                return None  # no journal record for a no-op delete
            self._journal.append_delete(flat)
            value = self._data.pop(flat)
            self._journal.maybe_compact(self._data)
            return value
        finally:
            self._cv.release()

    def _journal_group(self, mutations) -> None:
        if self._journal is not None:
            self._journal.append_group(mutations)

    def _after_batch_locked(self) -> None:
        if self._journal is not None:
            self._journal.maybe_compact(self._data)

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()


class HTTPStoreClient(Store):
    """Client for the launcher's rendezvous HTTP KV server.

    Wire contract (shared with ``horovod_tpu.runner.rendezvous``):
    ``PUT /scope/key`` stores the body; ``GET /scope/key`` returns 200+body or
    404; ``DELETE /scope/key`` removes (and serves as the worker-finalized
    hook, reference ``runner/http/http_server.py:112-133``)."""

    def __init__(self, addr: str, port: int, timeout: float = 30.0):
        self._base = f"http://{addr}:{port}"
        self._addr = addr
        self._port = port
        self._timeout = timeout
        # Keep-alive connections for the hot batch path, one per thread
        # (the driver's discovery thread and the main thread share one
        # client; http.client connections are not thread-safe).
        self._conn_local = threading.local()
        # Per-job HMAC key (common/secret.py); None = unsigned dev mode.
        from ..common import env as env_mod
        from ..common import secret as secret_mod

        self._secret = secret_mod.job_secret()
        # Batched transactions (POST /batch): knob-gated, capped, and
        # sticky-degraded — the first 404/501 from an old-protocol server
        # flips this client to per-op mode for its lifetime.
        self._batch_enabled = env_mod.get_bool(
            env_mod.HOROVOD_RENDEZVOUS_BATCH, True)
        self._batch_max_ops = max(1, env_mod.get_int(
            env_mod.HOROVOD_RENDEZVOUS_BATCH_MAX_OPS,
            env_mod.DEFAULT_RENDEZVOUS_BATCH_MAX_OPS))
        self._batch_unsupported = False

    def _url(self, scope: str, key: str) -> str:
        return f"{self._base}/{urllib.parse.quote(scope)}/{urllib.parse.quote(key)}"

    def _request(self, scope: str, key: str, method: str,
                 data: Optional[bytes] = None) -> urllib.request.Request:
        url = self._url(scope, key)
        req = urllib.request.Request(url, data=data, method=method)
        if self._secret is not None:
            from ..common import secret as secret_mod

            path = url[len(self._base):]
            req.add_header(secret_mod.SIG_HEADER,
                           secret_mod.sign(self._secret, method, path,
                                           data or b""))
        return req

    def _keepalive_post(self, path: str, body: bytes) -> bytes:
        """Signed POST over a persistent per-thread HTTP/1.1 connection.

        The per-tick coalesced batch makes the control plane's cost one
        round-trip per tick — but with one-shot ``urlopen`` most of that
        round-trip is TCP connect + the server's per-connection thread
        spawn, not the request itself.  Reusing the connection keeps
        ``http_roundtrip`` honest: it measures the wire, not the socket
        churn.  Same retry/answer contract as ``_open_with_retry``: a
        non-200 status is a protocol answer (raised as ``HTTPError`` so
        the 404/501 fallback logic upstream is unchanged), while a stale
        or reset connection — the server restarted, or an idle keep-alive
        timed out — reconnects and replays (idempotent signed KV ops)."""
        headers = {}
        if self._secret is not None:
            from ..common import secret as secret_mod

            headers[secret_mod.SIG_HEADER] = secret_mod.sign(
                self._secret, "POST", path, body)
        last: Optional[Exception] = None
        for attempt in range(4):
            conn = getattr(self._conn_local, "conn", None)
            if conn is None:
                conn = http.client.HTTPConnection(
                    self._addr, self._port, timeout=self._timeout)
                self._conn_local.conn = conn
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()  # drain fully: keeps the conn reusable
                if resp.will_close:
                    conn.close()
                    self._conn_local.conn = None
                if resp.status != 200:
                    raise urllib.error.HTTPError(
                        self._base + path, resp.status, resp.reason,
                        resp.headers, io.BytesIO(data))
                return data
            except urllib.error.HTTPError:
                raise  # protocol-level answer (404/501): not transient
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                conn.close()
                self._conn_local.conn = None
                last = e
                time.sleep(0.05 * (attempt + 1))
        raise last

    def _open_with_retry(self, req: urllib.request.Request):
        """Transient-failure retry: a whole job's workers hit the server
        at once and connections can be reset under burst load; signed
        requests are idempotent KV ops, safe to replay."""
        last: Optional[Exception] = None
        for attempt in range(4):
            try:
                return urllib.request.urlopen(req, timeout=self._timeout)
            except urllib.error.HTTPError:
                raise  # protocol-level answer (404/403): not transient
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e
                time.sleep(0.05 * (attempt + 1))
        raise last

    def set(self, scope: str, key: str, value: bytes) -> None:
        from ..common import faults
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if faults.ACTIVE:
            faults.inject("store.put")
        metrics.inc("rendezvous_store_ops_total", op="set")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            with self._open_with_retry(
                    self._request(scope, key, "PUT", value)):
                pass
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_SET", t0, scope=scope)

    def keys(self, scope: str) -> List[str]:
        """Enumerate a scope's keys (``GET /__keys__/<scope>``) — the
        driver-side lease scan and crash-recovery both need enumeration
        over the wire, which plain /scope/key GETs cannot express."""
        from ..core import metrics
        from ..core import timeline as timeline_mod

        metrics.inc("rendezvous_store_ops_total", op="keys")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            with self._open_with_retry(
                    self._request(KEYS_PSEUDO_SCOPE, scope, "GET")) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return []  # pre-survivability server: treat as empty
            raise
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_KEYS", t0, scope=scope)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        from ..common import faults
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if faults.ACTIVE:
            faults.inject("rendezvous.get")
        metrics.inc("rendezvous_store_ops_total", op="get")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            with self._open_with_retry(
                    self._request(scope, key, "GET")) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_GET", t0, scope=scope)

    def batch(self, ops: List[tuple]) -> List[object]:
        """One signed ``POST /batch`` round-trip for the whole ordered op
        list (split at the batch-size cap), with graceful degradation: an
        old-protocol server answers 404 (no /batch route) or 501 (no
        do_POST at all) and the client falls back to the per-op loop —
        correct on any server version, just un-coalesced."""
        from ..core import metrics

        if not ops:
            return []
        if not self._batch_enabled or self._batch_unsupported:
            return super().batch(ops)
        results: List[object] = []
        for i in range(0, len(ops), self._batch_max_ops):
            chunk = ops[i:i + self._batch_max_ops]
            try:
                results.extend(self._batch_request(chunk))
            except urllib.error.HTTPError as e:
                if e.code in (404, 501):
                    self._batch_unsupported = True
                    metrics.inc("rendezvous_batch_fallbacks_total")
                    results.extend(super().batch(ops[i:]))
                    return results
                raise
        return results

    def _batch_request(self, chunk: List[tuple]) -> List[object]:
        from ..common import faults
        from ..core import metrics
        from ..core import timeline as timeline_mod

        if faults.ACTIVE:
            faults.inject("store.put")  # batches carry the same PUTs
        body = encode_batch_ops(chunk)
        metrics.inc("rendezvous_batch_ops_total", len(chunk))
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        try:
            return decode_batch_results(
                self._keepalive_post(BATCH_PATH, body))
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_BATCH", t0, ops=len(chunk))

    def delete(self, scope: str, key: str) -> None:
        from ..core import metrics
        from ..core import timeline as timeline_mod

        metrics.inc("rendezvous_store_ops_total", op="delete")
        t0 = time.monotonic_ns() if timeline_mod.control_active() else None
        req = self._request(scope, key, "DELETE")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        finally:
            if t0 is not None:
                timeline_mod.control_span_since(
                    "rendezvous_client", "RVC_DELETE", t0, scope=scope)
