"""The rendezvous scope-name registry: every key the control plane reads
or writes lives under one of THESE scopes and no other.

Why a registry instead of per-module constants: a scope name is a wire
contract between three parties that never share code at runtime — the
driver (``elastic/driver.py``), the workers (``elastic/rendezvous_client``
/ ``core/state.py``), and the store server (``transport/store.py``).  A
typo in any one of them doesn't fail loudly; it reads an empty scope and
times out.  Centralizing the literals (and lint rule HVD010, which
rejects scope string literals anywhere else) turns that silent partition
into an import error or a lint failure.

Grep discipline: modules that historically defined these names keep
re-exporting them (``from ..transport.scopes import LEASE_SCOPE``) so
existing import sites stay valid; only the defining assignment moved.
"""

from __future__ import annotations

#: Driver-private scope: the durable epoch counter lives at
#: ``(DRIVER_SCOPE, "epoch")`` so ``recover_from_store()`` can re-adopt
#: it after a driver restart.
DRIVER_SCOPE = "driver"

#: Driver → worker slot table: ``hostname:local_rank`` → rank/size/epoch
#: JSON.  Rank −1 means "removed, exit".
RANK_AND_SIZE_SCOPE = "rank_and_size"

#: Worker → driver adoption ack: each identity posts the epoch it has
#: adopted so the driver stops re-notifying it.
EPOCH_ACK_SCOPE = "epoch_ack"

#: Worker → driver liveness: each identity's lease heartbeat payload,
#: judged by value-change freshness on the driver's monotonic clock.
LEASE_SCOPE = "lease"

#: Worker → driver reset back-channel: ``{"epoch": N, "reason": ...}``
#: from a surviving-but-aborted worker (current-epoch requests only).
RESET_REQUEST_SCOPE = "reset_request"

#: Coordinator → driver straggler verdicts: ``{"epoch": N, "rank": R,
#: ...}`` from the DemotionPolicy (current-epoch reports only).
DEMOTION_REPORT_SCOPE = "demotion_report"

#: Launcher bookkeeping: one key per spawned worker process.
WORKERS_SCOPE = "workers"

#: Worker → driver metrics snapshots, one key per rank.
METRICS_SCOPE = "metrics"

#: Worker → coordinator negotiation-fan-in vetoes: ``hostname`` →
#: ``{"epoch": N, "reason": ...}`` written best-effort by a member that
#: convicted its host's negotiation aggregator as wedged
#: (AggregatorStaleError); rank 0 reads the scope at the next epoch's
#: fan-in sync and keeps convicted hosts on the direct path for the
#: veto-cooldown window (docs/data_plane.md "Negotiation fan-in").
NEGOTIATION_VETO_SCOPE = "negotiation_veto"

ALL_SCOPES = (
    DRIVER_SCOPE,
    RANK_AND_SIZE_SCOPE,
    EPOCH_ACK_SCOPE,
    LEASE_SCOPE,
    RESET_REQUEST_SCOPE,
    DEMOTION_REPORT_SCOPE,
    WORKERS_SCOPE,
    METRICS_SCOPE,
    NEGOTIATION_VETO_SCOPE,
)
