from .store import HTTPStoreClient, MemoryStore, Store  # noqa: F401
from .tcp import AbortState, TcpMesh  # noqa: F401
from .shm import ShmMesh  # noqa: F401
from .select import LinkMesh, build_link_mesh  # noqa: F401
