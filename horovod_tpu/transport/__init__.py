from .store import HTTPStoreClient, MemoryStore, Store  # noqa: F401
from .tcp import TcpMesh  # noqa: F401
