"""Transport package: meshes (tcp/shm), the rendezvous store, and the
scope-name registry.

Re-exports are LAZY (PEP 562): ``transport.scopes`` must stay importable
from ``core/metrics.py`` without dragging in ``tcp`` → ``core/timeline``
→ ``core/metrics`` (a cycle).  Eagerly importing the mesh modules here
would make the registry unusable from anything the timeline depends on.
"""

_EXPORTS = {
    "HTTPStoreClient": ("store", "HTTPStoreClient"),
    "MemoryStore": ("store", "MemoryStore"),
    "Store": ("store", "Store"),
    "AbortState": ("tcp", "AbortState"),
    "TcpMesh": ("tcp", "TcpMesh"),
    "ShmMesh": ("shm", "ShmMesh"),
    "LinkMesh": ("select", "LinkMesh"),
    "build_link_mesh": ("select", "build_link_mesh"),
}


def __getattr__(name):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    value = getattr(importlib.import_module(f".{mod_name}", __name__), attr)
    globals()[name] = value  # cache: __getattr__ only fires on misses
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
