"""Frame-header bit registry — the wire contract's single source of truth.

Every transport frames payloads the same way: ``<Q len|flags>[<I crc32>]``
+ payload — an 8-byte little-endian length word whose top bits carry the
frame flags, optionally followed by a 4-byte CRC32 of the payload, then
the payload bytes.  The flag bits, dtype lane, and header structs are
defined HERE and only here; ``tcp.py``, ``shm.py``, ``digest.py`` and
every fixture import them.  hvd-lint rule HVD008 enforces the split: a
``1 << 56``..``1 << 63`` literal or a re-definition of any of these
names outside this module is a lint error, because two transports
re-deriving the same bit positions is exactly how framing contracts
drift apart (the pre-extraction state: ``tcp.py`` owned the bits and
``shm.py`` re-imported some while re-deriving the rest).  HVD005 checks
the contract VALUES in this module — the bit positions and struct
formats the docs and mixed-version analysis depend on.

Layout recap (full story in ``tcp.py``'s module docstring and
docs/data_plane.md):

- bit 63 ``_CTRL_FLAG`` — control frame (coordinated abort).  In-band
  marking keeps control ordered with data on the same stream; no payload
  is ever 2^63 bytes long, so the bit is unambiguous.
- bit 62 ``_DEFER_FLAG`` — digest-DEFERRED data frame: no inline CRC
  field follows; the payload is covered by the ring step's chained
  shadow digest instead (``transport/digest.py``).
- bit 61 ``_DIGEST_FLAG`` — the digest-check frame closing a deferred
  ring step (``<B algo><Q digest><Q frames>`` payload, always
  inline-CRC'd — it IS the verification).
- bits 56-58 ``_WIRE_DTYPE_MASK`` — wire dtype code stamped by
  cast-on-the-wire compression (``backend/compression.py``), so
  compression-config skew between peers is a loud poisoned-stream
  abort, not silent garbage.

A pre-flags peer masks only bit 63, reads any flagged frame as an
absurd length, and aborts on the frame-size cap — mixed-version meshes
fail loudly by construction.
"""

from __future__ import annotations

import collections
import struct

_LEN = struct.Struct("<Q")
# Wire CRC field (HOROVOD_WIRE_CRC, default on): crc32(payload) follows
# the length word, so the full frame header is <Q len|flags><I crc32>.
# Control frames carry it too — one header layout, no per-frame-kind
# branches.  The CRC is CORRUPTION detection, not authentication
# (docs/security.md); a mismatch is unrecoverable by design because
# positional framing after a bad frame cannot be trusted.
_CRC = struct.Struct("<I")
_CTRL_FLAG = 1 << 63
_DEFER_FLAG = 1 << 62
_DIGEST_FLAG = 1 << 61
_WIRE_DTYPE_SHIFT = 56
_WIRE_DTYPE_MASK = 0x7 << _WIRE_DTYPE_SHIFT
# Wire dtype codes carried in the 3-bit lane (bits 56-58).  The codes ARE
# the compression-config skew detector: a peer whose
# HOROVOD_WIRE_COMPRESSION disagrees stamps a different code and the
# receiver poisons the stream instead of mis-decoding bytes.  Codes are
# registered HERE and only here (HVD008); ``backend/compression.py``
# imports them.  Renumbering any of these is a wire protocol break.
_WIRE_DTYPE_RAW = 0      # uncompressed work-dtype bytes
_WIRE_DTYPE_FP16 = 1     # cast-on-the-wire float16
_WIRE_DTYPE_BF16 = 2     # cast-on-the-wire bfloat16
_WIRE_DTYPE_INT8 = 3     # <f4 scale> + symmetric int8 quantization
_WIRE_DTYPE_ONEBIT = 4   # <f4 pos><f4 neg> means + packed sign bits
_WIRE_DTYPE_TOPK = 5     # <u4 index><work-dtype value> pairs (top-k)
# All header flag bits — everything that is not payload length.
_FLAGS_MASK = _CTRL_FLAG | _DEFER_FLAG | _DIGEST_FLAG | _WIRE_DTYPE_MASK
# Digest-check frame payload: digest algorithm code, 64-bit chained
# digest, frame count for the step it closes.
_DIGEST_PAYLOAD = struct.Struct("<BQQ")

#: Decoded frame header: ``crc`` is None when the mesh CRC is off or the
#: frame is digest-deferred.
_FrameHeader = collections.namedtuple(
    "_FrameHeader", ("ctrl", "deferred", "check", "wire_dtype", "size", "crc"))

# Sanity cap on a frame's claimed payload size.  The length word itself
# is not CRC-covered, and a flipped HIGH byte claims terabytes: recv
# would allocate that buffer BEFORE any CRC or deadline could catch it
# (MemoryError or the OOM killer, not a coordinated abort).  Real frames
# are bounded by the fusion buffer (64 MB default) plus allgather
# fan-in — orders of magnitude under this cap — so an oversized claim is
# treated exactly like a CRC mismatch: poisoned stream, coordinated
# abort.
_MAX_FRAME_BYTES = 1 << 32  # 4 GiB
