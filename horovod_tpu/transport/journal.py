"""Write-ahead journal + compacted snapshots for the rendezvous KV store.

The control plane's durability layer (docs/control_plane.md): every store
mutation is appended to a journal file as a length-prefixed + crc32 record
— the same frame discipline the wire transport (transport/tcp.py) and the
checkpoint layer adopted in the integrity plane — and periodically the
full KV map is compacted into a snapshot so the journal cannot grow
without bound.  A restarted rendezvous server replays snapshot + journal
back to its exact pre-crash state.

On-disk layout (one directory per store)::

    snap-00000003        newest compacted snapshot (generation 3)
    journal-00000003     ops appended since that snapshot
    snap-00000002        previous generation, kept until the next compaction
    journal-00000002

Every file is a sequence of frames ``<Q payload_len><I crc32(payload)>``
followed by the payload.  A journal's first frame is the magic
``HVDJRNL1``; each later frame is one op: ``<B op><I key_len>key[value]``
with op 1 = SET, 2 = DELETE — or one **atomic group** (op 3): a batched
rendezvous transaction journaled as ``<B 3><I count>`` followed by
``count`` length-prefixed sub-op records (``<I len><op record>``).  A
snapshot is magic ``HVDSNAP1``, one SET frame per key, and the commit
marker ``HVDSNAP-END`` — a snapshot without its end marker is an aborted
compaction and is ignored by recovery.

A group is ONE frame, so the longest-valid-prefix rule makes it atomic
for free: a torn tail mid-group fails the frame's crc and replays NONE
of its sub-ops; an intact frame replays ALL of them.  There is no
begin/commit marker pair to keep consistent — the frame boundary IS the
transaction boundary.

Crash-consistency invariants:

- **Longest valid prefix**: a reader stops at the first frame whose
  header is short, whose payload is short, or whose crc32 mismatches — a
  torn final write (power loss mid-append) silently shortens the journal
  by at most the op being written, never misparses.  Recovery truncates
  the torn tail so later appends extend the valid prefix.
- **Snapshot-then-switch**: a compaction writes ``snap-<g+1>`` to a temp
  name, fsyncs, atomically publishes via ``os.replace`` (the checkpoint
  plane's tmp+rename discipline), and only THEN starts ``journal-<g+1>``
  and prunes generation g-1.  A crash mid-compaction leaves an invalid
  (or absent) ``snap-<g+1>`` and recovery falls back to generation g,
  which still holds every op.
- **WAL ordering**: the store appends (and, under the default fsync
  policy, syncs) the record BEFORE applying the op to memory, so a PUT
  the server acknowledged is durable.

Locking: :class:`StoreJournal` guards its file state with one private
lock that is a **leaf** — no other lock in this package is ever acquired
while holding it.  The store calls in holding its own condition lock, so
the only order is store-lock → journal-lock, and lockdep
(``HOROVOD_LOCK_DEBUG=1``) must keep reporting zero cycles through it.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from ..common.logging_util import get_logger
from ..core import metrics
from ..core import timeline as timeline_mod

log = get_logger("horovod_tpu.transport.journal")

#: Frame header: payload length, crc32(payload) — the PR-4 wire shape.
_HDR = struct.Struct("<QI")
#: Op record prefix inside a frame payload: op byte, key length.
_OP = struct.Struct("<BI")

OP_SET = 1
OP_DELETE = 2
#: Atomic record group (batched rendezvous transaction): the frame
#: payload is ``<B 3><I count>`` + count × ``<I len><sub-op record>``,
#: each sub-op an OP_SET/OP_DELETE record.  Replays all-or-nothing
#: because the group shares one frame (one crc32).
OP_GROUP = 3

#: Length prefix of each sub-op record inside a group payload.
_GROUP_LEN = struct.Struct("<I")

JOURNAL_MAGIC = b"HVDJRNL1"
SNAP_MAGIC = b"HVDSNAP1"
SNAP_END = b"HVDSNAP-END"

#: Refuse to trust a length field past this: a corrupt header with a huge
#: length must read as "torn frame", not attempt a giant allocation.
_MAX_PAYLOAD = 256 * 1024 * 1024


def pack_frame(payload: bytes) -> bytes:
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def iter_frames(blob: bytes) -> Iterator[Tuple[int, bytes]]:
    """Yield ``(end_offset, payload)`` for every valid frame in order,
    stopping at the first torn or corrupt one (longest-valid-prefix)."""
    off = 0
    n = len(blob)
    while n - off >= _HDR.size:
        length, crc = _HDR.unpack_from(blob, off)
        start = off + _HDR.size
        if length > _MAX_PAYLOAD or length > n - start:
            return  # torn tail (or a corrupt length field)
        payload = blob[start:start + length]
        if zlib.crc32(payload) != crc:
            return
        off = start + length
        yield off, payload


def encode_op(op: int, key: str, value: bytes = b"") -> bytes:
    kb = key.encode("utf-8")
    return _OP.pack(op, len(kb)) + kb + value


def decode_op(payload: bytes) -> Tuple[int, str, bytes]:
    op, klen = _OP.unpack_from(payload)
    key_end = _OP.size + klen
    if key_end > len(payload):
        raise ValueError("op record shorter than its key length")
    key = payload[_OP.size:key_end].decode("utf-8")
    return op, key, bytes(payload[key_end:])


def encode_group(records: List[Tuple[int, str, bytes]]) -> bytes:
    """One frame payload for an atomic group of (op, key, value) records."""
    parts = [_OP.pack(OP_GROUP, len(records))]
    for op, key, value in records:
        rec = encode_op(op, key, value)
        parts.append(_GROUP_LEN.pack(len(rec)))
        parts.append(rec)
    return b"".join(parts)


def decode_group(payload: bytes) -> List[Tuple[int, str, bytes]]:
    """Inverse of :func:`encode_group`; raises ValueError on any
    structural mismatch (count vs records, truncated sub-record)."""
    op, count = _OP.unpack_from(payload)
    if op != OP_GROUP:
        raise ValueError(f"not a group record (op={op})")
    records: List[Tuple[int, str, bytes]] = []
    off = _OP.size
    for _ in range(count):
        if off + _GROUP_LEN.size > len(payload):
            raise ValueError("group record truncated at a length prefix")
        (rec_len,) = _GROUP_LEN.unpack_from(payload, off)
        off += _GROUP_LEN.size
        if off + rec_len > len(payload):
            raise ValueError("group sub-record shorter than its length")
        records.append(decode_op(payload[off:off + rec_len]))
        off += rec_len
    if off != len(payload):
        raise ValueError("trailing bytes after the last group sub-record")
    return records


class StoreJournal:
    """Journal + snapshot manager for one KV store directory.

    All mutating methods are expected to be called with the owning
    store's lock held (the store is the serialization point for op
    order); the internal ``_lock`` only protects the file handle against
    a concurrent ``close()`` and keeps compaction atomic, and is a leaf.
    """

    def __init__(self, dirpath: str, fsync: bool = True,
                 snapshot_every: int = 512,
                 trace: Optional["timeline_mod.Timeline"] = None):
        self._dir = dirpath
        self._fsync = fsync
        self._snapshot_every = max(1, int(snapshot_every))
        self._lock = threading.Lock()  # LEAF — see module docstring
        self._fh = None
        self._gen = 0
        self._ops_since_snap = 0
        # Server-side trace (JR_* spans); metrics/trace recording happens
        # AFTER _lock is released so the leaf invariant holds.
        self._trace = trace
        os.makedirs(dirpath, exist_ok=True)

    # -- paths ---------------------------------------------------------

    def _snap_path(self, gen: int) -> str:
        return os.path.join(self._dir, f"snap-{gen:08d}")

    def _journal_path(self, gen: int) -> str:
        return os.path.join(self._dir, f"journal-{gen:08d}")

    def _generations(self) -> List[int]:
        gens = set()
        for name in os.listdir(self._dir):
            for prefix in ("snap-", "journal-"):
                if name.startswith(prefix) and not name.endswith(".tmp"):
                    try:
                        gens.add(int(name[len(prefix):]))
                    except ValueError:
                        continue
        return sorted(gens)

    # -- recovery ------------------------------------------------------

    def recover(self) -> Dict[str, bytes]:
        """Replay to the pre-crash KV state and arm the journal for
        appends (truncating any torn tail first).  Call exactly once,
        before the first append."""
        t0 = time.monotonic_ns()
        truncated = False
        with self._lock:
            state, gen, valid_len, nops = self._recover_locked()
            self._gen = gen
            jpath = self._journal_path(gen)
            if os.path.exists(jpath) and os.path.getsize(jpath) > valid_len:
                truncated = True
                torn = os.path.getsize(jpath) - valid_len
                log.warning("journal %s: truncating %d-byte torn tail "
                            "(replayed %d ops)", jpath, torn, nops)
                with open(jpath, "r+b") as f:
                    f.truncate(valid_len)
                    f.flush()
                    os.fsync(f.fileno())
            self._open_journal_locked(gen)
            self._ops_since_snap = nops
            if state or nops:
                log.info("rendezvous journal recovered: generation %d, "
                         "%d keys, %d journal ops", gen, len(state), nops)
        if metrics.ENABLED:
            metrics.observe("journal_replay_seconds",
                            (time.monotonic_ns() - t0) / 1e9)
            if truncated:
                metrics.inc("journal_truncated_tails_total")
            metrics.set_gauge("journal_generation", self._gen)
        if self._trace is not None and timeline_mod.CONTROL_PLANE_ENABLED:
            self._trace.span_since("journal", "JR_REPLAY", t0,
                                   {"generation": self._gen, "ops": nops})
        return state

    def _recover_locked(self) -> Tuple[Dict[str, bytes], int, int, int]:
        for gen in sorted(self._generations(), reverse=True) or [0]:
            if gen == 0:
                base: Optional[Dict[str, bytes]] = {}
            else:
                base = self._read_snapshot(gen)
                if base is None:
                    # Aborted compaction (no end marker / torn): the
                    # previous generation still holds every op.
                    log.warning("snapshot generation %d invalid; falling "
                                "back to generation %d", gen, gen - 1)
                    continue
            state, valid_len, nops = self._replay_journal(gen, base)
            return state, gen, valid_len, nops
        return {}, 0, 0, 0

    def _read_snapshot(self, gen: int) -> Optional[Dict[str, bytes]]:
        path = self._snap_path(gen)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        frames = [p for _, p in iter_frames(blob)]
        if len(frames) < 2 or frames[0] != SNAP_MAGIC \
                or frames[-1] != SNAP_END:
            return None
        state: Dict[str, bytes] = {}
        for payload in frames[1:-1]:
            try:
                op, key, value = decode_op(payload)
            except (ValueError, struct.error):
                return None
            if op != OP_SET:
                return None
            state[key] = value
        return state

    def _replay_journal(self, gen: int, base: Dict[str, bytes]
                        ) -> Tuple[Dict[str, bytes], int, int]:
        """Apply the journal's longest valid prefix over ``base``; returns
        (state, byte length of the valid prefix, ops replayed)."""
        path = self._journal_path(gen)
        state = dict(base)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return state, 0, 0
        valid_len = 0
        nops = 0
        first = True
        for end, payload in iter_frames(blob):
            if first:
                first = False
                if payload != JOURNAL_MAGIC:
                    break  # foreign file: replay nothing, rewrite below
                valid_len = end
                continue
            # Dispatch on the op byte BEFORE decode_op: a group frame's
            # count/length fields are binary, and decode_op would try to
            # utf-8 them as a key.
            if payload and payload[0] == OP_GROUP:
                # Atomic group: the frame's crc already vouched for every
                # byte, so a structural decode error here is corruption —
                # stop (applying a partial group would break atomicity).
                try:
                    records = decode_group(payload)
                except (ValueError, struct.error):
                    break
                for gop, gkey, gvalue in records:
                    if gop == OP_SET:
                        state[gkey] = gvalue
                    elif gop == OP_DELETE:
                        state.pop(gkey, None)
                nops += len(records) - 1  # +1 below, like a plain op
            else:
                try:
                    op, key, value = decode_op(payload)
                except (ValueError, struct.error):
                    break
                if op == OP_SET:
                    state[key] = value
                elif op == OP_DELETE:
                    state.pop(key, None)
                else:
                    break
            valid_len = end
            nops += 1
        return state, valid_len, nops

    # -- append path ---------------------------------------------------

    def _open_journal_locked(self, gen: int) -> None:
        self._fh = open(self._journal_path(gen), "ab")
        if self._fh.tell() == 0:
            self._fh.write(pack_frame(JOURNAL_MAGIC))
            self._sync_locked()

    def _sync_locked(self) -> float:
        """Flush (+ fsync under the default policy); returns the fsync
        wall seconds (0.0 when fsync is off)."""
        self._fh.flush()
        if not self._fsync:
            return 0.0
        t0 = time.monotonic_ns()
        os.fsync(self._fh.fileno())
        return (time.monotonic_ns() - t0) / 1e9

    def _record_append(self, t0_ns: int, fsync_s: float) -> None:
        """Metrics + trace for one append, called with ``_lock`` already
        released (leaf discipline); the store's condition lock may still
        be held — both sinks are terminal locks, no new order edges."""
        if metrics.ENABLED:
            metrics.observe("journal_append_seconds",
                            (time.monotonic_ns() - t0_ns) / 1e9)
            if fsync_s > 0.0:
                metrics.observe("journal_fsync_seconds", fsync_s)
        tr = self._trace
        if tr is not None and fsync_s > 0.0 \
                and timeline_mod.CONTROL_PLANE_ENABLED:
            tr.span_since("journal", "JR_FSYNC",
                          time.monotonic_ns() - int(fsync_s * 1e9))

    def append_set(self, key: str, value: bytes) -> None:
        t0 = time.monotonic_ns()
        with self._lock:
            if self._fh is None:
                return  # closed (server shutdown race): drop silently
            self._fh.write(pack_frame(encode_op(OP_SET, key, value)))
            fsync_s = self._sync_locked()
            self._ops_since_snap += 1
        self._record_append(t0, fsync_s)

    def append_delete(self, key: str) -> None:
        t0 = time.monotonic_ns()
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(pack_frame(encode_op(OP_DELETE, key)))
            fsync_s = self._sync_locked()
            self._ops_since_snap += 1
        self._record_append(t0, fsync_s)

    def append_group(self, records: List[Tuple[int, str, bytes]]) -> None:
        """Append a batched transaction as ONE frame (one write, one
        fsync): the whole group replays or none of it does.  ``records``
        are (OP_SET/OP_DELETE, key, value) tuples in apply order."""
        if not records:
            return
        t0 = time.monotonic_ns()
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(pack_frame(encode_group(records)))
            fsync_s = self._sync_locked()
            self._ops_since_snap += len(records)
        self._record_append(t0, fsync_s)

    def maybe_compact(self, state: Dict[str, bytes]) -> bool:
        """Compact when the op budget is spent; ``state`` is the full
        post-op KV map (the caller holds the store lock, so it cannot
        move underneath).  Returns whether a compaction ran."""
        t0 = time.monotonic_ns()
        with self._lock:
            if self._fh is None or \
                    self._ops_since_snap < self._snapshot_every:
                return False
            self._compact_locked(state)
        if metrics.ENABLED:
            metrics.observe("journal_compaction_seconds",
                            (time.monotonic_ns() - t0) / 1e9)
            metrics.set_gauge("journal_generation", self._gen)
        if self._trace is not None and timeline_mod.CONTROL_PLANE_ENABLED:
            self._trace.span_since("journal", "JR_COMPACT", t0,
                                   {"generation": self._gen})
        return True

    def _compact_locked(self, state: Dict[str, bytes]) -> None:
        new_gen = self._gen + 1
        snap = self._snap_path(new_gen)
        tmp = snap + ".tmp"
        with open(tmp, "wb") as f:
            f.write(pack_frame(SNAP_MAGIC))
            for key in sorted(state):
                f.write(pack_frame(encode_op(OP_SET, key, state[key])))
            f.write(pack_frame(SNAP_END))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, snap)
        self._fsync_dir()
        # Publish point passed: switch journals, then prune generations
        # older than the one we just superseded (keep 2: a torn NEW
        # snapshot must still find a complete predecessor).
        self._fh.close()
        self._gen = new_gen
        self._open_journal_locked(new_gen)
        self._ops_since_snap = 0
        for gen in self._generations():
            if gen < new_gen - 1:
                for path in (self._snap_path(gen), self._journal_path(gen)):
                    try:
                        os.remove(path)
                    except OSError:
                        pass
        log.debug("compacted rendezvous journal to generation %d "
                  "(%d keys)", new_gen, len(state))

    def _fsync_dir(self) -> None:
        """Make the rename durable (POSIX: the directory entry needs its
        own fsync); best-effort on filesystems without directory fds."""
        try:
            fd = os.open(self._dir, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._sync_locked()
                    self._fh.close()
                finally:
                    self._fh = None
