"""Chainable wire digests for the deferred (shadow) CRC path.

The inline per-frame crc32 costs ~0.8 GB/s of serial time on the recv
path — at 4 MiB payloads it dominates the ring step (ROADMAP item 2, the
measured 3.2-3.5x CRC-on/off gap).  The deferred path moves integrity off
the serial path: each endpoint of a ring step folds every segment frame
into a :class:`StreamDigest` as it is sent/landed (receiver-side on the
sendrecv helper thread, overlapped with the main thread's reduction), and
one small inline-CRC'd digest-check frame closes the step.  Corrupt bytes
are still detected BEFORE the collective returns — the granularity of
detection changes (per step instead of per frame), the guarantee does not.

Two algorithms, selected by ``HOROVOD_WIRE_DIGEST`` (all ranks must
agree; the check frame carries the algorithm code so skew fails loudly):

- ``fold64`` (default): a vectorized sum+xor fold over little-endian
  64-bit words (tail zero-padded), mixed with golden-ratio / FNV-64
  constants and chained order-sensitively across frames.  Runs at numpy
  memory bandwidth (~10x zlib.crc32 on the 1-core CI box), which is what
  makes default-on integrity ~free.
- ``crc32``: per-frame ``zlib.crc32`` chained through the running value.
  Because crc32 is streaming, the chain over any segmentation equals the
  crc32 of the concatenated payload bytes (property-tested) — the strict
  option when a standard digest is wanted end to end.

Not cryptographic — corruption detection, like the inline CRC
(docs/security.md).
"""

from __future__ import annotations

import zlib

import numpy as np

from ..common.exceptions import HorovodInternalError
from .frame_bits import _DIGEST_PAYLOAD

#: Byte size of a digest-check frame's payload — transports validate the
#: claimed frame size against this before unpacking.
CHECK_SIZE = _DIGEST_PAYLOAD.size

_MASK64 = (1 << 64) - 1
# Golden-ratio odd constant (splitmix64's increment): whitens the word
# sum so low-entropy payloads (all-zeros, all-ones) still spread digests.
_FOLD_MIX = 0x9E3779B97F4A7C15
# FNV-1a 64-bit prime: multiplicative chain step, makes the cross-frame
# combination order-sensitive (swapped segments change the digest).
_CHAIN_PRIME = 0x100000001B3

ALGO_CRC32 = 1
ALGO_FOLD64 = 2
_ALGO_BY_NAME = {"crc32": ALGO_CRC32, "fold64": ALGO_FOLD64}
_NAME_BY_ALGO = {v: k for k, v in _ALGO_BY_NAME.items()}


def algo_from_name(name: str) -> int:
    try:
        return _ALGO_BY_NAME[name]
    except KeyError:
        raise HorovodInternalError(
            f"unknown HOROVOD_WIRE_DIGEST algorithm {name!r} "
            f"(expected one of {sorted(_ALGO_BY_NAME)})") from None


def algo_name(algo: int) -> str:
    return _NAME_BY_ALGO.get(algo, f"algo#{algo}")


def pack_check(dig: "StreamDigest", frames: int) -> bytes:
    """Serialize the digest-check frame payload closing a ring step:
    ``<B algo><Q chained digest><Q frame count>``.  Both transports emit
    it through here so the check-frame layout cannot fork."""
    return _DIGEST_PAYLOAD.pack(dig.algo, dig.value(), frames)


def unpack_check(payload) -> "tuple[int, int, int]":
    """Decode a digest-check payload into ``(algo, value, frames)``."""
    return _DIGEST_PAYLOAD.unpack(payload)


def _fold64(view: memoryview) -> int:
    """Digest one frame's bytes: sum and xor over LE uint64 words (tail
    zero-padded to a word), mixed with the byte length.  Pure vectorized
    numpy — no per-byte Python work."""
    n = len(view)
    n8 = n & ~7
    if n8:
        words = np.frombuffer(view[:n8], dtype="<u8")
        s = int(words.sum(dtype=np.uint64))
        x = int(np.bitwise_xor.reduce(words))
    else:
        s = x = 0
    if n != n8:
        w = int.from_bytes(bytes(view[n8:]), "little")
        s = (s + w) & _MASK64
        x ^= w
    return (s * _FOLD_MIX + (x ^ (n * _CHAIN_PRIME))) & _MASK64


class StreamDigest:
    """Running digest over an ordered stream of frames.

    ``update`` folds one complete frame payload (both endpoints call it
    once per frame, so sender and receiver chains agree whenever the wire
    bytes do); ``value()`` is the 64-bit chain state the digest-check
    frame carries.  Not thread-safe by itself — the transport serializes
    updates per direction (sends under the peer send lock, receives on
    the FIFO helper thread) and the check-frame read happens strictly
    after the step's last frame landed."""

    __slots__ = ("algo", "_value", "frames")

    def __init__(self, algo: int):
        if algo not in _NAME_BY_ALGO:
            raise HorovodInternalError(f"unknown wire digest algo {algo}")
        self.algo = algo
        self._value = 0
        self.frames = 0

    def update(self, view) -> None:
        view = view if isinstance(view, memoryview) else memoryview(view)
        if self.algo == ALGO_CRC32:
            self._value = zlib.crc32(view, self._value) & 0xFFFFFFFF
        else:
            self._value = (self._value * _CHAIN_PRIME
                           + _fold64(view)) & _MASK64
        self.frames += 1

    def value(self) -> int:
        return self._value
