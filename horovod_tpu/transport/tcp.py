"""Full-mesh TCP transport — the self-contained Gloo role.

The reference leans on libgloo for its MPI-free path: every rank builds TCP
connections to every other rank through a rendezvous store
(``gloo_context.cc:63-84`` ``connectFullMesh``) and the controller/data ops
run over those sockets.  We are MPI- and gloo-free by design (north star), so
this module is that fabric: a framed, thread-safe, full-mesh TCP transport
bootstrapped through a ``Store``.

Framing: ``<Q len|flags>[<I crc32(payload)>]`` + payload — an 8-byte
little-endian length word whose top bits carry the frame flags, followed
by a 4-byte CRC32 of the payload when ``HOROVOD_WIRE_CRC`` is on (the
default) and the frame is not digest-deferred, then the payload bytes.
Flag bits: bit 63 marks control frames (``_CTRL_FLAG``); bit 62 marks a
digest-DEFERRED data frame (``_DEFER_FLAG``) — no inline CRC field
follows, the frame is covered instead by the ring step's chained shadow
digest (``transport/digest.py``), closed out by a digest-check frame; bit
61 marks that digest-check frame itself (``_DIGEST_FLAG``, always
inline-CRC'd — it IS the verification); bits 56-58 carry the wire dtype
code (``_WIRE_DTYPE_MASK``) stamped by cast-on-the-wire compression
(``backend/compression.py``), so peers that disagree on
``HOROVOD_WIRE_COMPRESSION`` poison the stream loudly instead of
mis-decoding bytes.  A pre-flags peer masks only bit 63, reads any
flagged frame as an absurd length, and aborts on the frame-size cap —
mixed-version meshes fail loudly by construction.  When
``HOROVOD_WIRE_CRC`` is off the CRC field is absent from every frame.
Connection establishment is deterministic to avoid crossed sockets: every
rank listens; rank *i* dials every rank *j < i* and introduces itself
with an 8-byte hello (magic + rank).

Zero-copy data plane: ``send`` accepts any C-contiguous bytes-like object
(a memoryview over a numpy slice included) and writes ``[header, payload]``
vectored, never concatenating; ``recv_into`` lands a frame's payload
directly in a caller-provided buffer, computing the wire CRC incrementally
over the destination view as bytes arrive — no intermediate heap
materialization on either side (docs/data_plane.md).

Only the background/controller thread performs transport I/O in steady state,
but sends and recvs are independently locked per peer so the elastic
notification path can interleave safely.
"""

from __future__ import annotations

import queue
import select
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from . import digest as digest_mod
# Frame-header contract — bits, structs, and size cap — lives in the
# frame_bits registry (HVD008: defined once, imported everywhere).
from .frame_bits import (
    _CRC,
    _CTRL_FLAG,
    _DEFER_FLAG,
    _DIGEST_FLAG,
    _FLAGS_MASK,
    _FrameHeader,
    _LEN,
    _MAX_FRAME_BYTES,
    _WIRE_DTYPE_MASK,
    _WIRE_DTYPE_SHIFT,
)
from ..common import faults
from ..common.exceptions import (
    CoordinatedAbortError,
    FrameCorruptError,
    HorovodInternalError,
    PeerGoneError,
)
from ..common.logging_util import get_logger
from ..core import flight_recorder, metrics
from ..core.timeline import wire_stats
from .store import Store

log = get_logger("horovod_tpu.transport.tcp")

_HELLO = struct.pack("<I", 0x48564D54)  # "HVMT"
# How often a blocked recv wakes to check the mesh-wide abort flag and its
# progress deadline.  Bounds abort-propagation latency for threads blocked
# on a DIFFERENT peer's socket than the one the abort arrived on.
_ABORT_POLL_SECS = 0.25


class _ProgressStall(Exception):
    """Internal: a recv made no byte progress within the deadline."""


def _wait_ready(sock: socket.socket, timeout: float, write: bool) -> bool:
    """poll(2)-based readiness wait: select(2) breaks past fd 1024 and
    large meshes hold one socket per peer."""
    fd = sock.fileno()
    if fd < 0:
        # Closed under us (mesh teardown racing a blocked op): surface
        # as the socket error it is, not a ValueError from poll/select.
        raise OSError("socket closed")
    if hasattr(select, "poll"):
        p = select.poll()
        p.register(fd, select.POLLOUT if write else select.POLLIN)
        return bool(p.poll(timeout * 1000.0))
    sets = ([], [sock], []) if write else ([sock], [], [])
    r, w, _ = select.select(*sets, timeout)
    return bool(w if write else r)


def _wait_readable(sock: socket.socket, timeout: float) -> bool:
    return _wait_ready(sock, timeout, write=False)


def _wait_writable(sock: socket.socket, timeout: float) -> bool:
    return _wait_ready(sock, timeout, write=True)


def _as_byte_view(data) -> memoryview:
    """Flat byte view over any C-contiguous bytes-like object — bytes,
    bytearray, memoryview, or a numpy array/slice — without copying.
    Raises for non-contiguous input: the caller holds a strided view it
    must materialize itself (silently copying here would defeat the
    zero-copy contract and hide the cost)."""
    view = data if isinstance(data, memoryview) else memoryview(data)
    if view.format != "B" or view.ndim != 1:
        view = view.cast("B")
    return view


def _as_writable_byte_view(data) -> memoryview:
    view = _as_byte_view(data)
    if view.readonly:
        raise ValueError("recv_into needs a writable destination buffer")
    return view


class PendingRecv:
    """Handle for an in-flight ``recv_into_async``: ``wait()`` blocks until
    the frame landed and returns its payload size, re-raising any
    transport error (PeerGoneError, CoordinatedAbortError,
    FrameCorruptError) on the caller's thread."""

    __slots__ = ("_done", "_box")

    def __init__(self, done: threading.Event, box: List):
        self._done = done
        self._box = box

    def wait(self) -> int:
        self._done.wait()
        if self._box[1] is not None:
            raise self._box[1]
        return self._box[0]


class AbortState:
    """Mesh-wide abort flag: ``(epoch, origin_rank, reason)`` once any
    link delivered (or this rank broadcast) a coordinated abort.

    A tiny holder rather than a bare attribute so SEVERAL meshes can
    share one flag: under a ``LinkMesh`` (transport/select.py) the TCP
    and shm fabrics are two halves of the same failure domain — a thread
    blocked on an shm ring must observe an abort that arrived on a TCP
    socket within one poll quantum, and vice versa."""

    __slots__ = ("value",)

    def __init__(self):
        self.value: Optional[Tuple[int, int, str]] = None


class _Peer:
    __slots__ = ("sock", "send_lock", "recv_lock", "dead", "ever_received",
                 "frames_in")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        # Registered peers run NON-BLOCKING: both directions are driven by
        # the poll loops in _send_bounded/_recv_bounded.  A blocking
        # send(2) queues its ENTIRE buffer before returning, so no
        # poll-first scheme can bound it once a live-but-wedged peer stops
        # reading; non-blocking send returns partial/EAGAIN and the loop
        # keeps the progress deadline and abort flag in charge.
        sock.setblocking(False)
        self.send_lock = threading.Lock()
        self.recv_lock = threading.Lock()
        # First send/recv failure marks the peer dead (reason string);
        # every later call fails fast with PeerGoneError instead of
        # re-blocking on the broken socket.
        self.dead: Optional[str] = None
        # The progress deadline ARMS on the first bytes ever received from
        # this peer: post-handshake bring-up staggers legitimately (slow
        # XLA init, store waits) and is covered by the startup timeout —
        # "gone" is a judgment about a peer that WAS participating and
        # stopped.
        self.ever_received = False
        # Completed frames received from this peer — diagnostic context
        # for FrameCorruptError ("which frame in the stream went bad").
        self.frames_in = 0


class TcpMesh:
    """Framed full-mesh TCP fabric between ``size`` ranks."""

    def __init__(self, rank: int, size: int, store: Store,
                 scope: str = "tcp", bind_addr: str = "0.0.0.0",
                 advertise_addr: Optional[str] = None,
                 timeout: float = 60.0,
                 epoch: Optional[int] = None,
                 progress_deadline: Optional[float] = None,
                 abort_state: Optional[AbortState] = None):
        from ..common import env as env_mod

        self.rank = rank
        self.size = size
        self._peers: Dict[int, _Peer] = {}
        self._closed = False
        self._sr_thread: Optional[threading.Thread] = None
        self._sr_queue: Optional[queue.SimpleQueue] = None
        # Elastic epoch stamped into abort frames; aborts from older epochs
        # are discarded on receipt (a pre-reset straggler must not kill the
        # re-rendezvoused world).
        self.epoch = env_mod.get_epoch() if epoch is None else epoch
        # Recv progress deadline (seconds; 0 disables): any bytes received
        # reset it, so slow-but-alive peers never trip it — only a peer
        # that stops sending entirely.
        self.progress_deadline = env_mod.get_float(
            env_mod.HOROVOD_TCP_PROGRESS_DEADLINE,
            env_mod.DEFAULT_TCP_PROGRESS_DEADLINE_SECS) \
            if progress_deadline is None else progress_deadline
        # Wire CRC (default on): sender stamps crc32(payload) into the
        # frame header, receiver verifies before handing bytes up.  All
        # ranks must agree (env-propagated like every other knob).
        self.wire_crc = env_mod.get_bool(env_mod.HOROVOD_WIRE_CRC, True)
        # Shadow (deferred) digesting for ring data frames (default on,
        # effective only with the CRC on): segment frames skip the inline
        # CRC field; each endpoint chains per-frame digests off the
        # serial path and a digest-check frame closes the step.  "0"
        # restores strict per-frame inline CRC.  All ranks must agree.
        self.crc_shadow = env_mod.get_bool(
            env_mod.HOROVOD_WIRE_CRC_SHADOW, True)
        self.digest_algo = digest_mod.algo_from_name(
            env_mod.get_str(env_mod.HOROVOD_WIRE_DIGEST, "fold64")
            or "fold64")
        # Mesh-wide abort state: (epoch, origin_rank, reason) once any link
        # delivered (or this rank broadcast) a coordinated abort.  Blocked
        # recvs observe it within _ABORT_POLL_SECS regardless of which
        # socket they wait on.  The holder may be SHARED with a sibling
        # shm mesh under a LinkMesh (see AbortState).
        self._abort_state = abort_state if abort_state is not None \
            else AbortState()
        # Set by LinkMesh: an abort detected HERE must fan out over every
        # transport, not just this mesh's links.
        self.abort_relay = None
        if size == 1:
            self._listener = None
            return

        from ..common import secret as secret_mod

        self._secret = secret_mod.job_secret()
        self._listener = socket.create_server((bind_addr, 0), backlog=size)
        port = self._listener.getsockname()[1]
        if advertise_addr is not None:
            candidates = [advertise_addr]
        else:
            # NIC negotiation, dial-side (reference role:
            # driver_service.py:162-194 intersects routable interfaces by
            # ssh-probing every host; here every rank advertises ALL its
            # candidate addresses and dialers try them in order — same
            # outcome on multi-homed hosts, no ssh dance).
            candidates = candidate_advertise_addrs()
        store.set(scope, str(rank),
                  ",".join(f"{a}:{port}" for a in candidates).encode())

        # Accept connections from higher ranks while dialing lower ranks.
        accept_err: List[BaseException] = []
        self._accept_done = threading.Event()
        n_expected = size - 1 - rank
        acceptor = threading.Thread(
            target=self._accept_loop, args=(n_expected, accept_err, timeout),
            name=f"hvd-tcp-accept-r{rank}", daemon=True)
        acceptor.start()

        lower = [str(j) for j in range(rank)]
        addrs = store.wait(scope, lower, timeout=timeout) if lower else {}
        for j in range(rank):
            endpoints = []
            for spec in addrs[str(j)].decode().split(","):
                host, p = spec.rsplit(":", 1)
                endpoints.append((host, int(p)))
            self._peers[j] = _Peer(
                self._dial_peer(j, endpoints, timeout))

        # The acceptor thread stays alive past the quota to service late
        # dial retries (see _accept_loop), so wait on its quota event, not
        # the thread itself.
        self._accept_done.wait(timeout=timeout)
        if accept_err:
            raise HorovodInternalError(f"tcp mesh accept failed: {accept_err[0]}")
        if len(self._peers) != size - 1:
            raise HorovodInternalError(
                f"tcp mesh incomplete: have {len(self._peers)}/{size - 1} peers")

    # -- handshake ----------------------------------------------------------
    #
    # dialer:   HELLO + my_rank + target_rank [+ HMAC]  →
    # acceptor:                    ←  HELLO + its_rank + dialer_rank [+ HMAC]
    #
    # Carrying the intended TARGET lets the acceptor refuse (without
    # registering) a connection that reached the wrong machine — with
    # multi-addr advertisement a dial can land on another rank's listener,
    # and registering it would leave that rank holding a socket its dialer
    # is about to close.  The HMAC (when HOROVOD_SECRET_KEY is set) keeps
    # arbitrary LAN peers out of the data fabric (reference
    # network.py:50-85 role).

    def _hello_blob(self, my_rank: int, target_rank: int) -> bytes:
        blob = _HELLO + struct.pack("<II", my_rank, target_rank)
        if self._secret is not None:
            from ..common import secret as secret_mod

            blob += secret_mod.sign_blob(self._secret, blob)
        return blob

    def _check_hello(self, data: bytes) -> tuple:
        """Validate magic+sig; returns (peer_rank, intended_target)."""
        if data[:4] != _HELLO:
            raise HorovodInternalError("bad tcp mesh hello")
        if self._secret is not None:
            from ..common import secret as secret_mod

            if not secret_mod.verify_blob(self._secret, data[:12], data[12:]):
                raise HorovodInternalError("tcp mesh hello failed HMAC check")
        return struct.unpack("<II", data[4:12])

    def _hello_len(self) -> int:
        return 12 + (32 if self._secret is not None else 0)

    def _dial_peer(self, target: int, endpoints: List,
                   timeout: float) -> socket.socket:
        """Connect to one peer, racing the TCP connects to ALL advertised
        candidates concurrently (reference driver probe-and-intersect
        role, ``driver/driver_service.py:162-194``): on a multi-homed host
        a dead first candidate costs nothing — a reachable one wins the
        race instead of waiting out the dead one's timeout serially.

        Only the CONNECT races; the hello handshake runs serially on one
        socket at a time.  Losing sockets close before any hello, so the
        acceptor sees EOF and drops them without registering — racing full
        handshakes could leave dialer and acceptor registered on
        *different* winners for the same rank pair."""
        import queue as queue_mod

        deadline = time.monotonic() + timeout
        last: List[Optional[Exception]] = [None]
        # Endpoints with a connect attempt still in flight: each 50 ms retry
        # must NOT stack a fresh 5 s-timeout thread on a dead candidate the
        # previous retry is still waiting out (threads/fds would accumulate
        # linearly in retry count otherwise).
        inflight: set = set()
        inflight_lock = threading.Lock()

        def connect_all() -> List[socket.socket]:
            if len(endpoints) == 1:
                host, port = endpoints[0]
                try:
                    return [socket.create_connection(
                        (host, port), timeout=min(5.0, timeout))]
                except OSError as e:
                    last[0] = e
                    return []
            results: "queue_mod.Queue" = queue_mod.Queue()

            def conn(host, port):
                try:
                    results.put(socket.create_connection(
                        (host, port), timeout=min(5.0, timeout)))
                except OSError as e:
                    last[0] = e
                    results.put(None)
                finally:
                    with inflight_lock:
                        inflight.discard((host, port))

            spawned = 0
            for host, port in endpoints:
                with inflight_lock:
                    if (host, port) in inflight:
                        continue
                    inflight.add((host, port))
                threading.Thread(target=conn, args=(host, port),
                                 name=f"hvd-tcp-dial-r{target}",
                                 daemon=True).start()
                spawned += 1
            socks = []
            received = 0
            for _ in range(spawned):
                try:
                    s = results.get(
                        timeout=max(0.1, deadline - time.monotonic()))
                except queue_mod.Empty:
                    break
                received += 1
                if s is not None:
                    socks.append(s)
                elif socks:
                    break  # have a candidate; don't wait for stragglers
            if received < spawned:
                # Straggler threads will still deposit sockets after we
                # return — reap and close them so they don't leak until
                # queue GC (ADVICE r3).
                remaining = spawned - received

                def reap():
                    for _ in range(remaining):
                        try:
                            s = results.get(timeout=6.0)
                        except queue_mod.Empty:
                            return
                        if s is not None:
                            s.close()

                threading.Thread(target=reap, name="hvd-tcp-dial-reap",
                                 daemon=True).start()
            return socks

        while time.monotonic() < deadline:
            socks = connect_all()
            winner: Optional[socket.socket] = None
            for i, sock in enumerate(socks):
                if winner is not None:
                    sock.close()  # pre-hello close: acceptor drops on EOF
                    continue
                try:
                    winner = self._handshake(sock, target)
                except (OSError, HorovodInternalError) as e:
                    last[0] = e
                    sock.close()
            if winner is not None:
                return winner
            time.sleep(0.05)
        raise HorovodInternalError(
            f"could not connect to rank {target} at {endpoints}: {last[0]}")

    def _handshake(self, sock: socket.socket, target: int) -> socket.socket:
        _configure(sock)
        # Bounded handshake: an endpoint that accepts but never answers
        # must fall through to the next candidate, not hang the mesh
        # (symmetric with the accept side).
        sock.settimeout(5.0)
        sock.sendall(self._hello_blob(self.rank, target))
        got, _ = self._check_hello(_recv_exact(sock, self._hello_len()))
        if got != target:
            raise HorovodInternalError(f"peer answered as rank {got}")
        sock.settimeout(None)
        return sock

    def _accept_one(self, sock: socket.socket) -> bool:
        """Handshake one inbound connection; True when a NEW peer was
        registered (duplicates and misroutes are answered, then closed)."""
        try:
            _configure(sock)
            sock.settimeout(5.0)
            peer_rank, intended = self._check_hello(
                _recv_exact(sock, self._hello_len()))
            # Always answer with our identity so a misrouted dialer
            # learns who it reached and falls through to its next
            # candidate; only register connections MEANT for us.
            sock.sendall(self._hello_blob(self.rank, peer_rank))
            if intended != self.rank:
                sock.close()
                return False
            sock.settimeout(None)
        except (OSError, HorovodInternalError):
            # Unauthenticated or malformed connection: drop it
            # without counting toward the expected peer set.
            sock.close()
            return False
        if peer_rank not in self._peers:
            self._peers[peer_rank] = _Peer(sock)
            return True
        sock.close()
        return False

    def _accept_loop(self, n_expected: int, err: List[BaseException],
                     timeout: float) -> None:
        try:
            deadline = time.monotonic() + timeout
            registered = 0
            while registered < n_expected:
                self._listener.settimeout(
                    max(0.1, deadline - time.monotonic()))
                sock, _ = self._listener.accept()
                if self._accept_one(sock):
                    registered += 1
            self._accept_done.set()
        except BaseException as e:  # surfaced by constructor
            err.append(e)
            # Wake the constructor NOW: it waits on the event (the thread
            # outlives the quota), and an accept failure must fail
            # bring-up immediately, not after the full startup timeout.
            self._accept_done.set()
            return
        # Quota filled — keep servicing LATE dial retries until close.
        # Under load a dialer can abandon a half-done handshake (5 s
        # hello timeout) that we already counted, then retry; with nobody
        # accepting, that retry jams in the listen backlog and its rank
        # blocks in connect until the job dies — the silent-hang flavor
        # of the bring-up race.  Answering the hello (and closing the
        # duplicate) turns it into a fast PeerGoneError on whichever
        # socket lost, which the coordinated-abort plane then cleans up.
        while not self._closed:
            try:
                self._listener.settimeout(1.0)
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed (mesh teardown)
            self._accept_one(sock)

    # -- framed messaging ---------------------------------------------------

    @staticmethod
    def _crc32_timed(payload) -> int:
        """crc32 with its cost accounted to ``crc_verify_seconds_total``
        — ROADMAP item 2 (CRC off the hot path) needs the absolute cost
        measurable on live jobs, not only in bench sweeps.  The two clock
        reads are skipped entirely when metrics are off."""
        if not metrics.ENABLED:
            return zlib.crc32(payload) & 0xFFFFFFFF
        t0 = time.perf_counter()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        metrics.inc("crc_verify_seconds_total", time.perf_counter() - t0)
        return crc

    @property
    def deferred_digests(self) -> bool:
        """True when ring steps should use the shadow-digest path
        (``HOROVOD_WIRE_CRC`` on and ``HOROVOD_WIRE_CRC_SHADOW`` not
        disabled)."""
        return self.wire_crc and self.crc_shadow

    def deferred_digests_for(self, peer: int) -> bool:
        """Per-LINK form of :attr:`deferred_digests` — the seam the ring
        collectives ask so a mixed-transport mesh (LinkMesh) can answer
        differently per peer.  Both endpoints of a link answer alike
        (each transport's CRC knobs are env-propagated to all ranks), so
        the two directions of one ring step may differ but one link's
        framing never does.  On a plain TcpMesh every link agrees."""
        return self.deferred_digests

    def new_digest(self) -> digest_mod.StreamDigest:
        """Fresh chained digest for one direction of one ring step."""
        return digest_mod.StreamDigest(self.digest_algo)

    @staticmethod
    def _digest_timed(dig: digest_mod.StreamDigest, view) -> None:
        """``StreamDigest.update`` with its cost accounted to
        ``crc_shadow_seconds_total`` — the shadow path's counterpart of
        ``_crc32_timed``, so the deferred-digest cost stays measurable on
        live jobs next to the inline CRC's counter."""
        if not metrics.ENABLED:
            dig.update(view)
            return
        t0 = time.perf_counter()
        dig.update(view)
        metrics.inc("crc_shadow_seconds_total", time.perf_counter() - t0)

    @property
    def _abort(self) -> Optional[Tuple[int, int, str]]:
        return self._abort_state.value

    @_abort.setter
    def _abort(self, value: Optional[Tuple[int, int, str]]) -> None:
        self._abort_state.value = value

    def _check_alive(self, p: _Peer, peer: int) -> None:
        if self._abort is not None:
            raise CoordinatedAbortError(*self._abort)
        if p.dead is not None:
            raise PeerGoneError(peer, p.dead)

    def _mark_dead(self, p: _Peer, reason: str) -> None:
        if p.dead is None:
            p.dead = reason

    def send(self, peer: int, payload,
             digest: Optional[digest_mod.StreamDigest] = None,
             wire_dtype: int = 0, _check_frame: bool = False) -> None:
        """Frame and send one payload — any C-contiguous bytes-like object
        (memoryview over a numpy slice included), never copied: the frame
        header and the payload view go to the kernel as one vectored
        write.

        With ``digest`` (and the mesh CRC on), the frame goes out
        digest-DEFERRED: no inline CRC field — the payload is folded into
        ``digest`` right after the vectored write is handed to the
        kernel (the shadow slot: the fold runs while the bytes are on the
        wire), and the caller closes the step with
        :meth:`send_step_digest`.  ``wire_dtype`` stamps the compression
        dtype code into the header so peers that disagree on
        ``HOROVOD_WIRE_COMPRESSION`` fail loudly on receipt."""
        p = self._peer(peer)
        deferred = digest is not None and self.wire_crc
        with p.send_lock:
            self._check_alive(p, peer)
            try:
                payload = _as_byte_view(payload)
                wire = payload
                if faults.ACTIVE:
                    verdict = faults.inject(
                        "tcp.send", rank=self.rank, peer=peer,
                        payload=payload)
                    if verdict is True:
                        return  # injected frame drop
                    if isinstance(verdict, faults.SendMutation):
                        # truncate: the frame is self-consistent (header
                        # and CRC computed over the SHORT payload) — an
                        # application-level misframe for the parse layer.
                        # corrupt: wire_flips apply AFTER the CRC is
                        # computed — in-flight corruption for the CRC
                        # layer.
                        payload = _as_byte_view(verdict.payload)
                        wire = _as_byte_view(verdict.wire_bytes())
                flags = (wire_dtype << _WIRE_DTYPE_SHIFT) & _WIRE_DTYPE_MASK
                if deferred:
                    flags |= _DEFER_FLAG
                if _check_frame:
                    flags |= _DIGEST_FLAG
                header = _LEN.pack(len(payload) | flags)
                if self.wire_crc and not deferred:
                    header += _CRC.pack(self._crc32_timed(payload))
                self._send_bounded(p, [memoryview(header), wire])
                if deferred:
                    # Digest the LOGICAL payload, not the wire bytes: an
                    # injected corrupt flip mutates only the latter —
                    # exactly the disagreement the peer's chain must
                    # catch at the digest-check frame.
                    self._digest_timed(digest, payload)
                if not _check_frame:
                    # Digest-check frames are integrity metadata, not
                    # data payload — excluded like control frames so the
                    # zero-copy tests' exact byte accounting holds.
                    wire_stats.add("bytes_on_wire", len(payload))
                flight_recorder.record("frame", dir="send", peer=peer,
                                       nbytes=len(payload))
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"send to rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"send to rank {peer} failed: {e}") from e

    def _send_bounded(self, p: _Peer, bufs: List[memoryview]) -> None:
        """Vectored ``sendall`` with the same failure-plane waits as the
        recv side: a peer that is alive but has stopped READING (hung
        mid-step) fills the socket buffer and a plain sendall would block
        forever — TCP never errors on a live-but-idle peer.  Any bytes the
        peer's stack accepts reset the progress clock; the mesh-wide abort
        flag is observed every poll quantum.  No first-bytes arming
        needed: the kernel accepts into the receive buffer even while the
        peer app is still initializing, so bring-up stagger cannot trip
        this.

        ``bufs`` is a writev(2)-style list (typically ``[header,
        payload]``) pushed via ``sendmsg`` so header and payload reach the
        kernel in one syscall without ever being concatenated on the
        heap."""
        sock = p.sock
        bufs = [b for b in bufs if len(b)]
        use_sendmsg = hasattr(sock, "sendmsg")
        budget = self.progress_deadline
        deadline = (time.monotonic() + budget) if budget > 0 else None
        while bufs:
            if self._abort is not None:
                raise CoordinatedAbortError(*self._abort)
            if not _wait_writable(sock, _ABORT_POLL_SECS):
                if deadline is not None and time.monotonic() > deadline:
                    raise _ProgressStall(
                        f"no send progress for {budget:.0f}s "
                        f"(HOROVOD_TCP_PROGRESS_DEADLINE_SECS={budget:g})")
                continue
            try:
                r = sock.sendmsg(bufs) if use_sendmsg \
                    else sock.send(bufs[0])
            except BlockingIOError:
                continue  # lost the race to buffer space; re-poll
            while r > 0:
                if r >= len(bufs[0]):
                    r -= len(bufs[0])
                    bufs.pop(0)
                else:
                    bufs[0] = bufs[0][r:]
                    r = 0
            if deadline is not None:
                deadline = time.monotonic() + budget

    def recv(self, peer: int) -> bytes:
        """Receive one data frame, materialized as fresh ``bytes`` — the
        control/negotiation-plane primitive.  The data plane uses
        :meth:`recv_into` instead, which lands the payload straight in a
        caller-owned buffer with no heap materialization."""
        p = self._peer(peer)
        with p.recv_lock:
            self._check_alive(p, peer)
            try:
                if faults.ACTIVE:
                    faults.inject("tcp.recv", rank=self.rank, peer=peer)
                while True:
                    hdr = self._recv_header(p, peer)
                    if hdr.ctrl:
                        self._consume_control_frame(p, peer, hdr.size,
                                                    hdr.crc)
                        continue  # stale control frame: keep reading
                    if hdr.deferred or hdr.check or hdr.wire_dtype:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"flagged data frame from rank {peer} on the "
                            f"control recv path (deferred={hdr.deferred}, "
                            f"check={hdr.check}, "
                            f"wire_dtype={hdr.wire_dtype}): wire-CRC/"
                            "compression framing skew between peers; "
                            "aborting, resync is impossible by design"))
                    payload = self._recv_bounded(p, hdr.size)
                    p.frames_in += 1
                    if hdr.crc is not None:
                        got = self._crc32_timed(payload)
                        if got != hdr.crc:
                            self._poison_stream(
                                p, peer,
                                FrameCorruptError(peer, p.frames_in,
                                                  hdr.crc, got))
                    wire_stats.add("bytes_on_wire", hdr.size)
                    flight_recorder.record("frame", dir="recv", peer=peer,
                                           nbytes=hdr.size)
                    return payload
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"recv from rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"recv from rank {peer} failed: {e}") from e

    def recv_into(self, peer: int, dest,
                  digest: Optional[digest_mod.StreamDigest] = None,
                  wire_dtype: int = 0) -> int:
        """Receive one data frame's payload directly into ``dest`` (a
        writable C-contiguous bytes-like — typically a memoryview over a
        numpy staging slice); returns the payload size.

        Zero-copy contract: bytes go from the kernel straight into
        ``dest`` via ``socket.recv_into``, and the wire CRC is folded
        incrementally over each landed span (``zlib.crc32`` accepts
        memoryviews), so integrity stays default-on with no intermediate
        buffer.  The frame must fill ``dest`` EXACTLY: the caller sized it
        from the same negotiated layout the sender framed from, so any
        mismatch (a truncating fault, a desynced negotiation) poisons the
        stream like a CRC failure — reading on after a misframe would
        turn one bad frame into positional desync.

        With ``digest``, the frame is expected digest-DEFERRED (no inline
        CRC field): the landed payload is folded into ``digest`` — on the
        helper thread when posted via :meth:`recv_into_async`, i.e. in
        the shadow of the main thread's reduction — and the caller
        settles integrity with :meth:`verify_step_digest`.  ``wire_dtype``
        is the compression dtype code this rank expects; any header
        disagreement (deferred-ness or dtype code) poisons the stream —
        config/version skew must fail loudly, not decode garbage.

        Control frames (coordinated abort) interleave transparently, as
        on the :meth:`recv` path."""
        p = self._peer(peer)
        dv = _as_writable_byte_view(dest)
        with p.recv_lock:
            self._check_alive(p, peer)
            try:
                if faults.ACTIVE:
                    faults.inject("tcp.recv", rank=self.rank, peer=peer)
                while True:
                    hdr = self._recv_header(p, peer)
                    if hdr.ctrl:
                        self._consume_control_frame(p, peer, hdr.size,
                                                    hdr.crc)
                        continue  # stale control frame: keep reading
                    if hdr.check:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"unexpected digest-check frame from rank "
                            f"{peer} where a data frame was due: ring-step "
                            "framing skew between peers; aborting"))
                    if hdr.deferred != (digest is not None):
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"data frame from rank {peer} is "
                            f"{'digest-deferred' if hdr.deferred else 'inline-CRC'} "
                            f"but this rank expected the "
                            f"{'deferred' if digest is not None else 'inline'} "
                            "wire-CRC path: HOROVOD_WIRE_CRC_SHADOW skew "
                            "between peers; aborting loudly"))
                    if hdr.wire_dtype != wire_dtype:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"data frame from rank {peer} carries wire "
                            f"dtype code {hdr.wire_dtype} but this rank "
                            f"expects {wire_dtype}: "
                            "HOROVOD_WIRE_COMPRESSION skew between peers "
                            "(mixed-version or mixed-config mesh); "
                            "aborting loudly instead of mis-decoding"))
                    if hdr.size != len(dv):
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"data frame from rank {peer} carries "
                            f"{hdr.size} bytes but the recv_into "
                            f"destination expects "
                            f"{len(dv)}: misframed stream (truncated or "
                            "desynced); aborting, resync is impossible by "
                            "design"))
                    got = self._recv_bounded_into(
                        p, dv, with_crc=hdr.crc is not None)
                    p.frames_in += 1
                    if hdr.crc is not None and got != hdr.crc:
                        self._poison_stream(
                            p, peer,
                            FrameCorruptError(peer, p.frames_in, hdr.crc,
                                              got))
                    if digest is not None:
                        # Shadow slot: the complete landed frame is
                        # folded here, off the main thread's serial path.
                        self._digest_timed(digest, dv)
                    wire_stats.add("bytes_on_wire", hdr.size)
                    flight_recorder.record("frame", dir="recv", peer=peer,
                                           nbytes=hdr.size)
                    return hdr.size
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"recv from rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"recv from rank {peer} failed: {e}") from e

    def _consume_control_frame(self, p: _Peer, peer: int, size: int,
                               crc: Optional[int]) -> None:
        """Read, CRC-verify, and handle one control frame — shared by the
        materializing ``recv`` and the zero-copy ``recv_into`` so the two
        receive paths cannot diverge.  Returns normally only for STALE
        control frames (``_handle_control`` discards them); control
        traffic is deliberately NOT counted in ``bytes_on_wire`` on
        either side (see ``CounterStats``)."""
        payload = self._recv_bounded(p, size)
        p.frames_in += 1
        if crc is not None:
            got = self._crc32_timed(payload)
            if got != crc:
                self._poison_stream(
                    p, peer,
                    FrameCorruptError(peer, p.frames_in, crc, got))
        self._handle_control(payload, peer)

    def _recv_header(self, p: _Peer, peer: int) -> _FrameHeader:
        """Read and decode one frame header (flag bits per the module
        docstring).  The inline CRC field is present only when the mesh
        CRC is on AND the frame is not digest-deferred."""
        n = _LEN.unpack(self._recv_bounded(p, _LEN.size))[0]
        size = n & ~_FLAGS_MASK
        if size > _MAX_FRAME_BYTES:
            self._poison_stream(p, peer, HorovodInternalError(
                f"frame header from rank {peer} claims "
                f"{size} bytes (cap {_MAX_FRAME_BYTES}): "
                "corrupted length word; aborting before "
                "allocating it"))
        deferred = bool(n & _DEFER_FLAG)
        crc = _CRC.unpack(self._recv_bounded(p, _CRC.size))[0] \
            if self.wire_crc and not deferred else None
        return _FrameHeader(bool(n & _CTRL_FLAG), deferred,
                            bool(n & _DIGEST_FLAG),
                            (n & _WIRE_DTYPE_MASK) >> _WIRE_DTYPE_SHIFT,
                            size, crc)

    def send_step_digest(self, peer: int, dig: digest_mod.StreamDigest,
                         frames: int) -> None:
        """Close one deferred ring-step direction: emit the digest-check
        frame carrying (algo, chained digest, frame count), itself
        inline-CRC'd — the check frame IS the integrity settlement, so it
        never defers."""
        self.send(peer, digest_mod.pack_check(dig, frames),
                  _check_frame=True)

    def verify_step_digest(self, peer: int, dig: digest_mod.StreamDigest,
                           frames: int) -> None:
        """Read the peer's digest-check frame and compare it against the
        locally chained ``dig``; any disagreement — digest value, frame
        count, or algorithm — poisons the stream exactly like an inline
        CRC mismatch (corrupted data never escapes the collective that
        received it).  Must run strictly after every recv of the step
        completed (the ring waits each ``PendingRecv``), so the helper
        thread is quiescent for this peer and the check frame is next in
        FIFO order."""
        p = self._peer(peer)
        with p.recv_lock:
            self._check_alive(p, peer)
            try:
                while True:
                    hdr = self._recv_header(p, peer)
                    if hdr.ctrl:
                        self._consume_control_frame(p, peer, hdr.size,
                                                    hdr.crc)
                        continue  # stale control frame: keep reading
                    if not hdr.check:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"expected a digest-check frame from rank "
                            f"{peer} to close the ring step but got a "
                            "data frame: step framing skew between "
                            "peers; aborting"))
                    if hdr.size != digest_mod.CHECK_SIZE:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"digest-check frame from rank {peer} "
                            f"carries {hdr.size} bytes (expected "
                            f"{digest_mod.CHECK_SIZE}): misframed stream "
                            "(truncated or desynced); aborting"))
                    payload = self._recv_bounded(p, hdr.size)
                    p.frames_in += 1
                    if hdr.crc is not None:
                        got = self._crc32_timed(payload)
                        if got != hdr.crc:
                            self._poison_stream(
                                p, peer,
                                FrameCorruptError(peer, p.frames_in,
                                                  hdr.crc, got))
                    algo, value, count = digest_mod.unpack_check(payload)
                    if algo != dig.algo:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"digest-check frame from rank {peer} uses "
                            f"wire digest "
                            f"{digest_mod.algo_name(algo)!r} but this "
                            f"rank runs "
                            f"{digest_mod.algo_name(dig.algo)!r}: "
                            "HOROVOD_WIRE_DIGEST skew between peers"))
                    if count != frames or value != dig.value():
                        # Same failure plane as an inline CRC mismatch:
                        # some frame in the step (or the step framing
                        # itself) went bad and resync is impossible.
                        self._poison_stream(
                            p, peer,
                            FrameCorruptError(peer, p.frames_in, value,
                                              dig.value()))
                    flight_recorder.record("frame", dir="recv", peer=peer,
                                           nbytes=hdr.size)
                    return
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"recv from rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"recv from rank {peer} failed: {e}") from e

    def _recv_bounded(self, p: _Peer, n: int) -> bytes:
        buf = bytearray(n)
        self._recv_bounded_into(p, memoryview(buf), with_crc=False)
        return bytes(buf)

    def _recv_bounded_into(self, p: _Peer, view: memoryview,
                           with_crc: bool) -> Optional[int]:
        """``_recv_exact`` into a caller view, with the failure-plane
        waits: wakes every ``_ABORT_POLL_SECS`` to observe a mesh-wide
        abort (which may have arrived on a different peer's link) and
        enforces the progress deadline — *any* bytes received reset it.
        The deadline only applies once the peer has EVER sent bytes (see
        ``_Peer``): the first-ever frame may legitimately lag the whole
        bring-up stagger.

        With ``with_crc``, folds CRC32 over each landed span as it
        arrives and returns the final digest — the incremental-CRC half of
        the zero-copy recv path."""
        sock = p.sock
        n = len(view)
        got = 0
        crc = 0
        # Incremental-CRC accounting: perf_counter pairs per landed span
        # (tens of ns each, vs ~µs of crc32 per span), folded into ONE
        # counter update per frame; skipped entirely with metrics off.
        measure_crc = with_crc and metrics.ENABLED
        crc_secs = 0.0
        budget = self.progress_deadline
        deadline = (time.monotonic() + budget) \
            if budget > 0 and p.ever_received else None
        while got < n:
            if self._abort is not None:
                raise CoordinatedAbortError(*self._abort)
            if not _wait_readable(sock, _ABORT_POLL_SECS):
                if deadline is not None and time.monotonic() > deadline:
                    raise _ProgressStall(
                        f"no recv progress for {budget:.0f}s "
                        f"(HOROVOD_TCP_PROGRESS_DEADLINE_SECS={budget:g})")
                continue
            try:
                r = sock.recv_into(view[got:], n - got)
            except BlockingIOError:
                continue  # readable raced away (non-blocking socket)
            if r == 0:
                raise OSError("peer closed connection")
            if with_crc:
                if measure_crc:
                    tc = time.perf_counter()
                    crc = zlib.crc32(view[got:got + r], crc)
                    crc_secs += time.perf_counter() - tc
                else:
                    crc = zlib.crc32(view[got:got + r], crc)
            got += r
            if not p.ever_received:
                p.ever_received = True
                if budget > 0:
                    deadline = time.monotonic() + budget
            elif deadline is not None:
                deadline = time.monotonic() + budget
        if measure_crc and crc_secs:
            metrics.inc("crc_verify_seconds_total", crc_secs)
        return (crc & 0xFFFFFFFF) if with_crc else None

    def _poison_stream(self, p: _Peer, peer: int,
                       err: HorovodInternalError) -> None:
        """The stream from ``peer`` is poisoned (wire-CRC mismatch, or a
        length word claiming an absurd size).

        Resync is impossible by design — the framing after a corrupt
        frame cannot be trusted, so reading on would turn one bad byte
        into positional desync (the PR 2 failure mode: survivors reading
        negotiation bytes as tensor data).  Mark the peer dead, broadcast
        the coordinated abort so every rank tears down at a frame
        boundary, and let the mesh epoch (elastic plane) recover."""
        flight_recorder.record("stream_poisoned", peer=peer,
                               error=str(err)[:300])
        self._mark_dead(p, str(err))
        self.send_abort(str(err))
        raise err

    def _handle_control(self, payload: bytes, peer: int) -> None:
        """Returns normally only for STALE control frames (discard)."""
        from ..core.messages import AbortFrame, is_abort_frame

        if not is_abort_frame(payload):
            raise HorovodInternalError(
                f"unknown control frame from rank {peer}")
        frame = AbortFrame.from_bytes(payload)
        if frame.epoch < self.epoch:
            log.warning(
                "discarding stale abort from rank %d (epoch %d < %d): %s",
                frame.origin_rank, frame.epoch, self.epoch, frame.reason)
            return
        metrics.inc("aborts_total", dir="received")
        flight_recorder.record("abort_received", origin=frame.origin_rank,
                               epoch=frame.epoch,
                               reason=frame.reason[:300])
        self._abort = (frame.epoch, frame.origin_rank, frame.reason)
        raise CoordinatedAbortError(frame.epoch, frame.origin_rank,
                                    frame.reason)

    def send_abort(self, reason: str, epoch: Optional[int] = None,
                   origin_rank: Optional[int] = None,
                   _relayed: bool = False) -> None:
        """Broadcast a coordinated abort over every surviving link.

        Best-effort and non-blocking-ish (bounded lock waits + socket
        timeouts): the caller is already tearing down and must not hang on
        a wedged peer.  Also flips this mesh's own abort flag so any local
        thread still blocked in a recv (e.g. the sendrecv helper) unblocks
        within one poll quantum.  ``origin_rank`` lets a RELAY of someone
        else's abort keep the original detector's identity.

        Under a LinkMesh, ``abort_relay`` redirects the broadcast to the
        facade so it reaches EVERY transport's links (``_relayed`` marks
        the facade's call back down and breaks the recursion)."""
        if self._closed or self.size == 1:
            return
        if not _relayed and self.abort_relay is not None:
            self.abort_relay(reason, epoch=epoch, origin_rank=origin_rank)
            return
        from ..core.messages import AbortFrame

        epoch = self.epoch if epoch is None else epoch
        origin_rank = self.rank if origin_rank is None else origin_rank
        payload = AbortFrame(epoch=epoch, origin_rank=origin_rank,
                             reason=reason).to_bytes()
        metrics.inc("aborts_total", dir="sent")
        flight_recorder.record("abort_broadcast", origin=origin_rank,
                               epoch=epoch, reason=reason[:300])
        if self._abort is None:
            self._abort = (epoch, origin_rank, reason)
        for peer, p in list(self._peers.items()):
            # Dead-marked links are still TRIED: a recv-deadline mark only
            # proves the peer stopped sending — its recv direction may be
            # fine (e.g. hung mid-step), and the abort is exactly what
            # unblocks it.  A truly torn socket errors out immediately.
            if not p.send_lock.acquire(timeout=2.0):
                continue  # a wedged send holds the lock; skip this link
            try:
                p.sock.settimeout(5.0)
                header = _LEN.pack(len(payload) | _CTRL_FLAG)
                if self.wire_crc:
                    header += _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF)
                # hvdlint: disable=HVD001 -- bounded by the settimeout(5.0)
                # above; the teardown path must push the abort even though
                # the non-blocking poll loops are already torn down.
                p.sock.sendall(header)
                p.sock.sendall(payload)  # hvdlint: disable=HVD001 -- same 5s socket timeout bounds this write
            except OSError as e:
                self._mark_dead(p, f"abort send failed: {e}")
            finally:
                try:
                    p.sock.setblocking(False)  # peers stay non-blocking
                except OSError:
                    pass
                p.send_lock.release()

    def sendrecv(self, send_to: int, payload, recv_from: int) -> bytes:
        """Concurrent send+recv — the ring-collective step primitive.

        A sequential send-then-recv deadlocks on rings once payloads exceed
        socket buffers (everyone blocked in sendall), so the recv runs on a
        persistent helper thread (not thread-per-call: this sits on the hot
        path, 2*(N-1) steps per fused response per cycle)."""
        done = threading.Event()
        box: List = [None, None]  # [result, error]

        def _recv():
            try:
                box[0] = self.recv(recv_from)
            except BaseException as e:  # noqa: BLE001
                box[1] = e
            finally:
                done.set()

        self._sr_submit(_recv)
        self.send(send_to, payload)
        done.wait()
        if box[1] is not None:
            raise box[1]
        return box[0]

    def recv_into_async(self, peer: int, dest,
                        digest: Optional[digest_mod.StreamDigest] = None,
                        wire_dtype: int = 0) -> PendingRecv:
        """Post a :meth:`recv_into` on the persistent helper thread and
        return a :class:`PendingRecv` handle — the segment-pipeline
        primitive: the collective layer posts the recv for segment k+1,
        sends its own segment, then reduces segment k while k+1 is still
        on the wire.

        Posts are FIFO on one helper thread, so posting recvs for
        segments k and k+1 back-to-back maps them onto the peer's frames
        in wire order — which also serializes ``digest`` updates in frame
        order without any extra locking."""
        done = threading.Event()
        box: List = [None, None]  # [nbytes, error]

        def _recv():
            try:
                box[0] = self.recv_into(peer, dest, digest=digest,
                                        wire_dtype=wire_dtype)
            except BaseException as e:  # noqa: BLE001
                box[1] = e
            finally:
                done.set()

        self._sr_submit(_recv)
        return PendingRecv(done, box)

    def sendrecv_into(self, send_to: int, payload, recv_from: int,
                      dest) -> int:
        """Zero-copy ``sendrecv``: concurrent send of ``payload`` (any
        bytes-like view) and recv of exactly ``len(dest)`` bytes straight
        into ``dest``.  Returns the received payload size."""
        pending = self.recv_into_async(recv_from, dest)
        self.send(send_to, payload)
        return pending.wait()

    def _sr_submit(self, task) -> None:
        if self._sr_thread is None or not self._sr_thread.is_alive():
            self._sr_queue = queue.SimpleQueue()
            self._sr_thread = threading.Thread(
                target=self._sr_loop, name="hvd-tcp-sendrecv", daemon=True)
            self._sr_thread.start()
        self._sr_queue.put(task)

    def _sr_loop(self) -> None:
        while True:
            task = self._sr_queue.get()
            if task is None:
                return
            try:
                task()
            except BaseException:  # noqa: BLE001 — a raising task must not
                # kill the loop: tasks already queued behind it would never
                # run and their callers would wait forever on completion
                # events nobody sets.  (sendrecv's own task catches its
                # errors into the result box; anything reaching here is a
                # foreign/broken submission.)
                log.error("sendrecv helper task raised", exc_info=True)

    def _peer(self, peer: int) -> _Peer:
        try:
            return self._peers[peer]
        except KeyError:
            raise HorovodInternalError(
                f"rank {self.rank} has no connection to rank {peer}") from None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._sr_thread is not None and self._sr_thread.is_alive():
            self._sr_queue.put(None)
        if self._listener is not None:
            self._listener.close()
        for p in self._peers.values():
            try:
                p.sock.close()
            except OSError:
                pass


def _default_advertise_addr() -> str:
    # Best-effort routable address; loopback fallback for single-host jobs.
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def candidate_advertise_addrs() -> List[str]:
    """All plausible addresses of this host, best first.

    Multi-host jobs (HOROVOD_CROSS_SIZE > 1) exclude loopback: a remote
    peer dialing 127.0.0.1 would reach itself.  Single-host jobs put
    loopback first — always right and fastest.
    """
    from ..common import env as env_mod

    multi_host = env_mod.get_int(env_mod.HOROVOD_CROSS_SIZE, 1) > 1
    addrs: List[str] = []
    primary = _default_advertise_addr()
    if primary != "127.0.0.1":
        addrs.append(primary)
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            a = info[4][0]
            if a not in addrs and not a.startswith("127."):
                addrs.append(a)
    except OSError:
        pass
    if multi_host:
        return addrs or [primary]
    return ["127.0.0.1"] + addrs


def _configure(sock: socket.socket) -> None:
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise HorovodInternalError("peer closed connection")
        got += r
    return bytes(buf)
