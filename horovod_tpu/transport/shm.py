"""Shared-memory intra-host transport — the zero-syscall sibling of tcp.py.

Colocated ranks talking over TCP loopback pay two syscalls and two kernel
copies per frame for bytes that never leave the machine (ROADMAP item 3:
the measured bottleneck of every intra-host sweep).  This module moves
those frames through per-peer-pair POSIX shared-memory segments instead:
each unordered rank pair {i, j} on one host shares ONE segment created by
the lower rank (name published through the rendezvous KV, exactly like
the TCP mesh publishes its listen addresses), holding two single-
producer/single-consumer byte rings — one per direction.  A frame send is
one ``memcpy`` into the ring; a ``recv_into`` is one ``memcpy`` out into
the caller's staging view.  No sockets, no syscalls, no kernel copies on
the steady-state path.

Frame discipline is IDENTICAL to ``transport/tcp.py`` — the same
``<Q len|flags>[<I crc32>]`` header, the same control/deferred/digest-
check/wire-dtype flag bits (imported from ``transport/frame_bits.py``,
the registry that owns the wire constants; HVD008), the same
poisoned-stream and coordinated-abort
semantics, the same progress deadline (reusing
``HOROVOD_TCP_PROGRESS_DEADLINE_SECS`` so the failure plane has ONE knob,
not one per transport).  The only intentional difference:
``HOROVOD_SHM_CRC`` defaults OFF — these bytes never cross a wire, and a
bit flip in host RAM is ECC's jurisdiction, so the default buys the
syscall win twice (no CRC pass either).  Turning it on restores the full
integrity plane, shadow digests included, for chaos tests and stomper
hunts.

Ring protocol: per direction a monotonic u64 ``head`` (total bytes ever
written, writer-owned) and u64 ``tail`` (total bytes ever read,
reader-owned) live in separate cache lines of the segment header;
``head - tail`` is the unread span, ``capacity - (head - tail)`` the free
span, and positions wrap modulo capacity.  Frames LARGER than the ring
stream through in chunks, so capacity bounds memory, never frame size.
Each side updates only its own counter and stores it strictly AFTER the
byte copy it covers — under CPython's bytecode ordering plus x86-64 TSO
an aligned 8-byte store is atomic and never reordered before the data
writes it publishes, which is the entirety of the memory model this
relies on.

Failure plane: a blocked ring wait wakes every ~0.5 ms (an Event nap, not
a sleep-under-lock) to observe the mesh-wide abort flag, enforce the
progress deadline, and — the shm equivalent of a TCP RST — probe the
peer's PID (stamped into the segment header at create/attach time) so a
SIGKILLed neighbour converts to ``PeerGoneError`` within one poll
quantum instead of a deadline timeout.  Orphan hygiene is layered:
attachers unregister from ``resource_tracker`` so exactly one process
(the creator) owns the unlink, the creator unlinks on ``close()``, the
creator's resource tracker unlinks after a hard kill, and the runner
sweeps ``/dev/shm`` by dead-worker PID (segment names embed the creator
PID) as the deterministic backstop.
"""

from __future__ import annotations

import ctypes
import errno
import glob
import os
import queue
import struct
import threading
import time
import uuid
import zlib
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, Iterable, List, Optional, Tuple

from . import digest as digest_mod
from ..common import faults
from ..common.exceptions import (
    CoordinatedAbortError,
    FrameCorruptError,
    HorovodInternalError,
    PeerGoneError,
)
from ..common.logging_util import get_logger
from ..core import flight_recorder, metrics
from .frame_bits import (
    _CRC,
    _CTRL_FLAG,
    _DEFER_FLAG,
    _DIGEST_FLAG,
    _FLAGS_MASK,
    _FrameHeader,
    _LEN,
    _MAX_FRAME_BYTES,
    _WIRE_DTYPE_MASK,
    _WIRE_DTYPE_SHIFT,
)
from .store import Store
from .tcp import (
    _ABORT_POLL_SECS,
    _ProgressStall,
    AbortState,
    PendingRecv,
    _as_byte_view,
    _as_writable_byte_view,
)

log = get_logger("horovod_tpu.transport.shm")

#: Segment names are ``hvdshm-<creator pid>-e<epoch>-<lo>x<hi>-<nonce>`` so
#: leak scans and the runner's dead-PID sweep can address them by glob
#: without attaching.
SEG_PREFIX = "hvdshm-"

_SHM_MAGIC = 0x48565348  # "HVSH"
# v2: the per-direction doorbell split into two single-writer bells
# (data bell / space bell) after hvd-mck exhibited an ABA lost-update on
# the shared-bell layout — see the doorbell comment below.  Version skew
# fails loudly at attach, like every other layout change.
_SHM_VERSION = 2

# Segment header layout (little-endian).  Direction counters sit 64 bytes
# apart so the two writers never share a cache line.
_OFF_MAGIC = 0          # u32
_OFF_VERSION = 4        # u32
_OFF_CAP = 8            # u64 ring capacity per direction
_OFF_CREATOR_PID = 16   # u64 lower rank's PID (stamped before publish)
_OFF_ATTACHER_PID = 24  # u64 higher rank's PID (0 until attach)
_OFF_L2H_HEAD = 64      # u64 lower→higher bytes written (lower owns)
_OFF_L2H_TAIL = 128     # u64 lower→higher bytes read (higher owns)
_OFF_H2L_HEAD = 192     # u64 higher→lower bytes written (higher owns)
_OFF_H2L_TAIL = 256     # u64 higher→lower bytes read (lower owns)
# Four doorbells, ONE WRITER EACH (see the doorbell comment below for
# why the shared-bell layout was an ABA bug): a direction's data bell is
# bumped only by its sender (waking a receiver out of data), its space
# bell only by its receiver (waking a sender out of ring space).
_OFF_L2H_DATA_BELL = 288   # u32: bumped by lower (L2H sender) only
_OFF_L2H_SPACE_BELL = 296  # u32: bumped by higher (L2H receiver) only
_OFF_H2L_DATA_BELL = 304   # u32: bumped by higher (H2L sender) only
_OFF_H2L_SPACE_BELL = 312  # u32: bumped by lower (H2L receiver) only
_RINGS_OFF = 320        # L2H ring, then H2L ring at +capacity

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# Blocked ring waits sleep on a FUTEX DOORBELL: each direction carries
# two u32 bells, each with exactly ONE writer — the sender bumps the
# data bell (with a FUTEX_WAKE) after publishing head advances, the
# receiver bumps the space bell after publishing tail advances — and a
# rank out of data/space does a kernel FUTEX_WAIT on (peer's bell ==
# value-seen-before-checking).  That gives shm the property the TCP path
# gets from blocking sockets — the waiter wakes the instant bytes (or
# space) land, with zero polling — which is what lets shm beat loopback
# TCP on wakeup latency instead of losing every blocked wait to a poll
# quantum.  The wait is still bounded (_BELL_WAIT_SECS) so the abort
# flag and the peer-PID probe keep their poll cadence, and the
# bump-after-store protocol makes lost wakeups impossible: a store is
# visible before its bump (x86-64 TSO), so a waiter either sees the
# progress or sees a moved bell and returns immediately.  That claim is
# no longer prose-only: `hvd-mck` explores every bounded interleaving of
# sender_steps/receiver_steps below and proves it under a TSO
# store-buffer model — and exhibits the missed wakeup under a weaker
# model, so the fence the protocol leans on is a machine-checked fact
# (tools/mck; docs/static_analysis.md).
#
# Why one writer per bell: v1 had a single bell per direction that BOTH
# ends incremented with a plain load+store (no atomic RMW exists for a
# Python shm buffer).  hvd-mck found the resulting ABA the first time it
# ran: one end's increment, delayed in its store buffer (or just
# preempted between load and store), lands late, clobbers the other
# end's bumps, and can restore the exact value a waiter is about to
# FUTEX_WAIT on — the waiter sleeps a full bounded wait with its data
# already published.  Splitting the bell by writer makes the lost update
# structurally impossible: an increment is a data race only if the word
# has a second writer.
# Where the futex syscall is unavailable (non-Linux,
# unknown arch), waits fall back to a two-phase nap ramp: ~one scheduler
# tick for the first _RING_NAP_RAMP polls, then the long nap so a rank
# stalled across a whole negotiation naps instead of spinning.
_BELL_WAIT_SECS = 0.05
_RING_NAP_SECS = 0.0005
_RING_NAP_FAST_SECS = 0.00002
_RING_NAP_RAMP = 64

_FUTEX_WAIT = 0
_FUTEX_WAKE = 1
_SYS_FUTEX = {"x86_64": 202, "aarch64": 98}.get(os.uname().machine)


class _Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


def _futex_libc():
    if _SYS_FUTEX is None:
        return None
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        libc.syscall.restype = ctypes.c_long
        # Self-test: WAIT with a mismatched expected value must return
        # EAGAIN immediately — proves the syscall number and calling
        # convention before the data plane trusts them.
        word = ctypes.c_uint32(0)
        res = libc.syscall(_SYS_FUTEX, ctypes.byref(word), _FUTEX_WAIT,
                           1, None, None, 0)
        if res == -1 and ctypes.get_errno() == errno.EAGAIN:
            return libc
    except Exception:  # pragma: no cover - exotic libc
        pass
    return None


_LIBC = _futex_libc()


def _futex_wait(addr: int, expected: int, timeout_s: float) -> None:
    ts = _Timespec(int(timeout_s), int(timeout_s % 1.0 * 1e9))
    _LIBC.syscall(_SYS_FUTEX, ctypes.c_void_p(addr), _FUTEX_WAIT,
                  expected, ctypes.byref(ts), None, 0)


def _futex_wake(addr: int) -> None:
    _LIBC.syscall(_SYS_FUTEX, ctypes.c_void_p(addr), _FUTEX_WAKE,
                  0x7FFFFFFF, None, None, 0)


_MIN_RING_BYTES = 4096


# Control-word accessors — the ONLY code allowed to move raw structs
# against the header offsets (hvd-lint HVD009).  Every head/tail load and
# store, every bell read and write, and the magic/version words go
# through these four functions, so the set of shared-memory accesses the
# model checker must consider is closed by construction.
def _load_u64(buf, off: int) -> int:
    return _U64.unpack_from(buf, off)[0]


def _store_u64(buf, off: int, value: int) -> None:
    _U64.pack_into(buf, off, value)


def _load_u32(buf, off: int) -> int:
    return _U32.unpack_from(buf, off)[0]


def _store_u32(buf, off: int, value: int) -> None:
    _U32.pack_into(buf, off, value)


# -- ring protocol kernel (model-checked; see tools/mck) ----------------------
#
# The SPSC ring-advance logic is written ONCE, as pure generators over an
# abstract memory: every shared-memory access is one yielded op tuple, in
# exact program order, and the caller (the "driver") executes it against
# real segment memory — or, under ``hvd-mck``, against a model memory
# with an explicit store-buffer semantics.  The model-checked code IS the
# production code; there is no second copy to drift (the pre-extraction
# tree had exactly that bug: ``_abort_write`` re-derived the send run
# with a diverging per-RUN bell discipline).
#
# Op vocabulary (first element is the kind; the driver answers loads and
# polls through ``generator.send``):
#
#   (OP_POLL,)                   -> SIG_OK | SIG_ABORT   abort-flag check
#   (OP_LOAD, loc, tag)          -> int                  read a control word
#   (OP_STORE, loc, value[, tag])                        write a control word
#   (OP_COPY, idx, off, pos, run)                        move run bytes
#                                   segment idx [off:off+run] <-> ring
#                                   [pos:pos+run] (direction is the
#                                   driver's; this op publishes nothing)
#   (OP_WAIT, expected)                                  bounded sleep until
#                                   the peer's bell moves off ``expected``
#   (OP_WAKE, tag)                                       FUTEX_WAKE own bell
#
# ``loc`` is LOC_HEAD / LOC_TAIL / LOC_BELL_OWN / LOC_BELL_PEER, always
# the DIRECTION'S words (the sender's head is the receiver's head).  The
# two bell locs are role-relative: LOC_BELL_OWN is the single-writer
# bell this role bumps (the sender's data bell, the receiver's space
# bell), LOC_BELL_PEER the one it prechecks and waits on.  ``tag``
# labels bell traffic for the checker ("precheck", "prewait", "final",
# "abort"); production drivers ignore it.  The generator returns DONE or
# ABORTED.

OP_POLL = "poll"
OP_LOAD = "load"
OP_STORE = "store"
OP_COPY = "copy"
OP_WAIT = "wait"
OP_WAKE = "wake"

LOC_HEAD = "head"
LOC_TAIL = "tail"
LOC_BELL_OWN = "own_bell"
LOC_BELL_PEER = "peer_bell"

SIG_OK = "ok"
SIG_ABORT = "abort"

DONE = "done"
ABORTED = "aborted"


def bell_bump_steps(tag: str):
    """Publish pending head/tail advances on this role's doorbell: move
    the bell and wake its futex waiters.  The increment is a plain
    load+store — safe ONLY because each bell has one writer (this role),
    so the RMW can never race another increment.  hvd-mck caught the v1
    layout, where both ends bumped one shared bell, losing updates and
    ABA-ing a waiter to sleep; the single-writer split is what makes
    this non-atomic bump correct, and the checker now proves it."""
    bell = yield (OP_LOAD, LOC_BELL_OWN, tag)
    yield (OP_STORE, LOC_BELL_OWN, (bell + 1) & 0xFFFFFFFF, tag)
    yield (OP_WAKE, tag)


def sender_steps(cap: int, lens: List[int]):
    """Write ``sum(lens)`` bytes (the segments' concatenation) into the
    ring, chunking at ring-wrap and ring-full boundaries.

    Data bytes land (OP_COPY) strictly BEFORE the head store that
    publishes them — under CPython's bytecode ordering plus x86-64 TSO an
    aligned 8-byte store is atomic and never reordered before the data
    writes it covers, which is the entirety of the memory model this
    relies on, and ``hvd-mck`` checks exactly that claim: the ``tso``
    model proves the protocol, the ``weak`` model (store-store
    reordering allowed) finds the missed wakeup.

    The bell is bumped once per CALL, not per run: each wake is a
    syscall plus a scheduler event, and on a timeshared core every extra
    wake is another chance to lose the CPU mid-frame.  The exception is
    going to sleep with unpublished advances — the peer may be asleep
    waiting for exactly those bytes, so the bump is published first
    (publish-before-sleep)."""
    pending = False  # head advances not yet published on the bell
    for idx, n in enumerate(lens):
        off = 0
        while off < n:
            if (yield (OP_POLL,)) == SIG_ABORT:
                if pending:
                    yield from bell_bump_steps("abort")
                return ABORTED
            # Space-bell load FIRST, ring state second: if the peer
            # frees space and bumps between these two loads, the futex
            # sees a stale expected value and returns immediately
            # (EAGAIN).
            bell = yield (OP_LOAD, LOC_BELL_PEER, "precheck")
            head = yield (OP_LOAD, LOC_HEAD, None)
            free = cap - (head - (yield (OP_LOAD, LOC_TAIL, None)))
            if free == 0:
                # Publish deferred advances before sleeping — the
                # peer may be asleep waiting for exactly those bytes.
                if pending:
                    yield from bell_bump_steps("prewait")
                    pending = False
                    continue
                yield (OP_WAIT, bell)
                continue
            pos = head % cap
            run = min(n - off, free, cap - pos)
            yield (OP_COPY, idx, off, pos, run)
            yield (OP_STORE, LOC_HEAD, head + run)
            pending = True
            off += run
    if pending:
        yield from bell_bump_steps("final")
    return DONE


def receiver_steps(cap: int, lens: List[int]):
    """Read ``sum(lens)`` bytes out of the ring into the segments'
    concatenation — the mirror of :func:`sender_steps` with tail in the
    writer role: the copy out of the ring happens strictly BEFORE the
    tail store that frees the span (the sender may overwrite those bytes
    the moment the tail moves), and the bell discipline is identical
    (one bump per call, publish-before-sleep)."""
    pending = False  # tail advances not yet published on the bell
    for idx, n in enumerate(lens):
        got = 0
        while got < n:
            if (yield (OP_POLL,)) == SIG_ABORT:
                if pending:
                    yield from bell_bump_steps("abort")
                return ABORTED
            # Same load order as the send side: the peer's (data) bell
            # first, ring state second.
            bell = yield (OP_LOAD, LOC_BELL_PEER, "precheck")
            tail = yield (OP_LOAD, LOC_TAIL, None)
            avail = (yield (OP_LOAD, LOC_HEAD, None)) - tail
            if avail == 0:
                # Publish deferred drains before sleeping — the peer may
                # be asleep waiting for exactly that ring space.
                if pending:
                    yield from bell_bump_steps("prewait")
                    pending = False
                    continue
                yield (OP_WAIT, bell)
                continue
            pos = tail % cap
            run = min(n - got, avail, cap - pos)
            yield (OP_COPY, idx, got, pos, run)
            yield (OP_STORE, LOC_TAIL, tail + run)
            pending = True
            got += run
    if pending:
        yield from bell_bump_steps("final")
    return DONE


def segment_size(ring_bytes: int) -> int:
    """Total segment size for a per-direction ring capacity."""
    return _RINGS_OFF + 2 * ring_bytes


def sweep_dead_segments(pids: Iterable[int]) -> List[str]:
    """Unlink ``/dev/shm`` segments created by the given (dead) PIDs.

    The runner's deterministic backstop after a worker exits: the
    creator's own resource tracker also unlinks after a hard kill, but
    asynchronously — this sweep makes "kill mid-step leaves no residue"
    a property the chaos suite can assert immediately.  Only ever called
    with PIDs whose processes have exited."""
    removed: List[str] = []
    root = "/dev/shm"
    if not os.path.isdir(root):
        return removed
    for pid in pids:
        for path in glob.glob(os.path.join(root, f"{SEG_PREFIX}{pid}-*")):
            try:
                os.unlink(path)
            except OSError:
                continue
            removed.append(os.path.basename(path))
            log.warning("swept orphaned shm segment %s (creator pid %d)",
                        os.path.basename(path), pid)
    return removed


class _ShmPeer:
    """One attached pair segment, viewed from this rank's side."""

    __slots__ = ("shm", "created", "cap", "out_ring", "in_ring",
                 "out_head_off", "out_tail_off", "in_head_off",
                 "in_tail_off", "out_data_bell_off", "out_space_bell_off",
                 "in_data_bell_off", "in_space_bell_off",
                 "base_addr", "addr_anchor", "peer_pid_off",
                 "send_lock", "recv_lock", "dead", "ever_received",
                 "frames_in")

    def __init__(self, shm: shared_memory.SharedMemory, created: bool,
                 cap: int, i_am_lower: bool):
        self.shm = shm
        self.created = created
        self.cap = cap
        buf = shm.buf
        if i_am_lower:
            self.out_head_off = _OFF_L2H_HEAD
            self.out_tail_off = _OFF_L2H_TAIL
            self.in_head_off = _OFF_H2L_HEAD
            self.in_tail_off = _OFF_H2L_TAIL
            # Sending L2H: I bump its data bell, wait on its space bell;
            # receiving H2L: I wait on its data bell, bump its space bell.
            self.out_data_bell_off = _OFF_L2H_DATA_BELL
            self.out_space_bell_off = _OFF_L2H_SPACE_BELL
            self.in_data_bell_off = _OFF_H2L_DATA_BELL
            self.in_space_bell_off = _OFF_H2L_SPACE_BELL
            self.out_ring = buf[_RINGS_OFF:_RINGS_OFF + cap]
            self.in_ring = buf[_RINGS_OFF + cap:_RINGS_OFF + 2 * cap]
            self.peer_pid_off = _OFF_ATTACHER_PID
        else:
            self.out_head_off = _OFF_H2L_HEAD
            self.out_tail_off = _OFF_H2L_TAIL
            self.in_head_off = _OFF_L2H_HEAD
            self.in_tail_off = _OFF_L2H_TAIL
            self.out_data_bell_off = _OFF_H2L_DATA_BELL
            self.out_space_bell_off = _OFF_H2L_SPACE_BELL
            self.in_data_bell_off = _OFF_L2H_DATA_BELL
            self.in_space_bell_off = _OFF_L2H_SPACE_BELL
            self.out_ring = buf[_RINGS_OFF + cap:_RINGS_OFF + 2 * cap]
            self.in_ring = buf[_RINGS_OFF:_RINGS_OFF + cap]
            self.peer_pid_off = _OFF_CREATOR_PID
        # Futex doorbells need the segment's MAPPED address; the ctypes
        # anchor pins a buffer export that close() must drop before the
        # mmap can unmap.
        if _LIBC is not None:
            self.addr_anchor = ctypes.c_ubyte.from_buffer(buf)
            self.base_addr = ctypes.addressof(self.addr_anchor)
        else:
            self.addr_anchor = None
            self.base_addr = 0
        self.send_lock = threading.Lock()
        self.recv_lock = threading.Lock()
        # Same failure-plane state as tcp._Peer: first failure marks the
        # peer dead, the recv deadline arms on first bytes, frames_in is
        # FrameCorruptError's diagnostic context.
        self.dead: Optional[str] = None
        self.ever_received = False
        self.frames_in = 0

    def wake(self, off: int) -> None:
        """FUTEX_WAKE the direction's bell waiters (the OP_WAKE half of
        :func:`bell_bump_steps` — the bell increment itself is a plain
        OP_STORE the driver already executed)."""
        if self.base_addr:
            _futex_wake(self.base_addr + off)
            # FUTEX_WAKE has no sync-wakeup hint (the thing a loopback
            # sendmsg gets for free), so on a timeshared core the woken
            # peer would otherwise sit runnable until this rank's slice
            # ends.  Yielding right after the wake hands the core over —
            # with idle cores it is a near-no-op.
            os.sched_yield()

    def bell_wait(self, off: int, seen: int, naps: int,
                  nap_event: threading.Event) -> int:
        """Sleep until the direction's bell moves off ``seen`` (or the
        bounded timeout / fallback nap elapses).  Returns the updated
        fallback nap counter."""
        if self.base_addr:
            _futex_wait(self.base_addr + off, seen, _BELL_WAIT_SECS)
            return naps
        nap_event.wait(_RING_NAP_FAST_SECS if naps < _RING_NAP_RAMP
                       else _RING_NAP_SECS)
        return naps + 1


class ShmMesh:
    """Framed shared-memory fabric between colocated ranks.

    ``peers`` is the subset of global ranks this mesh serves (the
    LinkMesh's intra-host set); ``size`` stays the WORLD size so epoch
    and abort semantics match the TCP mesh exactly.  The surface is the
    TcpMesh surface — send/recv/recv_into/recv_into_async/sendrecv/
    sendrecv_into/step digests/send_abort/close — so the selection layer
    can route per link without the collectives knowing which fabric they
    ride."""

    def __init__(self, rank: int, size: int, store: Store,
                 peers: Iterable[int], scope: str = "shm",
                 timeout: float = 60.0,
                 epoch: Optional[int] = None,
                 progress_deadline: Optional[float] = None,
                 abort_state: Optional[AbortState] = None,
                 ring_bytes: Optional[int] = None):
        from ..common import env as env_mod

        self.rank = rank
        self.size = size
        self._peers: Dict[int, _ShmPeer] = {}
        self._closed = False
        self._sr_thread: Optional[threading.Thread] = None
        self._sr_queue: Optional[queue.SimpleQueue] = None
        self.epoch = env_mod.get_epoch() if epoch is None else epoch
        # One deadline knob for the whole failure plane (see module
        # docstring): shm reuses the TCP progress deadline.
        self.progress_deadline = env_mod.get_float(
            env_mod.HOROVOD_TCP_PROGRESS_DEADLINE,
            env_mod.DEFAULT_TCP_PROGRESS_DEADLINE_SECS) \
            if progress_deadline is None else progress_deadline
        # Default OFF — the one deliberate divergence from TCP (module
        # docstring).  With it on, the shadow-digest machinery applies
        # unchanged.
        self.wire_crc = env_mod.get_bool(env_mod.HOROVOD_SHM_CRC, False)
        self.crc_shadow = env_mod.get_bool(
            env_mod.HOROVOD_WIRE_CRC_SHADOW, True)
        self.digest_algo = digest_mod.algo_from_name(
            env_mod.get_str(env_mod.HOROVOD_WIRE_DIGEST, "fold64")
            or "fold64")
        self._abort_state = abort_state if abort_state is not None \
            else AbortState()
        self.abort_relay = None
        # Nap timer for blocked ring waits: an Event, set only on abort/
        # close so every napping thread wakes instantly — never a bare
        # sleep under a peer lock (HVD001's jurisdiction).
        self._nap = threading.Event()
        cap = env_mod.get_int(env_mod.HOROVOD_SHM_RING_BYTES,
                              env_mod.DEFAULT_SHM_RING_BYTES) \
            if ring_bytes is None else ring_bytes
        cap = max(int(cap), _MIN_RING_BYTES)

        for j in sorted(set(int(p) for p in peers)):
            if j == rank:
                continue
            lo, hi = (rank, j) if rank < j else (j, rank)
            key = f"seg.{lo}.{hi}"
            if rank == lo:
                self._peers[j] = self._create_segment(store, scope, key,
                                                      lo, hi, cap)
            else:
                self._peers[j] = self._attach_segment(store, scope, key,
                                                      timeout)

    # -- segment bring-up ---------------------------------------------------

    def _create_segment(self, store: Store, scope: str, key: str,
                        lo: int, hi: int, cap: int) -> _ShmPeer:
        name = (f"{SEG_PREFIX}{os.getpid()}-e{self.epoch}-{lo}x{hi}-"
                f"{uuid.uuid4().hex[:8]}")
        seg = shared_memory.SharedMemory(name=name, create=True,
                                         size=segment_size(cap))
        buf = seg.buf
        # Header before publish: an attacher never sees a half-built
        # segment.  /dev/shm segments are born zero-filled, so the ring
        # counters and the attacher-PID slot start correct for free.
        _store_u32(buf, _OFF_MAGIC, _SHM_MAGIC)
        _store_u32(buf, _OFF_VERSION, _SHM_VERSION)
        _store_u64(buf, _OFF_CAP, cap)
        _store_u64(buf, _OFF_CREATOR_PID, os.getpid())
        store.set(scope, key, seg.name.encode())
        return _ShmPeer(seg, created=True, cap=cap, i_am_lower=True)

    def _attach_segment(self, store: Store, scope: str, key: str,
                        timeout: float) -> _ShmPeer:
        name = store.wait(scope, [key], timeout=timeout)[key].decode()
        seg = shared_memory.SharedMemory(name=name)
        # Python 3.10's SharedMemory registers EVERY attach with the
        # resource tracker; left alone, the attacher's tracker would
        # unlink the creator's still-live segment at exit.  Exactly one
        # owner: the creator (whose registration doubles as the hard-kill
        # safety net).
        try:
            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker internals moved
            log.warning("could not unregister shm attach from the resource "
                        "tracker; exit may unlink %s early", name)
        buf = seg.buf
        magic = _load_u32(buf, _OFF_MAGIC)
        version = _load_u32(buf, _OFF_VERSION)
        if magic != _SHM_MAGIC or version != _SHM_VERSION:
            seg.close()
            raise HorovodInternalError(
                f"shm segment {name} has magic=0x{magic:08x} "
                f"version={version} (want 0x{_SHM_MAGIC:08x} "
                f"v{_SHM_VERSION}): mixed-version mesh or a foreign "
                "segment; refusing to attach")
        cap = _load_u64(buf, _OFF_CAP)
        _store_u64(buf, _OFF_ATTACHER_PID, os.getpid())
        return _ShmPeer(seg, created=False, cap=cap, i_am_lower=False)

    # -- shared failure-plane plumbing --------------------------------------

    @property
    def _abort(self) -> Optional[Tuple[int, int, str]]:
        return self._abort_state.value

    @_abort.setter
    def _abort(self, value: Optional[Tuple[int, int, str]]) -> None:
        self._abort_state.value = value

    @property
    def deferred_digests(self) -> bool:
        """Shadow-digest path applies only with the (default-off) shm CRC
        on — same rule as TCP, different default."""
        return self.wire_crc and self.crc_shadow

    def deferred_digests_for(self, peer: int) -> bool:
        return self.deferred_digests

    def new_digest(self) -> digest_mod.StreamDigest:
        return digest_mod.StreamDigest(self.digest_algo)

    @staticmethod
    def _crc32_timed(payload) -> int:
        if not metrics.ENABLED:
            return zlib.crc32(payload) & 0xFFFFFFFF
        t0 = time.perf_counter()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        metrics.inc("crc_verify_seconds_total", time.perf_counter() - t0)
        return crc

    @staticmethod
    def _digest_timed(dig: digest_mod.StreamDigest, view) -> None:
        if not metrics.ENABLED:
            dig.update(view)
            return
        t0 = time.perf_counter()
        dig.update(view)
        metrics.inc("crc_shadow_seconds_total", time.perf_counter() - t0)

    def _check_alive(self, p: _ShmPeer, peer: int) -> None:
        if self._abort is not None:
            raise CoordinatedAbortError(*self._abort)
        if p.dead is not None:
            raise PeerGoneError(peer, p.dead)

    @staticmethod
    def _mark_dead(p: _ShmPeer, reason: str) -> None:
        if p.dead is None:
            p.dead = reason

    @staticmethod
    def _peer_pid(p: _ShmPeer) -> int:
        return _load_u64(p.shm.buf, p.peer_pid_off)

    def _require_peer_alive(self, p: _ShmPeer) -> None:
        """The shm stand-in for a TCP RST: a peer that died mid-step can
        never drain or fill its ring, so a stalled wait probes the PID it
        stamped into the header.  PID 0 means the higher rank has not
        attached yet — bring-up stagger, the startup timeout's
        jurisdiction, never judged here."""
        pid = self._peer_pid(p)
        if pid == 0 or pid == os.getpid():
            return
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            raise OSError(f"peer process {pid} died (shm segment "
                          f"{p.shm.name} orphaned mid-stream)") from None
        except PermissionError:
            return  # alive, just not ours to signal

    # -- ring I/O -----------------------------------------------------------

    def _send_bounded(self, p: _ShmPeer, bufs: List[memoryview],
                      budget: Optional[float] = None,
                      ignore_abort: bool = False) -> None:
        """Copy ``bufs`` into the outbound ring by driving the pure
        :func:`sender_steps` protocol against the live segment — ring
        math, bell discipline, and memory-access ORDER all come from the
        generator (the model-checked code path); this driver only
        executes the ops and supplies the failure plane: abort flag on
        every poll, progress deadline on zero byte progress, peer-PID
        probe while stalled.

        ``ignore_abort=True`` is the abort-broadcast variant (the frame
        being written IS the abort — the flag is already set and the
        normal path would refuse to write): polls never report the
        abort, the first stalled wait probes the peer immediately, and
        blocked waits plain-sleep (the nap Event is already set on this
        path, so only a real sleep yields)."""
        buf = p.shm.buf
        budget = self.progress_deadline if budget is None else budget
        deadline = (time.monotonic() + budget) if budget > 0 else None
        next_probe = 0.0 if ignore_abort \
            else time.monotonic() + _ABORT_POLL_SECS
        naps = 0
        steps = sender_steps(p.cap, [len(b) for b in bufs])
        resp = None
        while True:
            try:
                op = steps.send(resp)
            except StopIteration as fin:
                if fin.value == ABORTED:
                    raise CoordinatedAbortError(*self._abort) from None
                return
            kind = op[0]
            resp = None
            if kind == OP_LOAD:
                if op[1] == LOC_BELL_PEER:
                    resp = _load_u32(buf, p.out_space_bell_off)
                elif op[1] == LOC_BELL_OWN:
                    resp = _load_u32(buf, p.out_data_bell_off)
                elif op[1] == LOC_HEAD:
                    resp = _load_u64(buf, p.out_head_off)
                else:
                    resp = _load_u64(buf, p.out_tail_off)
            elif kind == OP_COPY:
                _, idx, off, pos, run = op
                p.out_ring[pos:pos + run] = bufs[idx][off:off + run]
                naps = 0
                if deadline is not None:
                    deadline = time.monotonic() + budget
                if not ignore_abort:
                    next_probe = time.monotonic() + _ABORT_POLL_SECS
            elif kind == OP_STORE:
                if op[1] == LOC_BELL_OWN:
                    _store_u32(buf, p.out_data_bell_off, op[2])
                else:
                    _store_u64(buf, p.out_head_off, op[2])
            elif kind == OP_WAKE:
                p.wake(p.out_data_bell_off)
            elif kind == OP_POLL:
                resp = SIG_ABORT if not ignore_abort \
                    and self._abort is not None else SIG_OK
            else:  # OP_WAIT — ring full
                now = time.monotonic()
                if deadline is not None and now > deadline:
                    raise _ProgressStall(
                        "shm ring full while broadcasting abort"
                        if ignore_abort else
                        f"no send progress for {budget:.0f}s "
                        f"(HOROVOD_TCP_PROGRESS_DEADLINE_SECS="
                        f"{budget:g}, shm ring full)")
                if now >= next_probe:
                    self._require_peer_alive(p)
                    next_probe = now + _ABORT_POLL_SECS
                if ignore_abort:
                    time.sleep(_RING_NAP_SECS)  # hvdlint: disable=HVD001 -- bounded by the abort-broadcast deadline above
                else:
                    naps = p.bell_wait(p.out_space_bell_off, op[1], naps,
                                       self._nap)

    def _recv_bounded_into(self, p: _ShmPeer, view: memoryview,
                           with_crc: bool) -> Optional[int]:
        """Copy exactly ``len(view)`` bytes out of the inbound ring into
        the caller's view by driving the pure :func:`receiver_steps`
        protocol (see ``_send_bounded`` — same driver split), folding
        CRC32 over each landed span when asked — the incremental-CRC half
        of the zero-copy contract, same as the TCP side.  The deadline
        arms only after the peer's first-ever bytes (bring-up stagger is
        the startup timeout's problem)."""
        buf = p.shm.buf
        crc = 0
        measure_crc = with_crc and metrics.ENABLED
        crc_secs = 0.0
        budget = self.progress_deadline
        deadline = (time.monotonic() + budget) \
            if budget > 0 and p.ever_received else None
        next_probe = time.monotonic() + _ABORT_POLL_SECS
        naps = 0
        steps = receiver_steps(p.cap, [len(view)])
        resp = None
        while True:
            try:
                op = steps.send(resp)
            except StopIteration as fin:
                if fin.value == ABORTED:
                    raise CoordinatedAbortError(*self._abort) from None
                break
            kind = op[0]
            resp = None
            if kind == OP_LOAD:
                if op[1] == LOC_BELL_PEER:
                    resp = _load_u32(buf, p.in_data_bell_off)
                elif op[1] == LOC_BELL_OWN:
                    resp = _load_u32(buf, p.in_space_bell_off)
                elif op[1] == LOC_HEAD:
                    resp = _load_u64(buf, p.in_head_off)
                else:
                    resp = _load_u64(buf, p.in_tail_off)
            elif kind == OP_COPY:
                # Copy (and CRC) BEFORE the tail store the generator
                # yields next — the sender may overwrite the span the
                # moment the tail moves.
                _, _idx, got, pos, run = op
                naps = 0
                view[got:got + run] = p.in_ring[pos:pos + run]
                if with_crc:
                    if measure_crc:
                        tc = time.perf_counter()
                        crc = zlib.crc32(view[got:got + run], crc)
                        crc_secs += time.perf_counter() - tc
                    else:
                        crc = zlib.crc32(view[got:got + run], crc)
                if not p.ever_received:
                    p.ever_received = True
                    if budget > 0:
                        deadline = time.monotonic() + budget
                elif deadline is not None:
                    deadline = time.monotonic() + budget
                next_probe = time.monotonic() + _ABORT_POLL_SECS
            elif kind == OP_STORE:
                if op[1] == LOC_BELL_OWN:
                    _store_u32(buf, p.in_space_bell_off, op[2])
                else:
                    _store_u64(buf, p.in_tail_off, op[2])
            elif kind == OP_WAKE:
                p.wake(p.in_space_bell_off)
            elif kind == OP_POLL:
                resp = SIG_ABORT if self._abort is not None else SIG_OK
            else:  # OP_WAIT — ring empty
                now = time.monotonic()
                if deadline is not None and now > deadline:
                    raise _ProgressStall(
                        f"no recv progress for {budget:.0f}s "
                        f"(HOROVOD_TCP_PROGRESS_DEADLINE_SECS={budget:g})")
                if now >= next_probe:
                    self._require_peer_alive(p)
                    next_probe = now + _ABORT_POLL_SECS
                naps = p.bell_wait(p.in_data_bell_off, op[1], naps,
                                   self._nap)
        if measure_crc and crc_secs:
            metrics.inc("crc_verify_seconds_total", crc_secs)
        return (crc & 0xFFFFFFFF) if with_crc else None

    def _recv_bounded(self, p: _ShmPeer, n: int) -> bytes:
        buf = bytearray(n)
        self._recv_bounded_into(p, memoryview(buf), with_crc=False)
        return bytes(buf)

    # -- framed messaging (tcp.py's discipline over the ring) ---------------

    def send(self, peer: int, payload,
             digest: Optional[digest_mod.StreamDigest] = None,
             wire_dtype: int = 0, _check_frame: bool = False) -> None:
        """Frame and send one payload — one memcpy into the shared ring.
        Flag bits, deferred-digest handling, and fault-mutation semantics
        match :meth:`TcpMesh.send` bit for bit; shm data frames count
        under ``shm_bytes_total``, never ``bytes_on_wire`` (these bytes
        are not on a wire, and the zero-copy tests' exact wire accounting
        must hold)."""
        p = self._peer(peer)
        deferred = digest is not None and self.wire_crc
        with p.send_lock:
            self._check_alive(p, peer)
            try:
                payload = _as_byte_view(payload)
                wire = payload
                if faults.ACTIVE:
                    verdict = faults.inject(
                        "shm.send", rank=self.rank, peer=peer,
                        payload=payload)
                    if verdict is True:
                        return  # injected frame drop
                    if isinstance(verdict, faults.SendMutation):
                        # Same contract as tcp.send: truncate reframes
                        # self-consistently; corrupt flips wire bytes
                        # AFTER the CRC was computed over the original.
                        payload = _as_byte_view(verdict.payload)
                        wire = _as_byte_view(verdict.wire_bytes())
                flags = (wire_dtype << _WIRE_DTYPE_SHIFT) & _WIRE_DTYPE_MASK
                if deferred:
                    flags |= _DEFER_FLAG
                if _check_frame:
                    flags |= _DIGEST_FLAG
                header = _LEN.pack(len(payload) | flags)
                if self.wire_crc and not deferred:
                    header += _CRC.pack(self._crc32_timed(payload))
                self._send_bounded(p, [memoryview(header), wire])
                if deferred:
                    self._digest_timed(digest, payload)
                if not _check_frame:
                    metrics.inc("shm_bytes_total", len(payload))
                flight_recorder.record("frame", dir="send", peer=peer,
                                       nbytes=len(payload), via="shm")
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"shm send to rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"shm send to rank {peer} failed: {e}") from e

    def _recv_header(self, p: _ShmPeer, peer: int) -> _FrameHeader:
        n = _LEN.unpack(self._recv_bounded(p, _LEN.size))[0]
        size = n & ~_FLAGS_MASK
        if size > _MAX_FRAME_BYTES:
            self._poison_stream(p, peer, HorovodInternalError(
                f"shm frame header from rank {peer} claims "
                f"{size} bytes (cap {_MAX_FRAME_BYTES}): "
                "corrupted length word; aborting before allocating it"))
        deferred = bool(n & _DEFER_FLAG)
        crc = _CRC.unpack(self._recv_bounded(p, _CRC.size))[0] \
            if self.wire_crc and not deferred else None
        return _FrameHeader(bool(n & _CTRL_FLAG), deferred,
                            bool(n & _DIGEST_FLAG),
                            (n & _WIRE_DTYPE_MASK) >> _WIRE_DTYPE_SHIFT,
                            size, crc)

    def recv(self, peer: int) -> bytes:
        """Materializing recv — the control/negotiation-plane primitive,
        identical contract to :meth:`TcpMesh.recv`."""
        p = self._peer(peer)
        with p.recv_lock:
            self._check_alive(p, peer)
            try:
                if faults.ACTIVE:
                    faults.inject("shm.recv", rank=self.rank, peer=peer)
                while True:
                    hdr = self._recv_header(p, peer)
                    if hdr.ctrl:
                        self._consume_control_frame(p, peer, hdr.size,
                                                    hdr.crc)
                        continue  # stale control frame: keep reading
                    if hdr.deferred or hdr.check or hdr.wire_dtype:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"flagged shm data frame from rank {peer} on "
                            f"the control recv path "
                            f"(deferred={hdr.deferred}, check={hdr.check}, "
                            f"wire_dtype={hdr.wire_dtype}): CRC/compression "
                            "framing skew between peers; aborting, resync "
                            "is impossible by design"))
                    payload = self._recv_bounded(p, hdr.size)
                    p.frames_in += 1
                    if hdr.crc is not None:
                        got = self._crc32_timed(payload)
                        if got != hdr.crc:
                            self._poison_stream(
                                p, peer,
                                FrameCorruptError(peer, p.frames_in,
                                                  hdr.crc, got))
                    metrics.inc("shm_bytes_total", hdr.size)
                    flight_recorder.record("frame", dir="recv", peer=peer,
                                           nbytes=hdr.size, via="shm")
                    return payload
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"shm recv from rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"shm recv from rank {peer} failed: {e}") from e

    def recv_into(self, peer: int, dest,
                  digest: Optional[digest_mod.StreamDigest] = None,
                  wire_dtype: int = 0) -> int:
        """Zero-copy recv: one memcpy from the shared ring into ``dest``.
        All header-skew checks (deferred-ness, wire dtype, exact size)
        poison the stream exactly as on TCP — config skew between peers
        must fail loudly on every transport."""
        p = self._peer(peer)
        dv = _as_writable_byte_view(dest)
        with p.recv_lock:
            self._check_alive(p, peer)
            try:
                if faults.ACTIVE:
                    faults.inject("shm.recv", rank=self.rank, peer=peer)
                while True:
                    hdr = self._recv_header(p, peer)
                    if hdr.ctrl:
                        self._consume_control_frame(p, peer, hdr.size,
                                                    hdr.crc)
                        continue  # stale control frame: keep reading
                    if hdr.check:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"unexpected digest-check frame from rank "
                            f"{peer} where a data frame was due: ring-step "
                            "framing skew between peers; aborting"))
                    if hdr.deferred != (digest is not None):
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"shm data frame from rank {peer} is "
                            f"{'digest-deferred' if hdr.deferred else 'inline-CRC'} "
                            f"but this rank expected the "
                            f"{'deferred' if digest is not None else 'inline'} "
                            "path: HOROVOD_SHM_CRC/"
                            "HOROVOD_WIRE_CRC_SHADOW skew between peers; "
                            "aborting loudly"))
                    if hdr.wire_dtype != wire_dtype:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"shm data frame from rank {peer} carries wire "
                            f"dtype code {hdr.wire_dtype} but this rank "
                            f"expects {wire_dtype}: "
                            "HOROVOD_WIRE_COMPRESSION skew between peers "
                            "(mixed-version or mixed-config mesh); "
                            "aborting loudly instead of mis-decoding"))
                    if hdr.size != len(dv):
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"shm data frame from rank {peer} carries "
                            f"{hdr.size} bytes but the recv_into "
                            f"destination expects {len(dv)}: misframed "
                            "stream (truncated or desynced); aborting, "
                            "resync is impossible by design"))
                    got = self._recv_bounded_into(
                        p, dv, with_crc=hdr.crc is not None)
                    p.frames_in += 1
                    if hdr.crc is not None and got != hdr.crc:
                        self._poison_stream(
                            p, peer,
                            FrameCorruptError(peer, p.frames_in, hdr.crc,
                                              got))
                    if digest is not None:
                        self._digest_timed(digest, dv)
                    metrics.inc("shm_bytes_total", hdr.size)
                    flight_recorder.record("frame", dir="recv", peer=peer,
                                           nbytes=hdr.size, via="shm")
                    return hdr.size
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"shm recv from rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"shm recv from rank {peer} failed: {e}") from e

    def send_step_digest(self, peer: int, dig: digest_mod.StreamDigest,
                         frames: int) -> None:
        self.send(peer, digest_mod.pack_check(dig, frames),
                  _check_frame=True)

    def verify_step_digest(self, peer: int, dig: digest_mod.StreamDigest,
                           frames: int) -> None:
        """Settle one deferred ring-step direction — same contract and
        same poison semantics as the TCP mesh's."""
        p = self._peer(peer)
        with p.recv_lock:
            self._check_alive(p, peer)
            try:
                while True:
                    hdr = self._recv_header(p, peer)
                    if hdr.ctrl:
                        self._consume_control_frame(p, peer, hdr.size,
                                                    hdr.crc)
                        continue  # stale control frame: keep reading
                    if not hdr.check:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"expected a digest-check frame from rank "
                            f"{peer} to close the ring step but got a "
                            "data frame: step framing skew between "
                            "peers; aborting"))
                    if hdr.size != digest_mod.CHECK_SIZE:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"digest-check frame from rank {peer} "
                            f"carries {hdr.size} bytes (expected "
                            f"{digest_mod.CHECK_SIZE}): misframed stream "
                            "(truncated or desynced); aborting"))
                    payload = self._recv_bounded(p, hdr.size)
                    p.frames_in += 1
                    if hdr.crc is not None:
                        got = self._crc32_timed(payload)
                        if got != hdr.crc:
                            self._poison_stream(
                                p, peer,
                                FrameCorruptError(peer, p.frames_in,
                                                  hdr.crc, got))
                    algo, value, count = digest_mod.unpack_check(payload)
                    if algo != dig.algo:
                        self._poison_stream(p, peer, HorovodInternalError(
                            f"digest-check frame from rank {peer} uses "
                            f"wire digest "
                            f"{digest_mod.algo_name(algo)!r} but this "
                            f"rank runs "
                            f"{digest_mod.algo_name(dig.algo)!r}: "
                            "HOROVOD_WIRE_DIGEST skew between peers"))
                    if count != frames or value != dig.value():
                        self._poison_stream(
                            p, peer,
                            FrameCorruptError(peer, p.frames_in, value,
                                              dig.value()))
                    flight_recorder.record("frame", dir="recv", peer=peer,
                                           nbytes=hdr.size, via="shm")
                    return
            except _ProgressStall as e:
                self._mark_dead(p, str(e))
                raise PeerGoneError(peer, str(e)) from None
            except OSError as e:
                self._mark_dead(p, f"shm recv from rank {peer} failed: {e}")
                raise PeerGoneError(
                    peer, f"shm recv from rank {peer} failed: {e}") from e

    # -- control plane ------------------------------------------------------

    def _consume_control_frame(self, p: _ShmPeer, peer: int, size: int,
                               crc: Optional[int]) -> None:
        payload = self._recv_bounded(p, size)
        p.frames_in += 1
        if crc is not None:
            got = self._crc32_timed(payload)
            if got != crc:
                self._poison_stream(
                    p, peer,
                    FrameCorruptError(peer, p.frames_in, crc, got))
        self._handle_control(payload, peer)

    def _poison_stream(self, p: _ShmPeer, peer: int,
                       err: HorovodInternalError) -> None:
        """Same unrecoverable-by-design contract as the TCP mesh: mark
        dead, broadcast the coordinated abort (via the LinkMesh relay
        when present, so TCP links hear it too), raise."""
        flight_recorder.record("stream_poisoned", peer=peer,
                               error=str(err)[:300], via="shm")
        self._mark_dead(p, str(err))
        self.send_abort(str(err))
        raise err

    def _handle_control(self, payload: bytes, peer: int) -> None:
        from ..core.messages import AbortFrame, is_abort_frame

        if not is_abort_frame(payload):
            raise HorovodInternalError(
                f"unknown control frame from rank {peer} (shm)")
        frame = AbortFrame.from_bytes(payload)
        if frame.epoch < self.epoch:
            log.warning(
                "discarding stale abort from rank %d (epoch %d < %d): %s",
                frame.origin_rank, frame.epoch, self.epoch, frame.reason)
            return
        metrics.inc("aborts_total", dir="received")
        flight_recorder.record("abort_received", origin=frame.origin_rank,
                               epoch=frame.epoch,
                               reason=frame.reason[:300])
        self._abort = (frame.epoch, frame.origin_rank, frame.reason)
        self._nap.set()
        raise CoordinatedAbortError(frame.epoch, frame.origin_rank,
                                    frame.reason)

    def send_abort(self, reason: str, epoch: Optional[int] = None,
                   origin_rank: Optional[int] = None,
                   _relayed: bool = False, _record: bool = True) -> None:
        """Broadcast a coordinated abort over every surviving shm link.

        Best-effort with a SHORT per-link budget: a dead peer's ring may
        be full forever, and the caller is already tearing down.  Flips
        the (possibly shared) abort flag first and wakes every napping
        ring wait.  ``_record`` lets the LinkMesh suppress the
        metrics/flight-recorder entries when it already recorded the
        broadcast via the TCP half."""
        if self._closed or self.size == 1:
            return
        if not _relayed and self.abort_relay is not None:
            self.abort_relay(reason, epoch=epoch, origin_rank=origin_rank)
            return
        from ..core.messages import AbortFrame

        epoch = self.epoch if epoch is None else epoch
        origin_rank = self.rank if origin_rank is None else origin_rank
        payload = AbortFrame(epoch=epoch, origin_rank=origin_rank,
                             reason=reason).to_bytes()
        if _record:
            metrics.inc("aborts_total", dir="sent")
            flight_recorder.record("abort_broadcast", origin=origin_rank,
                                   epoch=epoch, reason=reason[:300])
        if self._abort is None:
            self._abort = (epoch, origin_rank, reason)
        self._nap.set()
        header = _LEN.pack(len(payload) | _CTRL_FLAG)
        if self.wire_crc:
            header += _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF)
        for peer, p in list(self._peers.items()):
            # Dead-marked links are still tried, same as TCP: the peer's
            # recv direction may be fine and the abort is what unblocks
            # it.  The 2 s ring budget bounds a truly dead peer.
            if not p.send_lock.acquire(timeout=2.0):
                continue  # a wedged send holds the lock; skip this link
            try:
                self._abort_write(p, [memoryview(header),
                                      memoryview(payload)])
            except (OSError, _ProgressStall) as e:
                self._mark_dead(p, f"abort send failed: {e}")
            finally:
                p.send_lock.release()

    def _abort_write(self, p: _ShmPeer, bufs: List[memoryview]) -> None:
        """Ring write for the abort broadcast: ignores the mesh abort
        flag (it is ALREADY set — the normal path would refuse to write)
        but keeps a short no-progress deadline and the liveness probe.
        Rides the same :func:`sender_steps` protocol as every other send
        — one bump per call, publish-before-sleep — where a previous
        incarnation re-derived the ring run with a diverging per-RUN
        bell bump."""
        self._send_bounded(p, bufs, budget=2.0, ignore_abort=True)

    # -- concurrent helpers (ring-collective primitives) --------------------

    def sendrecv(self, send_to: int, payload, recv_from: int) -> bytes:
        done = threading.Event()
        box: List = [None, None]  # [result, error]

        def _recv():
            try:
                box[0] = self.recv(recv_from)
            except BaseException as e:  # noqa: BLE001
                box[1] = e
            finally:
                done.set()

        self._sr_submit(_recv)
        self.send(send_to, payload)
        done.wait()
        if box[1] is not None:
            raise box[1]
        return box[0]

    def recv_into_async(self, peer: int, dest,
                        digest: Optional[digest_mod.StreamDigest] = None,
                        wire_dtype: int = 0) -> PendingRecv:
        """FIFO posts on one helper thread map recvs onto the peer's
        frames in ring order — same serialization argument as the TCP
        helper, same digest-ordering guarantee."""
        done = threading.Event()
        box: List = [None, None]  # [nbytes, error]

        def _recv():
            try:
                box[0] = self.recv_into(peer, dest, digest=digest,
                                        wire_dtype=wire_dtype)
            except BaseException as e:  # noqa: BLE001
                box[1] = e
            finally:
                done.set()

        self._sr_submit(_recv)
        return PendingRecv(done, box)

    def sendrecv_into(self, send_to: int, payload, recv_from: int,
                      dest) -> int:
        pending = self.recv_into_async(recv_from, dest)
        self.send(send_to, payload)
        return pending.wait()

    def _sr_submit(self, task) -> None:
        if self._sr_thread is None or not self._sr_thread.is_alive():
            self._sr_queue = queue.SimpleQueue()
            self._sr_thread = threading.Thread(
                target=self._sr_loop, name="hvd-shm-sendrecv", daemon=True)
            self._sr_thread.start()
        self._sr_queue.put(task)

    def _sr_loop(self) -> None:
        while True:
            task = self._sr_queue.get()
            if task is None:
                return
            try:
                task()
            except BaseException:  # noqa: BLE001 — a raising task must not
                # kill the loop (queued tasks behind it would wait forever);
                # the posted closures catch their own errors into result
                # boxes, so anything here is a foreign/broken submission.
                log.error("shm sendrecv helper task raised", exc_info=True)

    # -- lifecycle ----------------------------------------------------------

    def _peer(self, peer: int) -> _ShmPeer:
        try:
            return self._peers[peer]
        except KeyError:
            raise HorovodInternalError(
                f"rank {self.rank} has no shm link to rank {peer}") from None

    def close(self) -> None:
        """Detach every segment; the CREATOR also unlinks it.  POSIX keeps
        the memory alive until the last mapping drops, so a peer still
        draining its ring is unaffected by the unlink — the name just
        leaves /dev/shm, which is exactly the no-residue property the
        leak tests assert."""
        if self._closed:
            return
        self._closed = True
        self._nap.set()
        if self._sr_thread is not None and self._sr_thread.is_alive():
            self._sr_queue.put(None)
        for p in self._peers.values():
            # Exported ring views and the ctypes futex anchor must drop
            # before SharedMemory.close() (its mmap refuses to unmap
            # under live exports).
            p.base_addr = 0
            p.addr_anchor = None
            p.out_ring.release()
            p.in_ring.release()
            try:
                p.shm.close()
            except (OSError, BufferError):
                pass
            if p.created:
                try:
                    p.shm.unlink()
                except FileNotFoundError:
                    pass
