"""Per-link transport selection — the dispatch seam in front of the fabrics.

ROADMAP item 1 demands the backend/transport choice be "a real dispatch
seam, not an if/else": this module is that seam for the host data plane.
Transports self-describe into a priority-ordered REGISTRY
(``register_transport``); at bring-up every rank publishes a host-identity
string through the rendezvous store, and each peer link is classified by
asking the registry for the first transport that is (a) allowed by the
``HOROVOD_TRANSPORT`` policy and (b) eligible for that link's endpoint
pair.  The result is a per-peer ROUTE TABLE inside :class:`LinkMesh` — a
facade with the full ``TcpMesh`` send/recv/recv_into/sendrecv_into/
send_abort surface whose every data call is one dict lookup away from the
fabric that owns the link.  The collectives (``backend/cpu_ring.py``)
never learn which fabric they ride; ``HierarchicalAllreduce``'s
intra-host phase lands on shm and its cross-host phase on TCP purely
because its peer sets classify that way.

Host identity: ``<physical>/<cross_rank>`` — the physical part is the
kernel boot id plus the ``/dev/shm`` device number (two containers
sharing neither cannot shm to each other), and folding in the topology's
``cross_rank`` makes a SIMULATED multi-host job on one box classify its
links exactly like a real one (the hierarchical parity tests depend on
this).  ``HOROVOD_SHM_HOSTID`` overrides the physical part.

Failure domain: the facade shares ONE :class:`AbortState` across both
fabrics and installs itself as each fabric's ``abort_relay``, so a
poisoned shm ring aborts the TCP links in the same broadcast and vice
versa — one failure plane, however many transports.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable, Dict, Iterable, List, Optional

from ..common.exceptions import HorovodInternalError
from ..common.logging_util import get_logger
from ..core import flight_recorder, metrics
from .shm import ShmMesh
from .store import Store
from .tcp import AbortState, PendingRecv, TcpMesh

log = get_logger("horovod_tpu.transport.select")


# -- host identity ----------------------------------------------------------

def _physical_host_id() -> str:
    """Best-effort physical-machine identity: boot id (stable across the
    machine, distinct across machines and reboots) plus the /dev/shm
    device number (distinct across containers that cannot actually share
    segments)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = socket.gethostname()
    try:
        dev = os.stat("/dev/shm").st_dev
    except OSError:
        dev = -1
    return f"{boot}.{dev}"


def host_identity(cross_rank: int = 0) -> str:
    """This rank's host-identity string (module docstring).  Two ranks
    get an shm link iff their strings compare equal."""
    from ..common import env as env_mod

    override = env_mod.get_str(env_mod.HOROVOD_SHM_HOSTID, "") or ""
    physical = override or _physical_host_id()
    return f"{physical}/{cross_rank}"


def transport_policy() -> str:
    """The validated ``HOROVOD_TRANSPORT`` policy.  A typo'd value is a
    loud startup error, not a silent fallback to TCP."""
    from ..common import env as env_mod

    policy = (env_mod.get_str(env_mod.HOROVOD_TRANSPORT, "auto")
              or "auto").strip().lower()
    if policy not in ("auto", "tcp", "shm"):
        raise HorovodInternalError(
            f"HOROVOD_TRANSPORT={policy!r} is not one of auto|tcp|shm")
    return policy


# -- transport registry -----------------------------------------------------

class LinkContext:
    """Everything a transport's ``build`` hook needs to bring up its mesh
    for the peers routed to it."""

    __slots__ = ("rank", "size", "store", "epoch", "timeout",
                 "progress_deadline", "abort_state", "host_id",
                 "peer_hosts", "shm_scope", "base_tcp")

    def __init__(self, rank: int, size: int, store: Store, epoch: int,
                 timeout: float, progress_deadline: Optional[float],
                 abort_state: AbortState, host_id: str,
                 peer_hosts: Dict[int, str], shm_scope: str,
                 base_tcp: TcpMesh):
        self.rank = rank
        self.size = size
        self.store = store
        self.epoch = epoch
        self.timeout = timeout
        self.progress_deadline = progress_deadline
        self.abort_state = abort_state
        self.host_id = host_id
        self.peer_hosts = peer_hosts
        self.shm_scope = shm_scope
        self.base_tcp = base_tcp


class TransportSpec:
    """One registered transport: ``eligible`` judges a single link,
    ``build`` brings up one mesh instance serving every peer the route
    table assigned to it.  Lower ``priority`` wins under ``auto``."""

    __slots__ = ("name", "priority", "eligible", "build")

    def __init__(self, name: str, priority: int,
                 eligible: Callable[[LinkContext, int], bool],
                 build: Callable[[LinkContext, List[int]], object]):
        self.name = name
        self.priority = priority
        self.eligible = eligible
        self.build = build


_REGISTRY: Dict[str, TransportSpec] = {}


def register_transport(spec: TransportSpec) -> None:
    _REGISTRY[spec.name] = spec


def registered_transports() -> List[TransportSpec]:
    return sorted(_REGISTRY.values(), key=lambda s: s.priority)


def select_transport(policy: str, ctx: LinkContext, peer: int) -> str:
    """Name of the transport carrying the link to ``peer`` — the first
    policy-allowed, link-eligible entry in priority order.  A FORCED
    policy whose transport cannot carry the link (shm across hosts) is a
    loud config error: silently widening to TCP would fake the perf the
    operator explicitly asked to measure."""
    for spec in registered_transports():
        if policy != "auto" and spec.name != policy:
            continue
        if spec.eligible(ctx, peer):
            return spec.name
    raise HorovodInternalError(
        f"HOROVOD_TRANSPORT={policy} cannot carry the link "
        f"{ctx.rank}<->{peer}: this host is {ctx.host_id!r}, peer is "
        f"{ctx.peer_hosts.get(peer)!r} (shm needs both on one host)")


def _shm_eligible(ctx: LinkContext, peer: int) -> bool:
    return ctx.peer_hosts.get(peer) == ctx.host_id


def _build_shm(ctx: LinkContext, peers: List[int]) -> ShmMesh:
    return ShmMesh(ctx.rank, ctx.size, ctx.store, peers,
                   scope=ctx.shm_scope, timeout=ctx.timeout,
                   epoch=ctx.epoch,
                   progress_deadline=ctx.progress_deadline,
                   abort_state=ctx.abort_state)


register_transport(TransportSpec(
    name="shm", priority=10, eligible=_shm_eligible, build=_build_shm))
register_transport(TransportSpec(
    name="tcp", priority=100,
    eligible=lambda ctx, peer: True,
    build=lambda ctx, peers: ctx.base_tcp))


# -- the facade -------------------------------------------------------------

class LinkMesh:
    """Route-table facade over the registered transports.

    Carries the full ``TcpMesh`` surface; every per-peer call dispatches
    through ``self._route[peer]``.  The TCP mesh is ALWAYS built
    underneath — it is the bootstrap fabric, the cross-host fabric, and
    every link's fallback — and anything not explicitly implemented here
    (``wire_crc``, ``digest_algo``, ...) delegates to it."""

    def __init__(self, rank: int, size: int, store: Store, *,
                 epoch: Optional[int] = None, timeout: float = 60.0,
                 policy: Optional[str] = None,
                 host_id: Optional[str] = None,
                 cross_rank: int = 0,
                 bind_addr: str = "0.0.0.0",
                 advertise_addr: Optional[str] = None,
                 progress_deadline: Optional[float] = None):
        from ..common import env as env_mod

        self.rank = rank
        self.size = size
        self.epoch = env_mod.get_epoch() if epoch is None else epoch
        self._policy = transport_policy() if policy is None else policy
        self._abort_state = AbortState()
        shm_scope = f"shm.{self.epoch}"
        self.tcp = TcpMesh(rank, size, store, scope=f"tcp.{self.epoch}",
                           bind_addr=bind_addr,
                           advertise_addr=advertise_addr, timeout=timeout,
                           epoch=self.epoch,
                           progress_deadline=progress_deadline,
                           abort_state=self._abort_state)
        self.tcp.abort_relay = self.send_abort
        self.shm: Optional[ShmMesh] = None
        self._route: Dict[int, object] = {}
        if size == 1:
            self.host_id = host_id or host_identity(cross_rank)
            return

        # Host-identity exchange rides the same rendezvous store the TCP
        # bring-up just proved out; classification is symmetric because
        # eligibility is an equality test and policy is env-propagated.
        self.host_id = host_id or host_identity(cross_rank)
        store.set(shm_scope, f"host.{rank}", self.host_id.encode())
        others = [j for j in range(size) if j != rank]
        hosts = store.wait(shm_scope, [f"host.{j}" for j in others],
                           timeout=timeout)
        peer_hosts = {j: hosts[f"host.{j}"].decode() for j in others}
        ctx = LinkContext(rank, size, store, self.epoch, timeout,
                          progress_deadline, self._abort_state,
                          self.host_id, peer_hosts, shm_scope, self.tcp)
        chosen: Dict[int, str] = {
            j: select_transport(self._policy, ctx, j) for j in others}
        by_name: Dict[str, List[int]] = {}
        for j, name in chosen.items():
            by_name.setdefault(name, []).append(j)
        built: Dict[str, object] = {}
        for name, peers in sorted(by_name.items()):
            mesh = _REGISTRY[name].build(ctx, peers)
            mesh.abort_relay = self.send_abort
            built[name] = mesh
            metrics.inc("transport_links_total", len(peers),
                        transport=name)
            for j in peers:
                self._route[j] = mesh
        self.shm = built.get("shm")
        flight_recorder.record(
            "transport_routes", policy=self._policy, host=self.host_id,
            routes={str(j): n for j, n in sorted(chosen.items())})
        log.info("transport routes (policy=%s, host=%s): %s",
                 self._policy, self.host_id,
                 {j: n for j, n in sorted(chosen.items())})

    # -- route introspection (tests, tools) --------------------------------

    def route_table(self) -> Dict[int, str]:
        shm_peers = set(self.shm._peers) if self.shm is not None else set()
        return {j: ("shm" if j in shm_peers else "tcp")
                for j in self._route}

    # -- per-link dispatch --------------------------------------------------

    def send(self, peer: int, payload, digest=None, wire_dtype: int = 0,
             _check_frame: bool = False) -> None:
        self._route[peer].send(peer, payload, digest=digest,
                               wire_dtype=wire_dtype,
                               _check_frame=_check_frame)

    def recv(self, peer: int) -> bytes:
        return self._route[peer].recv(peer)

    def recv_into(self, peer: int, dest, digest=None,
                  wire_dtype: int = 0) -> int:
        return self._route[peer].recv_into(peer, dest, digest=digest,
                                           wire_dtype=wire_dtype)

    def recv_into_async(self, peer: int, dest, digest=None,
                        wire_dtype: int = 0) -> PendingRecv:
        return self._route[peer].recv_into_async(peer, dest, digest=digest,
                                                 wire_dtype=wire_dtype)

    def send_step_digest(self, peer: int, dig, frames: int) -> None:
        self._route[peer].send_step_digest(peer, dig, frames)

    def verify_step_digest(self, peer: int, dig, frames: int) -> None:
        self._route[peer].verify_step_digest(peer, dig, frames)

    def deferred_digests_for(self, peer: int) -> bool:
        return self._route[peer].deferred_digests_for(peer)

    @property
    def deferred_digests(self) -> bool:
        """Mesh-wide view kept for compatibility; ring code asks the
        per-link :meth:`deferred_digests_for` instead."""
        return self.tcp.deferred_digests

    def new_digest(self):
        return self.tcp.new_digest()

    def sendrecv(self, send_to: int, payload, recv_from: int) -> bytes:
        ms = self._route[send_to]
        mr = self._route[recv_from]
        if ms is mr:
            return ms.sendrecv(send_to, payload, recv_from)
        # Cross-transport step: the recv mesh's helper thread takes the
        # recv (preserving its per-peer FIFO/digest ordering) while this
        # thread drives the send on the other fabric.
        done = threading.Event()
        box: List = [None, None]

        def _recv():
            try:
                box[0] = mr.recv(recv_from)
            except BaseException as e:  # noqa: BLE001
                box[1] = e
            finally:
                done.set()

        mr._sr_submit(_recv)
        ms.send(send_to, payload)
        done.wait()
        if box[1] is not None:
            raise box[1]
        return box[0]

    def sendrecv_into(self, send_to: int, payload, recv_from: int,
                      dest) -> int:
        ms = self._route[send_to]
        mr = self._route[recv_from]
        if ms is mr:
            return ms.sendrecv_into(send_to, payload, recv_from, dest)
        pending = mr.recv_into_async(recv_from, dest)
        ms.send(send_to, payload)
        return pending.wait()

    # -- failure plane ------------------------------------------------------

    @property
    def _abort(self):
        return self._abort_state.value

    @_abort.setter
    def _abort(self, value) -> None:
        self._abort_state.value = value

    def send_abort(self, reason: str, epoch: Optional[int] = None,
                   origin_rank: Optional[int] = None) -> None:
        """One abort, every fabric: the TCP half records the broadcast
        (metrics + flight recorder) and reaches every rank; the shm half
        re-broadcasts in-band so a peer blocked mid-ring unblocks without
        waiting for anyone to drain a TCP socket."""
        self.tcp.send_abort(reason, epoch=epoch, origin_rank=origin_rank,
                            _relayed=True)
        if self.shm is not None:
            self.shm.send_abort(reason, epoch=epoch,
                                origin_rank=origin_rank,
                                _relayed=True, _record=False)

    def close(self) -> None:
        if self.shm is not None:
            self.shm.close()
        self.tcp.close()

    def __getattr__(self, name: str):
        tcp = self.__dict__.get("tcp")
        if tcp is None:
            raise AttributeError(name)
        return getattr(tcp, name)


def build_link_mesh(topo, store: Store, *, epoch: int, timeout: float,
                    progress_deadline: Optional[float] = None):
    """What ``core/state.py`` calls instead of constructing a TcpMesh.

    Resolves the policy ONCE: under ``tcp`` the plain TcpMesh comes back
    directly (the pre-selection-layer object, zero new moving parts);
    under ``auto``/``shm`` the LinkMesh facade routes per link."""
    policy = transport_policy()
    if policy == "tcp":
        return TcpMesh(topo.rank, topo.size, store,
                       scope=f"tcp.{epoch}", timeout=timeout, epoch=epoch,
                       progress_deadline=progress_deadline)
    return LinkMesh(topo.rank, topo.size, store, epoch=epoch,
                    timeout=timeout, policy=policy,
                    cross_rank=int(getattr(topo, "cross_rank", 0) or 0),
                    progress_deadline=progress_deadline)
