"""Adasum: scale-insensitive gradient reduction via VHDD.

Reference: ``adasum/adasum.h:194-450`` (templated vector-halving
distance-doubling recursive allreduce whose combine step is the Adasum
operator) and ``adasum_mpi_operations.cc`` (the MPI point-to-point
realization).  The operator merges two gradients a, b as::

    a' = (1 - dot(a,b) / (2*||a||^2)) * a + (1 - dot(a,b) / (2*||b||^2)) * b

so identical directions average and orthogonal directions add — a
reduction that adapts to gradient correlation instead of assuming
independence (Microsoft's Adasum paper).  Dot products and norms accumulate
in fp64 exactly like the reference's ``double`` accumulators
(``adasum.h:101-140``).

Schedule (VHDD, power-of-two ranks like the reference): at distance d =
1, 2, 4, ..., each rank pairs with ``rank ^ d``, exchanges the half of the
buffer the peer owns, combines its kept half with Adasum, recursing on a
half-sized vector each round; then the halves are allgathered back by
walking the distances in reverse.  Per-tensor dot/norm triplets are
reduced per *tensor* (not per fused buffer) so fusion does not change the
math — same property the reference maintains by carrying per-layer
state.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..common.exceptions import HorovodInternalError
from ..common.topology import ProcessTopology
from ..core.messages import Response
from ..core.tensor_queue import Status, TensorTableEntry
from ..transport.tcp import TcpMesh
from . import cpu_ring


def _adasum_combine(a: np.ndarray, b: np.ndarray,
                    bounds: List[Tuple[int, int]]) -> np.ndarray:
    """Combine two equal-length fused segments tensor-by-tensor."""
    out = np.empty_like(a)
    for lo, hi in bounds:
        av, bv = a[lo:hi], b[lo:hi]
        dot = float(np.dot(av.astype(np.float64), bv.astype(np.float64)))
        na2 = float(np.dot(av.astype(np.float64), av.astype(np.float64)))
        nb2 = float(np.dot(bv.astype(np.float64), bv.astype(np.float64)))
        ca = 1.0 - dot / (2.0 * na2) if na2 > 0 else 0.5
        cb = 1.0 - dot / (2.0 * nb2) if nb2 > 0 else 0.5
        out[lo:hi] = ca * av + cb * bv
    return out


def _segment_bounds(sizes: List[int], lo: int, hi: int) -> List[Tuple[int, int]]:
    """Tensor boundaries clipped to the [lo, hi) slice of the fused buffer,
    re-based to slice-local offsets."""
    bounds = []
    off = 0
    for n in sizes:
        t_lo, t_hi = max(off, lo), min(off + n, hi)
        if t_lo < t_hi:
            bounds.append((t_lo - lo, t_hi - lo))
        off += n
    return bounds or [(0, hi - lo)]


class AdasumAllreduce(cpu_ring.CollectiveOp):
    """VHDD Adasum over the TCP mesh, registered for ``ResponseType.ADASUM``."""

    def enabled(self, response: Response,
                entries: List[TensorTableEntry]) -> bool:
        # VHDD needs a power-of-two world (reference adasum.h restriction);
        # other sizes fall through to the ring-allreduce op registered
        # behind this one in the ADASUM chain.
        return (self.topo.size & (self.topo.size - 1)) == 0

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        size, rank = self.topo.size, self.topo.rank
        if size == 1:
            for e in entries:
                e.output = np.array(e.tensor, copy=True)
            return Status.OK()
        if size & (size - 1):
            raise HorovodInternalError(
                f"Adasum VHDD requires a power-of-two world size, got {size} "
                f"(reference adasum.h has the same restriction)")

        acc_dtype = cpu_ring._accum_dtype(entries[0].tensor.dtype)
        buf = cpu_ring.fuse_entries(entries, acc_dtype)
        sizes = [int(np.prod(e.tensor.shape)) if e.tensor.shape else 1
                 for e in entries]
        real_n = buf.size
        # Zero-pad to a multiple of the world size so every halving round
        # splits evenly; pad regions sit outside all tensor bounds, stay
        # zero through combines, and are dropped before unfuse.
        if real_n % size:
            pad = size - real_n % size
            buf = np.concatenate([buf, np.zeros(pad, acc_dtype)])
        n = buf.size

        # Vector-halving distance-doubling reduce-scatter with Adasum
        # combine (reference adasum.h:194-320).
        lo, hi = 0, n
        halves: List[Tuple[int, bool]] = []  # (distance, kept_upper)
        distance = 1
        while distance < size:
            peer = rank ^ distance
            mid = lo + (hi - lo) // 2
            keep_upper = (rank & distance) != 0
            if keep_upper:
                send_lo, send_hi, keep_lo, keep_hi = lo, mid, mid, hi
            else:
                send_lo, send_hi, keep_lo, keep_hi = mid, hi, lo, mid
            peer_half = np.frombuffer(
                self.mesh.sendrecv(peer, buf[send_lo:send_hi].tobytes(), peer),
                dtype=acc_dtype).copy()
            kept = buf[keep_lo:keep_hi]
            if peer_half.size != kept.size:
                raise HorovodInternalError(
                    "Adasum exchange size mismatch "
                    f"({peer_half.size} vs {kept.size})")
            bounds = _segment_bounds(sizes, keep_lo, keep_hi)
            if rank < peer:
                combined = _adasum_combine(kept, peer_half, bounds)
            else:
                combined = _adasum_combine(peer_half, kept, bounds)
            buf[keep_lo:keep_hi] = combined
            halves.append((distance, keep_upper))
            lo, hi = keep_lo, keep_hi
            distance <<= 1

        # Allgather back: walk distances in reverse, exchanging the owned
        # slice for the peer's (reference adasum.h:321-380).
        for distance, keep_upper in reversed(halves):
            peer = rank ^ distance
            span = hi - lo
            if keep_upper:
                other_lo, other_hi = lo - span, lo
            else:
                other_lo, other_hi = hi, hi + span
            peer_data = np.frombuffer(
                self.mesh.sendrecv(peer, buf[lo:hi].tobytes(), peer),
                dtype=acc_dtype)
            buf[other_lo:other_hi] = peer_data
            lo, hi = min(lo, other_lo), max(hi, other_hi)

        buf = buf[:real_n]
        if response.postscale_factor != 1.0:
            buf = buf * response.postscale_factor
        cpu_ring.unfuse_entries(
            buf.astype(response.tensor_type.to_numpy(), copy=False), entries)
        return Status.OK()
