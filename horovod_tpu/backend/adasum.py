"""Adasum: scale-insensitive gradient reduction via VHDD.

Reference: ``adasum/adasum.h:194-450`` (templated vector-halving
distance-doubling recursive allreduce whose combine step is the Adasum
operator) and ``adasum_mpi_operations.cc`` (the MPI point-to-point
realization).  The operator merges two gradients a, b as::

    a' = (1 - dot(a,b) / (2*||a||^2)) * a + (1 - dot(a,b) / (2*||b||^2)) * b

so identical directions average and orthogonal directions add — a
reduction that adapts to gradient correlation instead of assuming
independence (Microsoft's Adasum paper).  Dot products and norms accumulate
in fp64 exactly like the reference's ``double`` accumulators
(``adasum.h:101-140``); a coefficient falls back to 1.0 when its norm is
~zero (``adasum.h:385-391``), so a zero gradient contributes nothing and
the peer's gradient passes through unchanged.

Schedule (VHDD, power-of-two ranks like the reference): at distance d =
1, 2, 4, ..., each rank pairs with ``rank ^ d``, exchanges the half of the
buffer the peer owns, combines its kept half with Adasum, recursing on a
half-sized vector each round; then the halves are allgathered back by
walking the distances in reverse.

Because each rank holds only a *slice* of the logical (a, b) pair at every
level, the per-tensor (dot, ||a||², ||b||²) triplets computed on the local
slice are partial sums; they are allreduced across the 2·d ranks that
together hold the full pair (the "reduction communicator" of
``adasum.h:368`` ``SumAllreduceWithComm``) before coefficients are formed —
so every slice of a tensor is combined with the same full-tensor
coefficients and fusion/slicing does not change the math.
"""

from __future__ import annotations

import copy
from typing import List, Tuple

import numpy as np

from ..common.exceptions import HorovodInternalError
from ..common.logging_util import get_logger
from ..core.messages import Response, ResponseType
from ..core.tensor_queue import Status, TensorTableEntry
from . import cpu_ring

log = get_logger("horovod_tpu.backend.adasum")

# Below this, a squared norm is treated as zero and the coefficient is 1.0
# (reference adasum.h:385-391 uses sqrt(DBL_MIN)).
_NORMSQ_EPS = float(np.sqrt(np.finfo(np.float64).tiny))


def _segment_bounds(sizes: List[int], lo: int,
                    hi: int) -> List[Tuple[int, int, int]]:
    """(tensor_index, slice_lo, slice_hi) for every tensor overlapping the
    [lo, hi) window of the fused buffer, re-based to window-local offsets.
    Pad regions (beyond the last tensor) belong to no segment."""
    bounds = []
    off = 0
    for idx, n in enumerate(sizes):
        t_lo, t_hi = max(off, lo), min(off + n, hi)
        if t_lo < t_hi:
            bounds.append((idx, t_lo - lo, t_hi - lo))
        off += n
    return bounds


def _partial_triplets(a: np.ndarray, b: np.ndarray,
                      segs: List[Tuple[int, int, int]],
                      num_tensors: int) -> np.ndarray:
    """Slice-local (dot, ||a||², ||b||²) partial sums per tensor, fp64.

    The native kernel (``_native/native.cc`` hvd_dot3) matches the
    reference's fused one-pass dot/norm loops (``adasum.h:101-140``)."""
    from .. import _native

    t = np.zeros((num_tensors, 3), np.float64)
    for idx, lo, hi in segs:
        av, bv = a[lo:hi], b[lo:hi]
        native = _native.dot3(av, bv)
        if native is not None:
            t[idx] += native
            continue
        av64 = av.astype(np.float64, copy=False)
        bv64 = bv.astype(np.float64, copy=False)
        t[idx, 0] += float(av64 @ bv64)
        t[idx, 1] += float(av64 @ av64)
        t[idx, 2] += float(bv64 @ bv64)
    return t


def _apply_combine(a: np.ndarray, b: np.ndarray,
                   segs: List[Tuple[int, int, int]],
                   triplets: np.ndarray) -> np.ndarray:
    """out = ca·a + cb·b per tensor segment, with full-tensor coefficients."""
    from .. import _native

    native_ok = _native.lib() is not None and a.dtype in (np.float32,
                                                          np.float64)
    out = np.zeros_like(a)
    for idx, lo, hi in segs:
        dot, na2, nb2 = triplets[idx]
        ca = 1.0 - dot / (2.0 * na2) if na2 >= _NORMSQ_EPS else 1.0
        cb = 1.0 - dot / (2.0 * nb2) if nb2 >= _NORMSQ_EPS else 1.0
        if native_ok:  # pre-copy is only useful as the in-place operand
            out[lo:hi] = a[lo:hi]
            if _native.combine_inplace(out[lo:hi], b[lo:hi], ca, cb):
                continue
        out[lo:hi] = ca * a[lo:hi] + cb * b[lo:hi]
    return out


class AdasumAllreduce(cpu_ring.CollectiveOp):
    """VHDD Adasum over the TCP mesh, registered for ``ResponseType.ADASUM``."""

    def enabled(self, response: Response,
                entries: List[TensorTableEntry]) -> bool:
        # VHDD needs a power-of-two world (reference adasum.h restriction);
        # other sizes fall through to the averaging ring fallback registered
        # behind this one in the ADASUM chain.
        return (self.topo.size & (self.topo.size - 1)) == 0

    def _allreduce_triplets(self, triplets: np.ndarray,
                            distance: int) -> np.ndarray:
        """Sum the per-tensor triplets across the 2·distance ranks that hold
        slices of the current (a, b) pair — recursive doubling over XOR
        distances 1..distance (reference SumAllreduceWithComm on the level's
        reduction communicator, adasum.h:368)."""
        rank = self.topo.rank
        got = np.empty_like(triplets)  # tiny per-tensor metadata scratch
        j = 1
        while j <= distance:
            peer = rank ^ j
            self.mesh.sendrecv_into(
                peer, cpu_ring._byte_view(np.ascontiguousarray(triplets)),
                peer, cpu_ring._byte_view(got))
            triplets = triplets + got
            j <<= 1
        return triplets

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        size, rank = self.topo.size, self.topo.rank
        if size == 1:
            for e in entries:
                out = np.array(e.tensor, copy=True)
                if response.prescale_factor != 1.0:
                    out = out * response.prescale_factor
                if response.postscale_factor != 1.0:
                    out = out * response.postscale_factor
                e.output = out
            return Status.OK()
        if size & (size - 1):
            raise HorovodInternalError(
                f"Adasum VHDD requires a power-of-two world size, got {size} "
                f"(reference adasum.h has the same restriction)")

        acc_dtype = cpu_ring._accum_dtype(entries[0].tensor.dtype)
        staged = len(entries) > 1 and self.fusion_buffers is not None
        buf = cpu_ring.fuse_entries(entries, acc_dtype, self.fusion_buffers)
        if response.prescale_factor != 1.0:
            buf *= response.prescale_factor
        sizes = [int(np.prod(e.tensor.shape)) if e.tensor.shape else 1
                 for e in entries]
        num_tensors = len(sizes)
        real_n = buf.size
        # Zero-pad to a multiple of the world size so every halving round
        # splits evenly; pad regions sit outside all tensor bounds, are
        # never touched by a combine, and are dropped before unfuse.
        if real_n % size:
            pad = size - real_n % size
            buf = np.concatenate([buf, np.zeros(pad, acc_dtype)])
        n = buf.size

        # Vector-halving distance-doubling reduce-scatter with Adasum
        # combine (reference adasum.h:194-320).
        lo, hi = 0, n
        halves: List[Tuple[int, bool]] = []  # (distance, kept_upper)
        distance = 1
        while distance < size:
            peer = rank ^ distance
            mid = lo + (hi - lo) // 2
            keep_upper = (rank & distance) != 0
            if keep_upper:
                send_lo, send_hi, keep_lo, keep_hi = lo, mid, mid, hi
            else:
                send_lo, send_hi, keep_lo, keep_hi = mid, hi, lo, mid
            kept = buf[keep_lo:keep_hi]
            # Zero-copy exchange: our half goes out as a view, the peer's
            # lands in persistent staging (recv_into enforces that the
            # frame carries exactly kept.size elements — a mismatch
            # poisons the stream instead of mis-combining).
            stage = self.fusion_buffers.get(
                acc_dtype, kept.size, key="adasum-stage") \
                if self.fusion_buffers is not None \
                else np.empty(kept.size, acc_dtype)
            peer_half = stage[:kept.size]
            self.mesh.sendrecv_into(
                peer, cpu_ring._byte_view(buf[send_lo:send_hi]),
                peer, cpu_ring._byte_view(peer_half))
            # Canonical orientation: `a` is the vector accumulated by the
            # lower subgroup (bit `distance` clear), `b` by the upper —
            # every rank in the reduction group agrees on which is which.
            if (rank & distance) == 0:
                a_slice, b_slice = kept, peer_half
            else:
                a_slice, b_slice = peer_half, kept
            segs = _segment_bounds(sizes, keep_lo, keep_hi)
            triplets = _partial_triplets(a_slice, b_slice, segs, num_tensors)
            triplets = self._allreduce_triplets(triplets, distance)
            buf[keep_lo:keep_hi] = _apply_combine(
                a_slice, b_slice, segs, triplets)
            halves.append((distance, keep_upper))
            lo, hi = keep_lo, keep_hi
            distance <<= 1

        # Allgather back: walk distances in reverse, exchanging the owned
        # slice for the peer's (reference adasum.h:321-380).
        for distance, keep_upper in reversed(halves):
            peer = rank ^ distance
            span = hi - lo
            if keep_upper:
                other_lo, other_hi = lo - span, lo
            else:
                other_lo, other_hi = hi, hi + span
            # Disjoint slices of `buf`: send our slice as a view while the
            # peer's lands directly in its final position — no staging.
            self.mesh.sendrecv_into(
                peer, cpu_ring._byte_view(buf[lo:hi]),
                peer, cpu_ring._byte_view(buf[other_lo:other_hi]))
            lo, hi = min(lo, other_lo), max(hi, other_hi)

        buf = buf[:real_n]
        if response.postscale_factor != 1.0:
            buf = buf * response.postscale_factor
        cpu_ring.unfuse_entries(
            buf.astype(response.tensor_type.to_numpy(), copy=False), entries,
            copy=staged)
        return Status.OK()


class AdasumRingFallback(cpu_ring.RingAllreduce):
    """Non-power-of-two ADASUM fallback: ring-sum then average.

    The reference refuses non-pow2 worlds outright; a plain-sum fallback
    would make ``hvd.Adasum`` of identical gradients return ``size·g`` on 3
    ranks but ``~g`` on 2/4 ranks — a silent size-dependent magnitude
    cliff.  Averaging matches Adasum's identical-gradient (fully
    correlated) behavior, the common case for data-parallel gradients; a
    loud one-time warning flags the approximation."""

    _warned = False

    def enabled(self, response: Response,
                entries: List[TensorTableEntry]) -> bool:
        return response.response_type == ResponseType.ADASUM

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        if not AdasumRingFallback._warned:
            AdasumRingFallback._warned = True
            log.warning(
                "Adasum VHDD requires a power-of-two world size (have %d); "
                "falling back to ring-allreduce AVERAGE, which approximates "
                "Adasum only for well-correlated gradients", self.topo.size)
        scaled = copy.copy(response)
        scaled.postscale_factor = response.postscale_factor / self.topo.size
        return super().execute(scaled, entries)
