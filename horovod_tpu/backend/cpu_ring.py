"""Host-memory collective ops over the TCP mesh — the Gloo-role data plane.

Role of the reference's ``horovod/common/ops/gloo_operations.cc`` (CPU,
MPI-free backend) and the template-method base classes in
``ops/collective_operations.h:38-256``: fuse entries into one flat buffer,
run the collective, scatter results back out.  Algorithms:

- allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
  2·(N−1) steps — same family as NCCL's ring; ``gloo::allreduce`` ring).
  Low-precision floats travel NARROW on the wire and widen only inside
  each reduction step (reference ``half.cc`` custom MPI fp16 sum).
- hierarchical allreduce: intra-host reduce-scatter → cross-host ring on
  each rank's chunk → intra-host allgather, so each local rank carries
  1/local_size of the cross-host traffic in parallel (reference
  ``NCCLHierarchicalAllreduce``, ``nccl_operations.cc:194-405``).
- allgather(v): ring pipeline, N−1 steps of neighbor forwarding.
- broadcast: binomial tree from root, ⌈log2 N⌉ rounds (reference
  ``gloo::broadcast`` tree; the old star was O(N·bytes) serialized at
  root).
- alltoall(v): pairwise exchange, N−1 rounds of offset sendrecv.

Zero-copy, segment-pipelined (docs/data_plane.md): every ring step streams
its chunk as ``HOROVOD_RING_SEGMENT_BYTES``-sized segments — segment k
reduces in numpy while segment k+1 is on the wire — with sends framed
straight from buffer views and receives landing in persistent
``FusionBufferManager`` staging (or the output's final resting place).
Steady-state ring steps perform ZERO heap materializations of payload
bytes; the ``core/timeline.py`` ``wire_stats`` counters (``bytes_on_wire``,
``heap_copies``) prove it, and the test suite asserts it.

These run on numpy buffers and serve CPU deployments, multi-process tests,
and as the cross-host fallback; the XLA backend (``backend/xla.py``) is the
TPU data plane.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..common import env as env_mod
from ..common.exceptions import HorovodInternalError
from ..common.topology import ProcessTopology
from ..core import timeline as timeline_mod
from ..core.messages import DataType, Response, ResponseType
from ..core.tensor_queue import Status, TensorTableEntry
from ..core.timeline import wire_stats
from ..transport.tcp import TcpMesh


def _lc_span(names, stage: str, begin: bool) -> None:
    """Emit a lifecycle begin/end for every tensor riding this fused op.
    Callers pass an empty list when no timeline is active, so the
    steady-state cost is iterating nothing."""
    f = timeline_mod.lifecycle_begin if begin else timeline_mod.lifecycle_end
    for n in names:
        f(n, stage)


class FusionBufferManager:
    """Persistent keyed staging arenas (reference
    ``fusion_buffer_manager.h``): one allocation reused across cycles
    instead of a fresh tens-of-MB concatenate-and-free per fused response
    (VERDICT missing #6 — page-fault churn on every cycle).

    ``key`` separates concurrent roles sharing a dtype — the fusion
    buffer proper (``"fusion"``, the default), the ring's receive staging
    (``"ring-stage"``), the fused-allgather block arena (``"allgather"``)
    — so a staged fuse and a staged recv never alias each other."""

    def __init__(self):
        self._bufs: dict = {}

    def get(self, dtype: np.dtype, elems: int,
            key: str = "fusion") -> np.ndarray:
        dtype = np.dtype(dtype)
        buf = self._bufs.get((key, dtype))
        if buf is None or buf.size < elems:
            buf = np.empty(max(elems, 1), dtype=dtype)
            self._bufs[(key, dtype)] = buf
        return buf[:elems]


class CollectiveOp:
    """Base op: ``HorovodOp::Execute(entries, response)`` +
    ``Enabled(...)`` (reference ``collective_operations.h:38-87``)."""

    def __init__(self, topo: ProcessTopology, mesh: Optional[TcpMesh],
                 fusion_buffers: Optional[FusionBufferManager] = None):
        self.topo = topo
        self.mesh = mesh
        self.fusion_buffers = fusion_buffers

    def enabled(self, response: Response,
                entries: List[TensorTableEntry]) -> bool:
        raise NotImplementedError

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        raise NotImplementedError


def _accum_dtype(dtype: np.dtype) -> np.dtype:
    """Accumulate low-precision floats in fp32 (the reference's fp16 MPI sum
    op and Adasum both widen; bf16 has ~8 bits of mantissa, so naive ring
    accumulation would lose gradient mass)."""
    if dtype.itemsize <= 2 and np.issubdtype(dtype, np.floating):
        return np.dtype(np.float32)
    name = getattr(dtype, "name", "")
    if name == "bfloat16":
        return np.dtype(np.float32)
    return dtype


def _byte_view(arr: np.ndarray) -> memoryview:
    """Flat byte view over a contiguous numpy array — what the zero-copy
    transport sends from and receives into.  Goes through a uint8
    reinterpret view because extension dtypes (ml_dtypes bfloat16) export
    no PEP-3118 buffer format of their own, so ``memoryview(arr)`` would
    raise on exactly the narrow-wire dtypes this path exists for.
    Non-contiguous input raises (numpy refuses the view): the caller holds
    a strided view it must materialize itself — copying silently here
    would defeat the zero-copy contract."""
    return memoryview(arr.view(np.uint8).reshape(-1))


def fuse_entries(entries: List[TensorTableEntry], dtype: np.dtype,
                 fbm: Optional[FusionBufferManager] = None) -> np.ndarray:
    """MemcpyInFusionBuffer analog (``collective_operations.cc``).

    Never returns a view of an entry's tensor, so backends may mutate the
    result freely without corrupting user input — which makes exactly ONE
    copy per entry the floor, and this performs exactly that:
    ``astype(copy=True)`` BEFORE ``ravel`` materializes contiguous
    ``dtype`` output in a single pass (the old ravel-then-astype order
    double-copied non-contiguous tensors: ravel copied to flatten, astype
    copied again).  With ``fbm``, multi-entry payloads stage into the
    persistent fusion buffer (the result then ALIASES the manager's
    storage — callers must unfuse with ``copy=True`` before the next
    cycle reuses it)."""
    if len(entries) == 1:
        wire_stats.add("heap_copies")
        # order="C" matters: astype's default order="K" would keep a
        # Fortran-ordered input F-ordered and the ravel would copy AGAIN.
        return np.asarray(entries[0].tensor).astype(
            dtype, order="C", copy=True).ravel()
    wire_stats.add("heap_copies", len(entries))
    if fbm is not None:
        total = sum(int(np.asarray(e.tensor).size) for e in entries)
        buf = fbm.get(dtype, total)
        off = 0
        for e in entries:
            arr = np.asarray(e.tensor).ravel()
            buf[off:off + arr.size] = arr  # casts to `dtype` on assignment
            off += arr.size
        return buf
    return np.concatenate(
        [np.asarray(e.tensor).ravel() for e in entries]).astype(dtype, copy=False)


def unfuse_entries(buf: np.ndarray, entries: List[TensorTableEntry],
                   copy: bool = False) -> None:
    """MemcpyOutFusionBuffer analog: slice results into per-entry outputs.

    ``copy=True`` materializes each output (required when ``buf`` is the
    persistent fusion buffer — a view would be silently overwritten by the
    next fused response)."""
    if copy:
        wire_stats.add("heap_copies", len(entries))
    offset = 0
    for e in entries:
        n = int(np.asarray(e.tensor).size)
        out = buf[offset:offset + n].reshape(np.asarray(e.tensor).shape)
        e.output = out.copy() if copy else out
        offset += n


def _scale_inplace(buf: np.ndarray, factor: float, wide: np.dtype) -> None:
    """Scale, widening for low-precision dtypes (reference ScaleBuffer,
    ``collective_operations.h:89-125`` widens fp16 through fp32).  The
    native kernel (``_native/native.cc``) does it in one pass; numpy
    fallback needs temporaries."""
    from .. import _native

    if _native.scale_inplace(buf, factor):
        return
    if buf.dtype == wide:
        buf *= factor
    else:
        buf[:] = (buf.astype(wide) * factor).astype(buf.dtype)


def _widen_add(chunk: np.ndarray, incoming: np.ndarray,
               wide: np.dtype) -> None:
    """chunk += incoming with wide-precision arithmetic: the wire carries
    NARROW values (half the bytes for bf16/fp16) and only the add widens —
    the reference's custom MPI fp16 sum (``half.cc``) does exactly this,
    and ``_native/native.cc`` is our single-pass version of it."""
    from .. import _native

    if _native.add_inplace(chunk, incoming):
        return
    if chunk.dtype == wide:
        chunk += incoming
    else:
        chunk[:] = (chunk.astype(wide) + incoming.astype(wide)).astype(
            chunk.dtype)


def _chunk_bounds(n: int, parts: int) -> np.ndarray:
    base, rem = divmod(n, parts)
    counts = [base + (1 if c < rem else 0) for c in range(parts)]
    return np.cumsum([0] + counts)


def _segment_elems(dtype: np.dtype) -> int:
    """Pipeline segment size in ELEMENTS (≥ 1), from the shared
    ``HOROVOD_RING_SEGMENT_BYTES`` knob.  Every rank derives the same
    value (launcher-propagated env), so both endpoints of every link
    frame identically; a byte count below one element clamps to one, a
    count at or above the chunk size degrades to the unpipelined
    single-frame step."""
    seg_bytes = env_mod.get_int(env_mod.HOROVOD_RING_SEGMENT_BYTES,
                                env_mod.DEFAULT_RING_SEGMENT_BYTES)
    return max(1, seg_bytes // max(1, np.dtype(dtype).itemsize))


def _ring_exchange(mesh: TcpMesh, nxt: int, prv: int,
                   send_arr: np.ndarray, recv_arr: np.ndarray,
                   reduce_to: Optional[np.ndarray] = None,
                   wide: Optional[np.dtype] = None,
                   compressor=None,
                   fbm: Optional[FusionBufferManager] = None,
                   ef=None, wire_code: int = 0) -> None:
    """One zero-copy, segment-pipelined ring step — the primitive every
    host collective builds on.

    Streams ``send_arr`` to ``nxt`` in segments while receiving
    ``recv_arr.size`` elements from ``prv`` directly into ``recv_arr``'s
    segments; when ``reduce_to`` is given, each landed segment is folded
    into it (wide-precision add) while the NEXT segment is still on the
    wire::

        post recv k → send k → wait k-1 → reduce k-1        (per segment)

    At most two receives are ever outstanding, so staging never needs
    more than the chunk itself.  Segment boundaries derive from the
    shared knob and the (negotiated) transfer sizes, so both endpoints of
    every link frame identically; zero-size transfers send no frame at
    all (both sides agree they would be empty).  Sends are views over
    ``send_arr`` and receives land via ``recv_into`` — the hot loop's
    only per-byte work is the numpy add.

    Integrity (``mesh.deferred_digests``, the default): segment frames go
    out digest-DEFERRED — no inline CRC; both endpoints chain per-frame
    digests off the serial path (sender right after the vectored write,
    receiver on the helper thread in the reduce's shadow) and the step
    closes with a digest-check frame each way, verified BEFORE this
    function returns, so corrupt bytes never escape the collective.

    Compression (``compressor`` + ``fbm``): each send segment is cast
    into a persistent narrow arena and framed from there; receives land
    in a narrow arena and widen during the reduce (or restore, allgather
    phase) — ``recv_arr`` then only defines the logical element layout.
    The frame header carries the wire dtype code, so a peer with a
    different ``HOROVOD_WIRE_COMPRESSION`` aborts loudly.

    Lossy codecs (``compressor.lossy``): segments travel as codec-framed
    BYTE blobs whose per-segment sizes both endpoints derive from
    ``wire_nbytes`` (the transport's exact-size contract holds even for
    variable-length topk); ``ef`` is the per-tensor error-feedback state
    threaded into every encode.  ``wire_code`` stamps a dtype code on a
    RAW (compressor-less) exchange — the byte-forwarding allgather sends
    already-encoded blobs verbatim but must keep the skew detector
    armed."""
    seg = _segment_elems(send_arr.dtype)
    sn, rn = int(send_arr.size), int(recv_arr.size)
    n_send = -(-sn // seg)
    n_recv = -(-rn // seg)
    lossy = compressor is not None and getattr(compressor, "lossy", False)
    # Deferred-ness is a PER-LINK question (transport/select.py): under a
    # mixed mesh the send direction may ride shm (CRC default off, no
    # digests) while the recv direction rides TCP (shadow digests on) —
    # each direction frames by its own link's answer, and both endpoints
    # of one link always agree (the knobs are env-propagated).
    send_dig = mesh.new_digest() \
        if n_send and mesh.deferred_digests_for(nxt) else None
    recv_dig = mesh.new_digest() \
        if n_recv and mesh.deferred_digests_for(prv) else None
    code = wire_code
    send_stage = recv_stage = None
    send_nb = recv_offs = None
    if lossy:
        code = compressor.code
        # Per-segment compressed byte sizes (the last segment may be
        # short); both endpoints derive the identical layout from the
        # shared bounds + knobs, never from the bytes themselves.
        wnb = compressor.wire_nbytes
        send_nb = [wnb(min(sn, (k + 1) * seg) - k * seg, send_arr.dtype)
                   for k in range(n_send)]
        recv_nb = [wnb(min(rn, (k + 1) * seg) - k * seg, recv_arr.dtype)
                   for k in range(n_recv)]
        recv_offs = [0]
        for b in recv_nb:
            recv_offs.append(recv_offs[-1] + b)
        sse = max(send_nb) if send_nb else 1
        rse = recv_offs[-1] if recv_nb else 1
        if fbm is not None:
            send_stage = fbm.get(np.uint8, sse, key="wire-send")
            recv_stage = fbm.get(np.uint8, rse, key="wire-recv")
        else:
            send_stage = np.empty(sse, dtype=np.uint8)
            recv_stage = np.empty(rse, dtype=np.uint8)
    elif compressor is not None:
        code = compressor.code
        wdt = compressor.wire_dtype
        # Send staging is one segment (``send`` returns only after the
        # kernel owns the bytes, so it is reusable); recv staging spans
        # the whole transfer because segment k+1 lands while k is still
        # being widened out of its slot.
        sse, rse = min(seg, sn) if sn else 1, rn if rn else 1
        if fbm is not None:
            send_stage = fbm.get(wdt, sse, key="wire-send")
            recv_stage = fbm.get(wdt, rse, key="wire-recv")
        else:
            send_stage = np.empty(sse, dtype=wdt)
            recv_stage = np.empty(rse, dtype=wdt)
    prev_k = -1
    prev_h = None
    # One extra iteration drains the final outstanding receive — the
    # k-bound guards make it a pure wait/reduce pass.
    for k in range(max(n_send, n_recv) + 1):
        cur = None
        if k < n_recv:
            lo = k * seg
            hi = min(rn, lo + seg)
            if lossy:
                dest = recv_stage[recv_offs[k]:recv_offs[k + 1]]
            elif compressor is not None:
                dest = recv_stage[lo:hi]
            else:
                dest = recv_arr[lo:hi]
            cur = mesh.recv_into_async(prv, _byte_view(dest),
                                       digest=recv_dig, wire_dtype=code)
        if k < n_send:
            lo = k * seg
            src = send_arr[lo:min(sn, lo + seg)]
            if lossy:
                blob = send_stage[:send_nb[k]]
                compressor.encode(src, blob, ef)
                src = blob
            elif compressor is not None:
                src = compressor.compress(src, send_stage)
            mesh.send(nxt, _byte_view(src), digest=send_dig,
                      wire_dtype=code)
        if prev_h is not None:
            prev_h.wait()
            lo = prev_k * seg
            hi = min(rn, lo + seg)
            if lossy:
                blob = recv_stage[recv_offs[prev_k]:recv_offs[prev_k + 1]]
                if reduce_to is not None:
                    compressor.decode_add(blob, reduce_to[lo:hi])
                else:
                    compressor.decode_into(blob, recv_arr[lo:hi])
            elif compressor is not None:
                if reduce_to is not None:
                    compressor.decompress_add(recv_stage[lo:hi],
                                              reduce_to[lo:hi])
                else:
                    compressor.decompress_into(recv_stage[lo:hi],
                                               recv_arr[lo:hi])
            elif reduce_to is not None:
                _widen_add(reduce_to[lo:hi], recv_arr[lo:hi], wide)
        prev_k, prev_h = k, cur
    # Settle integrity at the step boundary: every posted recv has been
    # waited above, so the check frame is next in FIFO order.
    if send_dig is not None:
        mesh.send_step_digest(nxt, send_dig, n_send)
    if recv_dig is not None:
        mesh.verify_step_digest(prv, recv_dig, n_recv)


def _ring_reduce_scatter(mesh: TcpMesh, buf: np.ndarray, group: List[int],
                         idx: int, wide: np.dtype,
                         fbm: Optional[FusionBufferManager] = None,
                         compressor=None,
                         lc_name: Optional[str] = None,
                         ef=None) -> np.ndarray:
    """Segment-pipelined ring reduce-scatter over ``group`` (ordered
    global ranks; ``idx`` is our position).  Returns the chunk bounds;
    afterwards position ``idx`` owns the fully reduced chunk
    ``(idx + 1) % len(group)``.

    Incoming segments land in a persistent staging slice (never a
    per-step allocation) and the only per-byte work on the hot path is
    the widened numpy add — zero heap copies per step.  With
    ``compressor``, segments travel narrow and the add widens straight
    out of the narrow staging (``backend/compression.py``).  ``ef`` is
    the error-feedback accumulator threaded into every lossy encode —
    reduce-scatter sends are the only place residuals are folded back."""
    g = len(group)
    bounds = _chunk_bounds(buf.size, g)
    nxt, prv = group[(idx + 1) % g], group[(idx - 1) % g]
    max_chunk = int(bounds[1] - bounds[0])  # chunk 0 is never the smaller
    stage = fbm.get(buf.dtype, max_chunk, key="ring-stage") \
        if fbm is not None else np.empty(max_chunk, dtype=buf.dtype)
    for s in range(g - 1):
        send_c = (idx - s) % g
        recv_c = (idx - s - 1) % g
        chunk = buf[bounds[recv_c]:bounds[recv_c + 1]]
        # Ring-step lifecycle spans go on ONE representative lane (the
        # fused buffer moves as a unit; per-tensor step spans would just
        # multiply trace volume).
        if lc_name is not None:
            timeline_mod.lifecycle_begin(lc_name, "LC_RS_STEP")
        _ring_exchange(mesh, nxt, prv,
                       buf[bounds[send_c]:bounds[send_c + 1]],
                       stage[:chunk.size], reduce_to=chunk, wide=wide,
                       compressor=compressor, fbm=fbm, ef=ef)
        if lc_name is not None:
            timeline_mod.lifecycle_end(lc_name, "LC_RS_STEP")
    return bounds


def _ring_allgather_chunks(mesh: TcpMesh, buf: np.ndarray, group: List[int],
                           idx: int, bounds: np.ndarray,
                           fbm: Optional[FusionBufferManager] = None,
                           compressor=None,
                           lc_name: Optional[str] = None) -> None:
    """Segment-pipelined ring allgather of per-position chunks (each
    position starts owning chunk ``(idx + 1) % g``, the reduce-scatter
    ownership).  Chunks land DIRECTLY in their final location in ``buf``
    — no staging, no copy; the wire is the only mover.  With
    ``compressor``, segments travel narrow and restore into place; the
    caller must have quantized owned chunks first so ranks stay
    bit-identical (``quantize_inplace``)."""
    g = len(group)
    nxt, prv = group[(idx + 1) % g], group[(idx - 1) % g]
    for s in range(g - 1):
        send_c = (idx + 1 - s) % g
        recv_c = (idx - s) % g
        if lc_name is not None:
            timeline_mod.lifecycle_begin(lc_name, "LC_AG_STEP")
        _ring_exchange(mesh, nxt, prv,
                       buf[bounds[send_c]:bounds[send_c + 1]],
                       buf[bounds[recv_c]:bounds[recv_c + 1]],
                       compressor=compressor, fbm=fbm)
        if lc_name is not None:
            timeline_mod.lifecycle_end(lc_name, "LC_AG_STEP")


def _ring_allgather_bytes(mesh: TcpMesh, buf: np.ndarray, group: List[int],
                          idx: int, bounds: np.ndarray, compressor,
                          fbm: Optional[FusionBufferManager] = None,
                          lc_name: Optional[str] = None) -> None:
    """Byte-forwarding ring allgather for LOSSY codecs.  The owner of
    each chunk encodes it ONCE (no error feedback — the residual was
    already folded in during reduce-scatter) and decodes its own bytes
    back in place; every subsequent hop forwards the received byte blob
    VERBATIM and decodes a copy locally.  All ranks therefore decode the
    exact same bytes for every chunk — bit-identical by construction,
    which is stronger than re-encoding at each hop (lossy encode∘decode
    is not provably idempotent the way fp16/bf16 casts are).  Compressed
    chunk sizes come from ``wire_nbytes`` on the shared bounds, so the
    variable-length topk frames keep the exact-size wire contract."""
    g = len(group)
    nxt, prv = group[(idx + 1) % g], group[(idx - 1) % g]
    sizes = [compressor.wire_nbytes(int(bounds[c + 1] - bounds[c]),
                                    buf.dtype)
             if bounds[c + 1] > bounds[c] else 0 for c in range(g)]
    arena = max(sizes) if sizes else 0
    if arena == 0:
        return
    if fbm is not None:
        hold = fbm.get(np.uint8, arena, key="wire-ag-hold")
        land = fbm.get(np.uint8, arena, key="wire-ag-land")
    else:
        hold = np.empty(arena, dtype=np.uint8)
        land = np.empty(arena, dtype=np.uint8)
    own = (idx + 1) % g
    chunk = buf[bounds[own]:bounds[own + 1]]
    if chunk.size:
        compressor.encode(chunk, hold[:sizes[own]])
        compressor.decode_into(hold[:sizes[own]], chunk)
    for s in range(g - 1):
        send_c = (idx + 1 - s) % g
        recv_c = (idx - s) % g
        if lc_name is not None:
            timeline_mod.lifecycle_begin(lc_name, "LC_AG_STEP")
        _ring_exchange(mesh, nxt, prv, hold[:sizes[send_c]],
                       land[:sizes[recv_c]], fbm=fbm,
                       wire_code=compressor.code)
        if sizes[recv_c]:
            compressor.decode_into(land[:sizes[recv_c]],
                                   buf[bounds[recv_c]:bounds[recv_c + 1]])
        hold, land = land, hold
        if lc_name is not None:
            timeline_mod.lifecycle_end(lc_name, "LC_AG_STEP")


def _quantize_owned(compressor, chunk: np.ndarray,
                    fbm: Optional[FusionBufferManager]) -> None:
    """Round-trip an owned (fully reduced) chunk through the wire dtype
    before it is allgathered: receivers only ever see quantized values,
    so the owner must not keep its extra wide precision — all ranks end
    the allreduce bit-identical (the elastic recovery proof depends on
    it)."""
    if chunk.size == 0:
        return
    arena = fbm.get(compressor.wire_dtype, chunk.size, key="wire-quant") \
        if fbm is not None \
        else np.empty(chunk.size, dtype=compressor.wire_dtype)
    compressor.quantize_inplace(chunk, arena)


class RingAllreduce(CollectiveOp):
    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.ALLREDUCE

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        np_dtype = response.tensor_type.to_numpy()
        wide = _accum_dtype(np_dtype)
        # Fuse in the ORIGINAL dtype: the ring sends narrow bytes and
        # widens only inside the reduction (VERDICT weak #4 — fusing wide
        # doubled the wire cost of every bf16/fp16 tensor).
        staged = len(entries) > 1 and self.fusion_buffers is not None
        lc = [e.tensor_name for e in entries] \
            if timeline_mod.ACTIVE is not None \
            and timeline_mod.LIFECYCLE_ENABLED else []
        _lc_span(lc, "LC_FUSE", True)
        work = fuse_entries(entries, np_dtype, self.fusion_buffers)
        _lc_span(lc, "LC_FUSE", False)

        if response.prescale_factor != 1.0:
            _scale_inplace(work, response.prescale_factor, wide)

        if self.topo.size > 1:
            # Error-feedback accumulators are keyed by the fused tensor
            # set: the same fusion replays the same compress sequence, so
            # residuals line up with the segments that produced them.
            ef_key = tuple(e.tensor_name for e in entries)
            work = self._ring_allreduce(work, wide, lc, ef_key=ef_key)

        if response.postscale_factor != 1.0:
            _scale_inplace(work, response.postscale_factor, wide)

        _lc_span(lc, "LC_UNFUSE", True)
        unfuse_entries(work, entries, copy=staged)
        _lc_span(lc, "LC_UNFUSE", False)
        return Status.OK()

    def _ef_for(self, comp, ef_key):
        """Per-op error-feedback state, lazily created.  Owned by the op
        instance so an elastic re-init drops stale residuals along with
        the op — surviving ranks and joiners agree on empty accumulators,
        which the bit-identical recovery proof depends on."""
        from .compression import EfState, ef_enabled

        if comp is None or not getattr(comp, "lossy", False) \
                or not ef_enabled():
            return None
        ef = getattr(self, "_ef_state", None)
        if ef is None:
            ef = self._ef_state = EfState()
        ef.begin(ef_key)
        return ef

    def _ring_allreduce(self, buf: np.ndarray, wide: np.dtype,
                        lc_names: List[str] = (),
                        ef_key=()) -> np.ndarray:
        from .compression import wire_compressor_for

        group = list(range(self.topo.size))
        comp = wire_compressor_for(buf.dtype)
        lossy = comp is not None and getattr(comp, "lossy", False)
        ef = self._ef_for(comp, ef_key)
        step_lane = lc_names[0] if lc_names else None
        _lc_span(lc_names, "LC_WIRE_REDUCE_SCATTER", True)
        bounds = _ring_reduce_scatter(
            self.mesh, buf, group, self.topo.rank, wide,
            self.fusion_buffers, compressor=comp, lc_name=step_lane,
            ef=ef)
        _lc_span(lc_names, "LC_WIRE_REDUCE_SCATTER", False)
        _lc_span(lc_names, "LC_WIRE_ALLGATHER", True)
        if lossy:
            _ring_allgather_bytes(
                self.mesh, buf, group, self.topo.rank, bounds, comp,
                self.fusion_buffers, lc_name=step_lane)
        else:
            if comp is not None:
                own = (self.topo.rank + 1) % len(group)
                _quantize_owned(comp, buf[bounds[own]:bounds[own + 1]],
                                self.fusion_buffers)
            _ring_allgather_chunks(
                self.mesh, buf, group, self.topo.rank, bounds,
                self.fusion_buffers, compressor=comp, lc_name=step_lane)
        _lc_span(lc_names, "LC_WIRE_ALLGATHER", False)
        return buf


class HierarchicalAllreduce(RingAllreduce):
    """Two-level allreduce using the LOCAL/CROSS coordinates (reference
    ``NCCLHierarchicalAllreduce``, ``nccl_operations.cc:194-405``):

      1. intra-host ring reduce-scatter (fast local fabric),
      2. cross-host ring allreduce of each local rank's chunk — all
         local ranks drive their cross-host ring IN PARALLEL, so each
         host moves only 1/local_size of the payload over the slow links,
      3. intra-host ring allgather.

    Enabled for homogeneous multi-host × multi-local topologies with the
    host-major rank layout the launcher guarantees; HOROVOD_HIERARCHICAL_
    ALLREDUCE=0/1 forces it off/on (reference knob, ``common.h:79``)."""

    @staticmethod
    def applicable(topo: ProcessTopology) -> bool:
        from ..common import env as env_mod

        if env_mod.get_str(env_mod.HOROVOD_HIERARCHICAL_ALLREDUCE) in (
                "0", "false", "False"):
            return False
        # The structural requirements are safety, not preference — a forced
        # "1" cannot override them (heterogeneous hosts would disagree on
        # chunk bounds in the cross phase and deadlock).
        return (topo.local_size > 1 and topo.cross_size > 1
                and topo.is_homogeneous
                and topo.rank == topo.cross_rank * topo.local_size
                + topo.local_rank)

    def _ring_allreduce(self, buf: np.ndarray, wide: np.dtype,
                        lc_names: List[str] = (),
                        ef_key=()) -> np.ndarray:
        from .compression import wire_compressor_for

        t = self.topo
        comp = wire_compressor_for(buf.dtype)
        lossy = comp is not None and getattr(comp, "lossy", False)
        # One EF sequence spans the local AND cross reduce-scatters —
        # ``begin`` rewinds the counter once per allreduce and the two
        # phases replay their encodes in a fixed order.
        ef = self._ef_for(comp, ef_key)
        local_group = [t.cross_rank * t.local_size + l
                       for l in range(t.local_size)]
        cross_group = [c * t.local_size + t.local_rank
                       for c in range(t.cross_size)]
        step_lane = lc_names[0] if lc_names else None

        _lc_span(lc_names, "LC_WIRE_REDUCE_SCATTER", True)
        bounds = _ring_reduce_scatter(
            self.mesh, buf, local_group, t.local_rank, wide,
            self.fusion_buffers, compressor=comp, lc_name=step_lane,
            ef=ef)
        _lc_span(lc_names, "LC_WIRE_REDUCE_SCATTER", False)
        own = (t.local_rank + 1) % t.local_size
        seg = buf[bounds[own]:bounds[own + 1]]
        if seg.size:
            # Cross-host phase (its own reduce-scatter + allgather ring)
            # gets one combined span — LC_WIRE_CROSS — per tensor.
            _lc_span(lc_names, "LC_WIRE_CROSS", True)
            seg_bounds = _ring_reduce_scatter(
                self.mesh, seg, cross_group, t.cross_rank, wide,
                self.fusion_buffers, compressor=comp, ef=ef)
            if lossy:
                _ring_allgather_bytes(
                    self.mesh, seg, cross_group, t.cross_rank,
                    seg_bounds, comp, self.fusion_buffers)
            else:
                if comp is not None:
                    own_c = (t.cross_rank + 1) % t.cross_size
                    _quantize_owned(
                        comp,
                        seg[seg_bounds[own_c]:seg_bounds[own_c + 1]],
                        self.fusion_buffers)
                _ring_allgather_chunks(
                    self.mesh, seg, cross_group, t.cross_rank,
                    seg_bounds, self.fusion_buffers, compressor=comp)
            _lc_span(lc_names, "LC_WIRE_CROSS", False)
        if comp is not None and not lossy:
            # The whole owned chunk goes into the local allgather; parts
            # restored from the wire are already quantized (idempotent),
            # this pins the cross-phase leftovers.  Lossy codecs skip
            # this — the local byte-forwarding allgather owner-encodes
            # the chunk once and every rank decodes those same bytes.
            _quantize_owned(comp, seg, self.fusion_buffers)
        _lc_span(lc_names, "LC_WIRE_ALLGATHER", True)
        if lossy:
            _ring_allgather_bytes(
                self.mesh, buf, local_group, t.local_rank, bounds, comp,
                self.fusion_buffers, lc_name=step_lane)
        else:
            _ring_allgather_chunks(
                self.mesh, buf, local_group, t.local_rank, bounds,
                self.fusion_buffers, compressor=comp, lc_name=step_lane)
        _lc_span(lc_names, "LC_WIRE_ALLGATHER", False)
        return buf


class RingAllgather(CollectiveOp):
    """Fused allgatherv: each rank's entries are packed into ONE local
    block which makes a single trip around the ring; outputs are sliced
    out by the negotiated per-(tensor, rank) first-dim matrix (reference
    allgather fusion + displacement math,
    ``collective_operations.h:140-176``).

    Zero-copy: per-origin blocks live contiguously in one persistent
    arena (or, single-entry, directly in the output buffer, whose
    rank-major block order IS the output layout), and every ring step
    receives straight into the destination block — per-step allocations
    and ``tobytes``/``frombuffer`` round trips are gone."""

    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.ALLGATHER

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        size, rank = self.topo.size, self.topo.rank
        k = len(entries)
        # tensor_sizes is k blocks of `size` per-rank first dims:
        # dim0 of tensor i on rank r = tensor_sizes[i*size + r].
        m = response.tensor_sizes
        tensors = [np.ascontiguousarray(e.tensor) for e in entries]
        inners = [t.shape[1:] if t.ndim else () for t in tensors]
        inner_ns = [int(np.prod(i)) if i else 1 for i in inners]

        if size == 1:
            wire_stats.add("heap_copies", k)
            for e, t in zip(entries, tensors):
                e.output = t.copy()
            return Status.OK()

        def block_elems(r: int) -> int:
            return sum(m[i * size + r] * inner_ns[i] for i in range(k))

        dtype = tensors[0].dtype
        offs = np.cumsum([0] + [block_elems(r) for r in range(size)])
        total = int(offs[-1])
        if k == 1:
            # Rank-major blocks ARE the single tensor's output layout:
            # gather straight into the output allocation, zero staging.
            arena = np.empty(total, dtype=dtype)
        elif self.fusion_buffers is not None:
            arena = self.fusion_buffers.get(dtype, total, key="allgather")
        else:
            arena = np.empty(total, dtype=dtype)
        blocks = [arena[int(offs[r]):int(offs[r + 1])] for r in range(size)]

        # Stage our own block into place — the op's one local copy.
        wire_stats.add("heap_copies", k)
        own = blocks[rank]
        off = 0
        for t in tensors:
            flat = t.ravel()
            own[off:off + flat.size] = flat
            off += flat.size

        # Ring forwarding: at step s we send the block that originated at
        # (rank - s) and receive the one originated at (rank - s - 1),
        # segment-pipelined, straight into its arena slot.  recv_into
        # enforces the exact negotiated block size — a corrupt frame or
        # desynced negotiation poisons the stream instead of mis-slicing
        # outputs.
        nxt, prv = (rank + 1) % size, (rank - 1) % size
        for s in range(size - 1):
            send_origin = (rank - s) % size
            recv_origin = (rank - s - 1) % size
            _ring_exchange(self.mesh, nxt, prv,
                           blocks[send_origin], blocks[recv_origin])

        if k == 1:
            entries[0].output = arena.reshape((-1,) + inners[0])
            return Status.OK()
        # Multi-entry: outputs interleave across blocks, so assembly
        # materializes each tensor (also required — the arena is reused
        # by the next fused response).
        wire_stats.add("heap_copies", k)
        for i, e in enumerate(entries):
            parts = []
            for r in range(size):
                off = sum(m[j * size + r] * inner_ns[j] for j in range(i))
                n = m[i * size + r] * inner_ns[i]
                parts.append(blocks[r][off:off + n].reshape(
                    (m[i * size + r],) + inners[i]))
            e.output = np.concatenate(parts, axis=0)
        return Status.OK()


class TreeBroadcast(CollectiveOp):
    """Binomial-tree broadcast: ⌈log2 N⌉ rounds, root sends each payload
    at most log N times instead of N−1 (reference ``gloo::broadcast``
    tree; VERDICT weak #3 — the old star serialized O(N·bytes) at root).

    Segment-pipelined relay: each landed segment is forwarded to every
    child while the NEXT segment is still arriving from the parent, so a
    deep tree streams like a pipeline instead of store-and-forwarding
    whole payloads at every level.  Non-root ranks receive straight into
    the output allocation — no intermediate bytes, no final copy."""

    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.BROADCAST

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        entry = entries[0]
        root = entry.root_rank
        size, rank = self.topo.size, self.topo.rank
        if size == 1:
            entry.output = np.ascontiguousarray(entry.tensor)
            return Status.OK()

        # Virtual ranks put the root at 0 so the tree math is uniform.
        vrank = (rank - root) % size
        shape = np.asarray(entry.tensor).shape
        if vrank == 0:
            data = np.ascontiguousarray(entry.tensor).ravel()
            # Never received; may send on every bit below the tree height
            # (next power of two ≥ size — size itself may not be one).
            recv_mask = 1 << (size - 1).bit_length()
            parent = None
        else:
            # Receive from the parent: the peer that differs in our lowest
            # set bit (it got the payload in an earlier round).
            mask = 1
            while not (vrank & mask):
                mask <<= 1
            parent = ((vrank ^ mask) + root) % size
            recv_mask = mask
            data = np.empty(int(np.asarray(entry.tensor).size),
                            dtype=response.tensor_type.to_numpy())

        # Forward to children: every peer vrank|mask for masks below the
        # one we received on (binomial fan-out).
        children = []
        mask = recv_mask >> 1
        while mask:
            child_v = vrank | mask
            if child_v != vrank and child_v < size:
                children.append((child_v + root) % size)
            mask >>= 1

        seg = _segment_elems(data.dtype)
        n = int(data.size)
        nseg = -(-n // seg)
        if parent is None:
            for k in range(nseg):
                lo, hi = k * seg, min(n, (k + 1) * seg)
                for child in children:
                    self.mesh.send(child, _byte_view(data[lo:hi]))
        else:
            pending = self.mesh.recv_into_async(
                parent, _byte_view(data[0:min(n, seg)])) if nseg else None
            for k in range(nseg):
                cur, pending = pending, None
                if k + 1 < nseg:
                    lo = (k + 1) * seg
                    pending = self.mesh.recv_into_async(
                        parent, _byte_view(data[lo:min(n, lo + seg)]))
                cur.wait()
                lo, hi = k * seg, min(n, (k + 1) * seg)
                for child in children:
                    self.mesh.send(child, _byte_view(data[lo:hi]))

        if vrank == 0:
            entry.output = np.ascontiguousarray(entry.tensor)
        else:
            entry.output = data.reshape(shape)
        return Status.OK()


# Backwards-compatible alias (the star topology is gone; VERDICT weak #3).
StarBroadcast = TreeBroadcast


class PairwiseAlltoall(CollectiveOp):
    """Pairwise exchange, N−1 rounds of offset sendrecv — each peer's
    block received straight into its final position in the preallocated
    output (zero staging, zero assembly concatenate)."""

    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.ALLTOALL

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        entry = entries[0]
        tensor = np.ascontiguousarray(entry.tensor)
        size, rank = self.topo.size, self.topo.rank
        # Flattened N×N split matrix from the controller; row r = rank r's
        # send splits, so our recv split from rank r is matrix[r][rank].
        matrix = response.tensor_sizes
        send_splits = matrix[rank * size:(rank + 1) * size]
        recv_splits = [matrix[r * size + rank] for r in range(size)]
        entry.received_splits = recv_splits

        inner = tensor.shape[1:]
        inner_n = int(np.prod(inner)) if inner else 1
        send_bounds = np.cumsum([0] + list(send_splits))
        recv_bounds = np.cumsum([0] + [s * inner_n for s in recv_splits])
        out = np.empty(int(recv_bounds[-1]), dtype=tensor.dtype)

        # Our own block goes straight to its final position — the op's
        # one local copy.
        wire_stats.add("heap_copies")
        out[int(recv_bounds[rank]):int(recv_bounds[rank + 1])] = \
            tensor[send_bounds[rank]:send_bounds[rank + 1]].ravel()

        for off in range(1, size):
            to = (rank + off) % size
            frm = (rank - off) % size
            _ring_exchange(
                self.mesh, to, frm,
                tensor[send_bounds[to]:send_bounds[to + 1]].reshape(-1),
                out[int(recv_bounds[frm]):int(recv_bounds[frm + 1])])

        entry.output = out.reshape((-1,) + inner)
        return Status.OK()


def zero_entry_for(response: Response, index: int, offset_elems: int,
                   num_elems: int) -> TensorTableEntry:
    """Zero-substitute a tensor a joined rank never submitted (reference
    ``tensor_queue.h:39-41`` builds zero tensors for joined ranks).

    When the response was negotiated on the XLA device plane, the zeros are
    a jax device array so the joined rank still takes the same (device)
    code path as its peers — a host-numpy substitute would silently flip
    this rank to the TCP backend while the others run the XLA collective."""
    dtype = response.tensor_type.to_numpy()
    from . import xla as xla_backend

    if response.devices == [xla_backend.XLA_DEVICE_ID]:
        if not xla_backend.context().ready:
            # Peers negotiated the device plane but this rank cannot join
            # it: a numpy substitute would silently flip this rank to the
            # TCP backend while the others dispatch the XLA collective — a
            # cross-rank deadlock.  Fail loudly instead; the peers' device
            # collective times out and errors, and elastic recovery (when
            # enabled) takes over.
            from ..common.exceptions import HorovodInternalError

            raise HorovodInternalError(
                "join zero-substitution: peers negotiated the XLA device "
                "plane but the local XlaContext is not ready")
        import jax.numpy as jnp

        zeros = jnp.zeros(num_elems, dtype=dtype)
    else:
        zeros = np.zeros(num_elems, dtype=dtype)
    return TensorTableEntry(
        tensor_name=response.tensor_names[index],
        tensor=zeros,
        callback=lambda status, entry: None,
        device=(xla_backend.XLA_DEVICE_ID
                if not isinstance(zeros, np.ndarray) else -1),
    )
