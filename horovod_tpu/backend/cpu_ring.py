"""Host-memory collective ops over the TCP mesh — the Gloo-role data plane.

Role of the reference's ``horovod/common/ops/gloo_operations.cc`` (CPU,
MPI-free backend) and the template-method base classes in
``ops/collective_operations.h:38-256``: fuse entries into one flat buffer,
run the collective, scatter results back out.  Algorithms:

- allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
  2·(N−1) steps — same family as NCCL's ring; ``gloo::allreduce`` ring).
- allgather(v): ring pipeline, N−1 steps of neighbor forwarding.
- broadcast: star from root (control-plane sizes; tree is a later
  optimization).
- alltoall(v): pairwise exchange, N−1 rounds of offset sendrecv.

These run on numpy buffers and serve CPU deployments, multi-process tests,
and as the cross-host fallback; the XLA backend (``backend/xla.py``) is the
TPU data plane.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..common.topology import ProcessTopology
from ..core.messages import DataType, Response, ResponseType
from ..core.tensor_queue import Status, TensorTableEntry
from ..transport.tcp import TcpMesh


class CollectiveOp:
    """Base op: ``HorovodOp::Execute(entries, response)`` +
    ``Enabled(...)`` (reference ``collective_operations.h:38-87``)."""

    def __init__(self, topo: ProcessTopology, mesh: Optional[TcpMesh]):
        self.topo = topo
        self.mesh = mesh

    def enabled(self, response: Response,
                entries: List[TensorTableEntry]) -> bool:
        raise NotImplementedError

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        raise NotImplementedError


def _accum_dtype(dtype: np.dtype) -> np.dtype:
    """Accumulate low-precision floats in fp32 (the reference's fp16 MPI sum
    op and Adasum both widen; bf16 has ~8 bits of mantissa, so naive ring
    accumulation would lose gradient mass)."""
    if dtype.itemsize <= 2 and np.issubdtype(dtype, np.floating):
        return np.dtype(np.float32)
    name = getattr(dtype, "name", "")
    if name == "bfloat16":
        return np.dtype(np.float32)
    return dtype


def fuse_entries(entries: List[TensorTableEntry], dtype: np.dtype) -> np.ndarray:
    """MemcpyInFusionBuffer analog (``collective_operations.cc``).

    Always returns a fresh buffer in ``dtype`` — never a view of an entry's
    tensor, so backends may mutate it freely without corrupting user input."""
    if len(entries) == 1:
        return np.asarray(entries[0].tensor).ravel().astype(dtype, copy=True)
    return np.concatenate(
        [np.asarray(e.tensor).ravel() for e in entries]).astype(dtype, copy=False)


def unfuse_entries(buf: np.ndarray, entries: List[TensorTableEntry]) -> None:
    """MemcpyOutFusionBuffer analog: slice results into per-entry outputs."""
    offset = 0
    for e in entries:
        n = int(np.asarray(e.tensor).size)
        e.output = buf[offset:offset + n].reshape(np.asarray(e.tensor).shape)
        offset += n


class RingAllreduce(CollectiveOp):
    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.ALLREDUCE

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        np_dtype = response.tensor_type.to_numpy()
        acc = _accum_dtype(np_dtype)
        work = fuse_entries(entries, acc)

        if response.prescale_factor != 1.0:
            work *= response.prescale_factor

        if self.topo.size > 1:
            work = self._ring_allreduce(work)

        if response.postscale_factor != 1.0:
            work *= response.postscale_factor

        out = work.astype(np_dtype, copy=False)
        unfuse_entries(out, entries)
        return Status.OK()

    def _ring_allreduce(self, buf: np.ndarray) -> np.ndarray:
        size, rank = self.topo.size, self.topo.rank
        nxt, prv = (rank + 1) % size, (rank - 1) % size
        n = buf.size
        # chunk c covers [bounds[c], bounds[c+1])
        base, rem = divmod(n, size)
        counts = [base + (1 if c < rem else 0) for c in range(size)]
        bounds = np.cumsum([0] + counts)

        def chunk(c):
            return buf[bounds[c]:bounds[c + 1]]

        # reduce-scatter: step s, send chunk (rank - s), recv chunk (rank-s-1)
        for s in range(size - 1):
            send_c = (rank - s) % size
            recv_c = (rank - s - 1) % size
            recv = self.mesh.sendrecv(nxt, chunk(send_c).tobytes(), prv)
            incoming = np.frombuffer(recv, dtype=buf.dtype)
            chunk(recv_c)[:] += incoming
        # allgather: step s, send chunk (rank+1-s), recv chunk (rank-s)
        for s in range(size - 1):
            send_c = (rank + 1 - s) % size
            recv_c = (rank - s) % size
            recv = self.mesh.sendrecv(nxt, chunk(send_c).tobytes(), prv)
            chunk(recv_c)[:] = np.frombuffer(recv, dtype=buf.dtype)
        return buf


class RingAllgather(CollectiveOp):
    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.ALLGATHER

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        # Single tensor per response (allgather fusion not implemented).
        entry = entries[0]
        tensor = np.ascontiguousarray(entry.tensor)
        size, rank = self.topo.size, self.topo.rank
        if size == 1:
            entry.output = tensor.copy()
            return Status.OK()

        # Per-rank first-dim sizes negotiated by the controller.
        dim0s = response.tensor_sizes
        inner = tensor.shape[1:] if tensor.ndim else ()
        blocks: List[Optional[np.ndarray]] = [None] * size
        blocks[rank] = tensor

        # ring forwarding: at step s we send the block that originated at
        # (rank - s) and receive the one originated at (rank - s - 1)
        nxt, prv = (rank + 1) % size, (rank - 1) % size
        for s in range(size - 1):
            send_origin = (rank - s) % size
            recv_origin = (rank - s - 1) % size
            got = self.mesh.sendrecv(nxt, blocks[send_origin].tobytes(), prv)
            arr = np.frombuffer(got, dtype=tensor.dtype).reshape(
                (dim0s[recv_origin],) + inner)
            blocks[recv_origin] = arr

        entry.output = np.concatenate([blocks[i] for i in range(size)], axis=0) \
            if tensor.ndim else np.stack(blocks)
        return Status.OK()


class StarBroadcast(CollectiveOp):
    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.BROADCAST

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        entry = entries[0]
        root = entry.root_rank
        if self.topo.size == 1:
            entry.output = np.ascontiguousarray(entry.tensor)
            return Status.OK()
        if self.topo.rank == root:
            data = np.ascontiguousarray(entry.tensor)
            payload = data.tobytes()
            for peer in range(self.topo.size):
                if peer != root:
                    self.mesh.send(peer, payload)
            entry.output = data
        else:
            raw = self.mesh.recv(root)
            shape = np.asarray(entry.tensor).shape
            entry.output = np.frombuffer(
                raw, dtype=response.tensor_type.to_numpy()).reshape(shape).copy()
        return Status.OK()


class PairwiseAlltoall(CollectiveOp):
    def enabled(self, response, entries) -> bool:
        return response.response_type == ResponseType.ALLTOALL

    def execute(self, response: Response,
                entries: List[TensorTableEntry]) -> Status:
        entry = entries[0]
        tensor = np.ascontiguousarray(entry.tensor)
        size, rank = self.topo.size, self.topo.rank
        # Flattened N×N split matrix from the controller; row r = rank r's
        # send splits, so our recv split from rank r is matrix[r][rank].
        matrix = response.tensor_sizes
        send_splits = matrix[rank * size:(rank + 1) * size]
        recv_splits = [matrix[r * size + rank] for r in range(size)]
        entry.received_splits = recv_splits

        inner = tensor.shape[1:]
        send_bounds = np.cumsum([0] + list(send_splits))
        out_blocks: List[Optional[np.ndarray]] = [None] * size
        out_blocks[rank] = tensor[send_bounds[rank]:send_bounds[rank + 1]]

        for off in range(1, size):
            to = (rank + off) % size
            frm = (rank - off) % size
            payload = tensor[send_bounds[to]:send_bounds[to + 1]].tobytes()
            got = self.mesh.sendrecv(to, payload, frm)
            out_blocks[frm] = np.frombuffer(got, dtype=tensor.dtype).reshape(
                (recv_splits[frm],) + inner)

        entry.output = np.concatenate([out_blocks[i] for i in range(size)], axis=0)
        return Status.OK()


def zero_entry_for(response: Response, index: int, offset_elems: int,
                   num_elems: int) -> TensorTableEntry:
    """Zero-substitute a tensor a joined rank never submitted (reference
    ``tensor_queue.h:39-41`` builds zero tensors for joined ranks).

    When the response was negotiated on the XLA device plane, the zeros are
    a jax device array so the joined rank still takes the same (device)
    code path as its peers — a host-numpy substitute would silently flip
    this rank to the TCP backend while the others run the XLA collective."""
    dtype = response.tensor_type.to_numpy()
    from . import xla as xla_backend

    if response.devices == [xla_backend.XLA_DEVICE_ID]:
        if not xla_backend.context().ready:
            # Peers negotiated the device plane but this rank cannot join
            # it: a numpy substitute would silently flip this rank to the
            # TCP backend while the others dispatch the XLA collective — a
            # cross-rank deadlock.  Fail loudly instead; the peers' device
            # collective times out and errors, and elastic recovery (when
            # enabled) takes over.
            from ..common.exceptions import HorovodInternalError

            raise HorovodInternalError(
                "join zero-substitution: peers negotiated the XLA device "
                "plane but the local XlaContext is not ready")
        import jax.numpy as jnp

        zeros = jnp.zeros(num_elems, dtype=dtype)
    else:
        zeros = np.zeros(num_elems, dtype=dtype)
    return TensorTableEntry(
        tensor_name=response.tensor_names[index],
        tensor=zeros,
        callback=lambda status, entry: None,
        device=(xla_backend.XLA_DEVICE_ID
                if not isinstance(zeros, np.ndarray) else -1),
    )
