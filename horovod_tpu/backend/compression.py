"""Cast-on-the-wire gradient compression for the host-ring data plane.

The reference's core bandwidth lever is fp16 wire compression
(``horovod/common/ops/...`` compressors; PAPER.md): gradients cross the
wire at half width and widen only inside the reduction.  Here the work
buffer stays WIDE (f32/f64) end to end — ``_ring_exchange`` casts each
segment into a keyed staging arena at send time and restores/reduces in
wide precision on land — so compression composes with the zero-copy
segment pipeline instead of replacing it:

- send: ``compress`` casts the wide segment into a persistent narrow
  arena (one cast, no heap allocation in steady state) and the transport
  frames that view, stamping the wire dtype code into the frame header
  (``transport/tcp.py`` ``_WIRE_DTYPE_MASK``) so config/version skew
  between peers poisons the stream loudly.
- land: ``decompress_add`` folds the narrow segment into the wide chunk
  in one mixed-dtype ``np.add`` (numpy widens in-register — no
  temporary), or ``decompress_into`` restores allgather segments.
- agreement: after reduce-scatter each owner quantizes its own chunk
  through the wire dtype (``quantize_inplace``) before the allgather, so
  every rank ends bit-identical — the owner's extra wide precision must
  not survive on one rank only.

fp16 halves f32 bytes but saturates beyond ±65504 (casts to inf — numpy's
overflow handling also makes that cast pathologically slow); bf16 keeps
f32's range with ~8 mantissa bits and casts at memory bandwidth via
ml_dtypes.  ``HOROVOD_WIRE_COMPRESSION`` selects (all ranks must agree);
only f32/f64 payloads compress — other dtypes pass through raw.

Beyond the casts, three LOSSY byte codecs ride the same knob
(``HOROVOD_WIRE_COMPRESSION=int8|onebit|topk<K>``), the 1-bit-SGD /
error-feedback family (Seide et al. 2014; Karimireddy et al. 2019):

- **int8** — per-segment symmetric quantization: ``<f4 scale>`` prefix
  then one signed byte per element (``q = clip(round(x/scale), ±127)``,
  ``scale = max|x|/127``).  ~4× on f32.
- **onebit** — sign bits packed 8:1 plus per-segment positive/negative
  means: ``<f4 pos_mean><f4 neg_mean>`` then ``ceil(n/8)`` sign bytes.
  ~32× on f32.
- **topk<K>** — only the K% largest-magnitude elements travel, as
  ``<u4 index><work-dtype value>`` pairs (``k = max(1, n*K//100)`` per
  segment — deterministic, so both peers frame identically).

Lossy codecs are BYTE codecs, not casts: compressed segments have
codec-specific sizes (``wire_nbytes``), which every rank derives from
the shared segment bounds + knobs, so the transport's exact-size frame
contract holds even for the variable-length topk path.  Convergence
safety comes from per-tensor ERROR FEEDBACK (:class:`EfState`): the
residual ``x - decode(encode(x))`` of step *t* is added back into the
same segment before quantizing at step *t+1*, keyed by (tensor key,
compress sequence), reset on shape change and on re-init.

Costs are first-class observables: cast seconds accumulate in
``wire_compress_seconds_total`` and narrow payload bytes in the
``compressed_bytes`` wire stat (surfaced as
``wire_compressed_bytes_total``) — the "half the bytes" claim is
counter-asserted in tests, not wall-clock-argued.  Lossy codecs add
``wire_codec_bytes_total{codec=}`` (bytes produced per codec),
``wire_ef_residual_bytes`` (EF state held), and
``wire_ef_flush_seconds_total`` (the EF fold/carry cost).
"""

from __future__ import annotations

import re
import time
from typing import Optional

import numpy as np

from ..common import env as env_mod
from ..common.exceptions import HorovodInternalError
from ..core import metrics
from ..core.timeline import wire_stats
from ..transport.frame_bits import (_WIRE_DTYPE_BF16, _WIRE_DTYPE_FP16,
                                    _WIRE_DTYPE_INT8, _WIRE_DTYPE_ONEBIT,
                                    _WIRE_DTYPE_RAW, _WIRE_DTYPE_TOPK)

# Wire dtype codes carried in the frame header (3 bits; 0 = raw).
# Values live in transport/frame_bits.py (the HVD008-closed registry);
# these are the compression-plane aliases every caller imports.
WIRE_DTYPE_RAW = _WIRE_DTYPE_RAW
WIRE_DTYPE_FP16 = _WIRE_DTYPE_FP16
WIRE_DTYPE_BF16 = _WIRE_DTYPE_BF16
WIRE_DTYPE_INT8 = _WIRE_DTYPE_INT8
WIRE_DTYPE_ONEBIT = _WIRE_DTYPE_ONEBIT
WIRE_DTYPE_TOPK = _WIRE_DTYPE_TOPK

#: little-endian f4 — the scale/mean prefix dtype every peer agrees on
_F4 = np.dtype("<f4")

#: Work dtypes eligible for narrowing; everything else travels raw.
_COMPRESSIBLE = (np.dtype(np.float32), np.dtype(np.float64))


class WireCompressor:
    """One wire dtype's cast pair + the error-feedback hook."""

    #: knob value and frame-header code (subclasses set these)
    name: str = "none"
    code: int = WIRE_DTYPE_RAW
    #: byte codecs (int8/onebit/topk) set True: segments travel as
    #: codec-sized byte blobs, not element-for-element casts, and the
    #: ring takes the encode/decode + byte-forwarding path instead of
    #: compress/decompress + quantize_inplace.
    lossy: bool = False

    def __init__(self, wire_dtype: np.dtype):
        self.wire_dtype = np.dtype(wire_dtype)

    def wire_nbytes(self, n: int, dtype: np.dtype) -> int:
        """Compressed byte size of an ``n``-element segment of work dtype
        ``dtype`` — deterministic from (n, dtype, knobs) alone, so both
        endpoints of a link frame identically (the transport enforces
        exact frame sizes; this is the allgather-v style sizing the
        variable-length codecs need)."""
        return n * self.wire_dtype.itemsize

    @staticmethod
    def _account(t0: float, nbytes: int) -> None:
        if metrics.ENABLED:
            metrics.inc("wire_compress_seconds_total",
                        time.perf_counter() - t0)
        wire_stats.add("compressed_bytes", nbytes)

    def compress(self, src: np.ndarray, arena: np.ndarray) -> np.ndarray:
        """Cast the wide segment ``src`` into the persistent narrow
        ``arena`` and return the narrow view to frame.  ``errstate``
        silences fp16 overflow noise (saturation to inf is the documented
        fp16 contract; warnings per segment would swamp logs)."""
        t0 = time.perf_counter()
        dst = arena[:src.size]
        with np.errstate(over="ignore"):
            dst[:] = src
        self.residual(src, dst)
        self._account(t0, dst.nbytes)
        if metrics.ENABLED:
            metrics.inc("wire_codec_bytes_total", dst.nbytes,
                        codec=self.name)
        return dst

    def decompress_add(self, wire_seg: np.ndarray,
                       out_seg: np.ndarray) -> None:
        """``out_seg += wire_seg`` widening in-register: one mixed-dtype
        ``np.add`` straight into the wide chunk — no temporary, no heap
        copy (verified for f32/f64 × fp16/bf16)."""
        t0 = time.perf_counter()
        np.add(out_seg, wire_seg, out=out_seg)
        self._account(t0, wire_seg.nbytes)

    def decompress_into(self, wire_seg: np.ndarray,
                        out_seg: np.ndarray) -> None:
        """Restore a landed narrow segment into its wide destination (the
        allgather half: values are already fully reduced)."""
        t0 = time.perf_counter()
        out_seg[:] = wire_seg
        self._account(t0, wire_seg.nbytes)

    def quantize_inplace(self, chunk: np.ndarray,
                         arena: np.ndarray) -> None:
        """Round-trip ``chunk`` through the wire dtype in place (via the
        narrow ``arena``) — run by each reduce-scatter owner on its own
        chunk BEFORE the allgather, so the wide precision only the owner
        holds cannot make ranks bit-diverge.  Idempotent: narrow→wide→
        narrow is exact."""
        t0 = time.perf_counter()
        dst = arena[:chunk.size]
        with np.errstate(over="ignore"):
            dst[:] = chunk
        chunk[:] = dst
        if metrics.ENABLED:
            metrics.inc("wire_compress_seconds_total",
                        time.perf_counter() - t0)

    def residual(self, src: np.ndarray, compressed: np.ndarray) -> None:
        """Error-feedback hook: observe the quantization error of this
        segment (``src - widen(compressed)``) and carry it forward.  The
        cast-only compressors drop the error (no-op); an error-feedback
        subclass overrides this without touching the ring or transport."""


class Fp16Compressor(WireCompressor):
    name = "fp16"
    code = WIRE_DTYPE_FP16

    def __init__(self):
        super().__init__(np.dtype(np.float16))


class Bf16Compressor(WireCompressor):
    name = "bf16"
    code = WIRE_DTYPE_BF16

    def __init__(self):
        try:
            import ml_dtypes
        except ImportError:
            raise HorovodInternalError(
                "HOROVOD_WIRE_COMPRESSION=bf16 needs ml_dtypes (ships "
                "with jax); install it or use fp16/none") from None
        super().__init__(np.dtype(ml_dtypes.bfloat16))


class EfState:
    """Per-tensor error-feedback residual accumulators.

    The ring compresses a deterministic SEQUENCE of segments per
    allreduce (reduce-scatter steps × pipeline segments), and that
    sequence replays identically at the next iteration of the same fused
    tensor (same bounds, same knobs) — so a residual slot is keyed by
    (tensor key, position in the compress sequence).  ``begin`` rewinds
    the sequence counter at the top of each allreduce; ``take`` hands the
    slot's residual to the codec, creating (or resetting to) zeros when
    the slot is new or the segment's shape/dtype changed — a re-fused or
    re-sharded tensor must not absorb a stale residual.  State is owned
    by the collective op instance, so elastic re-initialization (a new
    op) drops every accumulator — recovery replay starts from the same
    zero state a fresh run does.
    """

    def __init__(self):
        self._slots: dict = {}
        self._key = None
        self._seq = 0
        self._nbytes = 0

    def begin(self, key) -> None:
        self._key = key
        self._seq = 0

    def take(self, n: int, dtype: np.dtype) -> np.ndarray:
        slot = (self._key, self._seq)
        self._seq += 1
        r = self._slots.get(slot)
        if r is None or r.size != n or r.dtype != dtype:
            if r is not None:
                self._nbytes -= r.nbytes
            r = np.zeros(n, dtype=dtype)
            self._slots[slot] = r
            self._nbytes += r.nbytes
            if metrics.ENABLED:
                metrics.set_gauge("wire_ef_residual_bytes", self._nbytes)
        return r


def ef_enabled() -> bool:
    """Error feedback on/off (HOROVOD_WIRE_EF, default on).  Off exists
    for the convergence control arm: without the accumulator the lossy
    codecs' bias goes uncorrected, which the np=2 convergence test
    asserts is detectably worse — the accumulator is load-bearing."""
    return env_mod.get_bool(env_mod.HOROVOD_WIRE_EF, True)


class LossyWireCompressor(WireCompressor):
    """Byte-codec base: encode/decode between wide segments and
    codec-framed byte blobs, with optional error feedback.

    Unlike the casts, decode∘encode is NOT provably idempotent (float
    scale round trips), so cross-rank bit-identity is the ring's job:
    the allgather owner encodes its reduced chunk ONCE, decodes its own
    bytes back, and every hop forwards the bytes verbatim
    (``cpu_ring._ring_allgather_bytes``) — all ranks decode identical
    bytes by construction.  Codec scratch lives in a small per-instance
    pool (persistent, grown on demand), not per-call allocations."""

    lossy = True

    def __init__(self):
        super().__init__(np.dtype(np.uint8))
        self._pool: dict = {}

    def _scratch(self, tag: str, n: int, dtype: np.dtype) -> np.ndarray:
        key = (tag, np.dtype(dtype))
        a = self._pool.get(key)
        if a is None or a.size < n:
            a = np.empty(max(n, 1), dtype)
            self._pool[key] = a
        return a[:n]

    # -- codec payload (subclasses implement) ---------------------------

    def _encode(self, src: np.ndarray, out: np.ndarray) -> None:
        raise NotImplementedError

    def _decode(self, wire: np.ndarray, out: np.ndarray) -> None:
        raise NotImplementedError

    # -- ring-facing API ------------------------------------------------

    def encode(self, src: np.ndarray, out: np.ndarray,
               ef: Optional[EfState] = None) -> None:
        """Quantize the wide segment ``src`` into the byte buffer ``out``
        (exactly ``wire_nbytes(src.size, src.dtype)`` bytes).  With
        ``ef``, the slot's carried residual is added back BEFORE
        quantizing and the new quantization error is stored after —
        ``src`` itself is never mutated."""
        t0 = time.perf_counter()
        if ef is not None:
            r = ef.take(src.size, src.dtype)
            adj = self._scratch("ef-adj", src.size, src.dtype)
            np.add(src, r, out=adj)
        else:
            adj = src
        self._encode(adj, out)
        if ef is not None:
            t1 = time.perf_counter()
            dec = self._scratch("ef-dec", src.size, src.dtype)
            self._decode(out, dec)
            np.subtract(adj, dec, out=r)
            if metrics.ENABLED:
                metrics.inc("wire_ef_flush_seconds_total",
                            time.perf_counter() - t1)
        self._account(t0, out.nbytes)
        if metrics.ENABLED:
            metrics.inc("wire_codec_bytes_total", out.nbytes,
                        codec=self.name)

    def decode_add(self, wire: np.ndarray, out_seg: np.ndarray) -> None:
        """``out_seg += decode(wire)`` — the reduce-scatter landing."""
        t0 = time.perf_counter()
        dec = self._scratch("dec", out_seg.size, out_seg.dtype)
        self._decode(wire, dec)
        np.add(out_seg, dec, out=out_seg)
        self._account(t0, wire.nbytes)

    def decode_into(self, wire: np.ndarray, out_seg: np.ndarray) -> None:
        """``out_seg[:] = decode(wire)`` — the allgather restore (and the
        owner's own decode of its encoded chunk)."""
        t0 = time.perf_counter()
        self._decode(wire, out_seg)
        self._account(t0, wire.nbytes)


class Int8Compressor(LossyWireCompressor):
    """Per-segment symmetric int8: ``<f4 scale>`` + one s8/element."""

    name = "int8"
    code = WIRE_DTYPE_INT8

    def wire_nbytes(self, n: int, dtype: np.dtype) -> int:
        return _F4.itemsize + n

    def _encode(self, src, out):
        n = src.size
        mag = self._scratch("mag", n, src.dtype)
        np.abs(src, out=mag)
        scale = np.float32(float(mag.max()) / 127.0) if n else np.float32(0)
        out[:4] = np.frombuffer(scale.astype(_F4).tobytes(), np.uint8)
        q = out[4:4 + n].view(np.int8)
        if scale:
            # Multiply by the reciprocal (multiply streams ~2x faster
            # than divide) and skip clipping: |x| <= max means
            # |x/scale| <= 127 by construction, and rint cannot push a
            # value past it.  rint(x * (1/scale)) rounds one ulp
            # differently from rint(x / scale) for a handful of inputs —
            # irrelevant, both are valid quantizations and every rank
            # decodes the same bytes.
            np.multiply(src, src.dtype.type(1.0 / np.float64(scale)),
                        out=mag)
            np.rint(mag, out=mag)
            np.clip(mag, -127, 127, out=mag)  # inf/nan inputs only
            q[:] = mag  # integral-valued floats: cast is exact
        else:
            q[:] = 0

    def _decode(self, wire, out):
        n = out.size
        scale = np.frombuffer(wire[:4].tobytes(), _F4)[0]
        q = wire[4:4 + n].view(np.int8)
        np.multiply(q, out.dtype.type(scale), out=out)


class OneBitCompressor(LossyWireCompressor):
    """Sign bits packed 8:1 + per-segment positive/negative means:
    ``<f4 pos_mean><f4 neg_mean>`` then ``ceil(n/8)`` sign bytes (bit 1 =
    non-negative → pos_mean, bit 0 → neg_mean)."""

    name = "onebit"
    code = WIRE_DTYPE_ONEBIT

    def wire_nbytes(self, n: int, dtype: np.dtype) -> int:
        return 2 * _F4.itemsize + (n + 7) // 8

    def _encode(self, src, out):
        n = src.size
        pos = np.greater_equal(src, 0)
        npos = int(pos.sum())
        total = float(src.sum(dtype=np.float64))
        pos_sum = float(src[pos].sum(dtype=np.float64)) if npos else 0.0
        pos_mean = pos_sum / npos if npos else 0.0
        neg_mean = (total - pos_sum) / (n - npos) if n - npos else 0.0
        hdr = np.array([pos_mean, neg_mean], _F4)
        out[:8] = hdr.view(np.uint8)
        out[8:8 + (n + 7) // 8] = np.packbits(pos)

    def _decode(self, wire, out):
        n = out.size
        means = np.frombuffer(wire[:8].tobytes(), _F4)
        bits = np.unpackbits(wire[8:8 + (n + 7) // 8], count=n)
        out[:] = out.dtype.type(means[1])
        out[bits.astype(bool)] = out.dtype.type(means[0])


class TopKCompressor(LossyWireCompressor):
    """Magnitude top-k sparsification: only ``k = max(1, n*K//100)``
    elements per segment travel, as packed ``<u4 index><work-dtype
    value>`` records; everything else decodes to zero (its mass rides
    the EF accumulator into later steps)."""

    code = WIRE_DTYPE_TOPK

    def __init__(self, density_pct: int):
        super().__init__()
        self.density_pct = int(density_pct)
        self.name = f"topk{self.density_pct}"

    def _k(self, n: int) -> int:
        return max(1, n * self.density_pct // 100) if n else 0

    def _pair(self, dtype: np.dtype) -> np.dtype:
        return np.dtype([("i", "<u4"), ("v", np.dtype(dtype))])

    def wire_nbytes(self, n: int, dtype: np.dtype) -> int:
        return self._k(n) * self._pair(dtype).itemsize

    def _encode(self, src, out):
        n, k = src.size, self._k(src.size)
        mag = self._scratch("mag", n, src.dtype)
        np.abs(src, out=mag)
        if k < n:
            idx = np.sort(np.argpartition(mag, n - k)[n - k:])
        else:
            idx = np.arange(n)
        rec = out[:k * self._pair(src.dtype).itemsize] \
            .view(self._pair(src.dtype))
        rec["i"] = idx
        rec["v"] = src[idx]

    def _decode(self, wire, out):
        k = self._k(out.size)
        rec = wire[:k * self._pair(out.dtype).itemsize] \
            .view(self._pair(out.dtype))
        out[:] = 0
        out[rec["i"].astype(np.intp)] = rec["v"]


_COMPRESSORS = {"fp16": Fp16Compressor, "bf16": Bf16Compressor,
                "int8": Int8Compressor, "onebit": OneBitCompressor}
_TOPK_RE = re.compile(r"^topk(\d+)$")
_cache: dict = {}


def wire_compressor_for(dtype: np.dtype) -> Optional[WireCompressor]:
    """The configured compressor for a work dtype, or None when the
    payload should travel raw (knob off, or dtype not f32/f64 — already
    narrow or not a float, where casting would corrupt)."""
    name = env_mod.get_str(env_mod.HOROVOD_WIRE_COMPRESSION, "none") \
        or "none"
    if name == "none":
        return None
    topk = _TOPK_RE.match(name)
    if topk is not None:
        density = int(topk.group(1))
        if not 1 <= density <= 100:
            raise HorovodInternalError(
                f"HOROVOD_WIRE_COMPRESSION {name!r}: topk density must "
                "be an integer percentage in [1, 100] (e.g. topk10)")
    elif name not in _COMPRESSORS:
        raise HorovodInternalError(
            f"unknown HOROVOD_WIRE_COMPRESSION {name!r} "
            f"(expected none|{'|'.join(sorted(_COMPRESSORS))}|topk<K>)")
    if np.dtype(dtype) not in _COMPRESSIBLE:
        return None
    if name not in _cache:
        _cache[name] = TopKCompressor(int(topk.group(1))) \
            if topk is not None else _COMPRESSORS[name]()
    return _cache[name]
