"""Cast-on-the-wire gradient compression for the host-ring data plane.

The reference's core bandwidth lever is fp16 wire compression
(``horovod/common/ops/...`` compressors; PAPER.md): gradients cross the
wire at half width and widen only inside the reduction.  Here the work
buffer stays WIDE (f32/f64) end to end — ``_ring_exchange`` casts each
segment into a keyed staging arena at send time and restores/reduces in
wide precision on land — so compression composes with the zero-copy
segment pipeline instead of replacing it:

- send: ``compress`` casts the wide segment into a persistent narrow
  arena (one cast, no heap allocation in steady state) and the transport
  frames that view, stamping the wire dtype code into the frame header
  (``transport/tcp.py`` ``_WIRE_DTYPE_MASK``) so config/version skew
  between peers poisons the stream loudly.
- land: ``decompress_add`` folds the narrow segment into the wide chunk
  in one mixed-dtype ``np.add`` (numpy widens in-register — no
  temporary), or ``decompress_into`` restores allgather segments.
- agreement: after reduce-scatter each owner quantizes its own chunk
  through the wire dtype (``quantize_inplace``) before the allgather, so
  every rank ends bit-identical — the owner's extra wide precision must
  not survive on one rank only.

fp16 halves f32 bytes but saturates beyond ±65504 (casts to inf — numpy's
overflow handling also makes that cast pathologically slow); bf16 keeps
f32's range with ~8 mantissa bits and casts at memory bandwidth via
ml_dtypes.  ``HOROVOD_WIRE_COMPRESSION`` selects (all ranks must agree);
only f32/f64 payloads compress — other dtypes pass through raw.

``residual`` is the error-feedback hook: called with the wide segment and
its just-compressed narrow image, it may carry quantization error into
the next step.  The base implementation is a no-op — the hook exists so
an error-feedback compressor is a subclass, not a transport change.

Costs are first-class observables: cast seconds accumulate in
``wire_compress_seconds_total`` and narrow payload bytes in the
``compressed_bytes`` wire stat (surfaced as
``wire_compressed_bytes_total``) — the "half the bytes" claim is
counter-asserted in tests, not wall-clock-argued.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..common import env as env_mod
from ..common.exceptions import HorovodInternalError
from ..core import metrics
from ..core.timeline import wire_stats

# Wire dtype codes carried in the frame header (3 bits; 0 = raw).
WIRE_DTYPE_RAW = 0
WIRE_DTYPE_FP16 = 1
WIRE_DTYPE_BF16 = 2

#: Work dtypes eligible for narrowing; everything else travels raw.
_COMPRESSIBLE = (np.dtype(np.float32), np.dtype(np.float64))


class WireCompressor:
    """One wire dtype's cast pair + the error-feedback hook."""

    #: knob value and frame-header code (subclasses set these)
    name: str = "none"
    code: int = WIRE_DTYPE_RAW

    def __init__(self, wire_dtype: np.dtype):
        self.wire_dtype = np.dtype(wire_dtype)

    @staticmethod
    def _account(t0: float, nbytes: int) -> None:
        if metrics.ENABLED:
            metrics.inc("wire_compress_seconds_total",
                        time.perf_counter() - t0)
        wire_stats.add("compressed_bytes", nbytes)

    def compress(self, src: np.ndarray, arena: np.ndarray) -> np.ndarray:
        """Cast the wide segment ``src`` into the persistent narrow
        ``arena`` and return the narrow view to frame.  ``errstate``
        silences fp16 overflow noise (saturation to inf is the documented
        fp16 contract; warnings per segment would swamp logs)."""
        t0 = time.perf_counter()
        dst = arena[:src.size]
        with np.errstate(over="ignore"):
            dst[:] = src
        self.residual(src, dst)
        self._account(t0, dst.nbytes)
        return dst

    def decompress_add(self, wire_seg: np.ndarray,
                       out_seg: np.ndarray) -> None:
        """``out_seg += wire_seg`` widening in-register: one mixed-dtype
        ``np.add`` straight into the wide chunk — no temporary, no heap
        copy (verified for f32/f64 × fp16/bf16)."""
        t0 = time.perf_counter()
        np.add(out_seg, wire_seg, out=out_seg)
        self._account(t0, wire_seg.nbytes)

    def decompress_into(self, wire_seg: np.ndarray,
                        out_seg: np.ndarray) -> None:
        """Restore a landed narrow segment into its wide destination (the
        allgather half: values are already fully reduced)."""
        t0 = time.perf_counter()
        out_seg[:] = wire_seg
        self._account(t0, wire_seg.nbytes)

    def quantize_inplace(self, chunk: np.ndarray,
                         arena: np.ndarray) -> None:
        """Round-trip ``chunk`` through the wire dtype in place (via the
        narrow ``arena``) — run by each reduce-scatter owner on its own
        chunk BEFORE the allgather, so the wide precision only the owner
        holds cannot make ranks bit-diverge.  Idempotent: narrow→wide→
        narrow is exact."""
        t0 = time.perf_counter()
        dst = arena[:chunk.size]
        with np.errstate(over="ignore"):
            dst[:] = chunk
        chunk[:] = dst
        if metrics.ENABLED:
            metrics.inc("wire_compress_seconds_total",
                        time.perf_counter() - t0)

    def residual(self, src: np.ndarray, compressed: np.ndarray) -> None:
        """Error-feedback hook: observe the quantization error of this
        segment (``src - widen(compressed)``) and carry it forward.  The
        cast-only compressors drop the error (no-op); an error-feedback
        subclass overrides this without touching the ring or transport."""


class Fp16Compressor(WireCompressor):
    name = "fp16"
    code = WIRE_DTYPE_FP16

    def __init__(self):
        super().__init__(np.dtype(np.float16))


class Bf16Compressor(WireCompressor):
    name = "bf16"
    code = WIRE_DTYPE_BF16

    def __init__(self):
        try:
            import ml_dtypes
        except ImportError:
            raise HorovodInternalError(
                "HOROVOD_WIRE_COMPRESSION=bf16 needs ml_dtypes (ships "
                "with jax); install it or use fp16/none") from None
        super().__init__(np.dtype(ml_dtypes.bfloat16))


_COMPRESSORS = {"fp16": Fp16Compressor, "bf16": Bf16Compressor}
_cache: dict = {}


def wire_compressor_for(dtype: np.dtype) -> Optional[WireCompressor]:
    """The configured compressor for a work dtype, or None when the
    payload should travel raw (knob off, or dtype not f32/f64 — already
    narrow or not a float, where casting would corrupt)."""
    name = env_mod.get_str(env_mod.HOROVOD_WIRE_COMPRESSION, "none") \
        or "none"
    if name == "none":
        return None
    if name not in _COMPRESSORS:
        raise HorovodInternalError(
            f"unknown HOROVOD_WIRE_COMPRESSION {name!r} "
            f"(expected none|{'|'.join(sorted(_COMPRESSORS))})")
    if np.dtype(dtype) not in _COMPRESSIBLE:
        return None
    if name not in _cache:
        _cache[name] = _COMPRESSORS[name]()
    return _cache[name]
